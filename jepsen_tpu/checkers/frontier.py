"""Sparse batched-frontier linearizability engine — the device search for
high-concurrency histories.

Upstream analogue: ``knossos/src/knossos/linear.clj`` / ``wgl.clj``'s
explicit configuration sets (SURVEY.md §2.2) and SURVEY.md §7 phase 4's
original "batched frontier" design. The dense engine (:mod:`.reach`)
represents the reachable config set as a boolean tensor over
``states × 2**W`` and therefore dies (``DenseOverflow`` /
``ConcurrencyOverflow``) when ``W`` — the maximum number of concurrently
pending ops, which grows with every crashed ``info`` op a nemesis leaves
behind — exceeds ~20. This engine keeps the *sparse* set of reachable
configurations ⟨model-state, linearized-pending bitset⟩ as packed uint32
rows and advances all of them per history event with vectorized device
ops, so ``W`` may reach ``MAX_SLOTS`` (128) while memory scales with the
number of *reachable* configs, not ``2**W``:

- a config is one row of a ``uint32[F, K+1]`` array: ``K = ceil(W/32)``
  bitset words plus the model-state id (the row IS its dedup key);
- **fire** (linearize one more pending op) expands every config by every
  pending slot at once — a single gather through the flattened transition
  table — and the union is deduplicated by a lexicographic
  ``lax.sort`` over the row words followed by an adjacent-unique compact;
  passes repeat to a fixpoint (monotone, detected by the unique count);
- **return** keeps configs whose bitset linearized the returning op and
  clears that slot bit — an order-preserving filter (clearing one fixed
  bit in every surviving row preserves lexicographic order), so no
  re-sort is needed;
- an empty frontier at a return is a linearizability violation at exactly
  that event, the same minimal evidence knossos reports.

**Crashed-op quotient.** Knossos explores crashed (``info``) ops exactly:
each one holds a bitset slot forever, so ``k`` crashes contribute ``2**k``
linearized-subset combinations — the classic "info ops are expensive"
blowup. This engine canonicalizes them away: two *pending crashed* ops
with the same op id are interchangeable (neither ever returns, and firing
either produces the same successor state — live ops are never grouped,
since a live op's own return requires *its* bit), so a config only needs
the *count* of fired ops per ⟨crashed, op-id⟩ group. Canonical form packs
each group's fired bits into its lowest-ranked slots — computed on device
from the per-return pending map — collapsing ``2**k`` to
``∏ (group_size+1)`` while remaining exact.

The frontier capacity ``F`` is a static shape: the walk runs at a small
``F`` first and the host retries at 4× on overflow (knossos.linear
instead *dies* on config-set explosion; here only :class:`FrontierOverflow`
past ``max_frontier`` gives up, and the facade falls back to the CPU
searches). Exact, not probabilistic: rows are compared in full — no
fingerprint hashing — so verdicts cannot be corrupted by collisions.

The default ``max_frontier`` (131072 rows) admits dedup sorts of
~1.2M rows. (Round 1 capped it at 16384 to dodge a dev-tunnel bug —
~590k-row ``lax.sort`` calls crashed the TPU worker; re-verified
2026-07-30 that both bare sorts at 1M+ rows and full F=65536 frontier
walks now run clean on device, so the cap once again reflects memory
budget, not a workaround.)
"""
from __future__ import annotations

import functools
import time as _time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from jepsen_tpu import history as h
from jepsen_tpu import obs
from jepsen_tpu.checkers import events as ev
from jepsen_tpu.checkers import reach
from jepsen_tpu.models import Model
from jepsen_tpu.models.memo import Memo
from jepsen_tpu.op import Op

MAX_SLOTS = 128                 # bitset capped at 4 uint32 words

_STATUS_RUNNING = 0
_STATUS_DEAD = 1
_STATUS_OVERFLOW = 2
_STATUS_ABORT = 3              # host-side only (deadline / search control)


class FrontierOverflow(RuntimeError):
    """The reachable config set exceeds ``max_frontier`` rows; callers
    should fall back to another engine (upstream behaviour:
    knossos.linear dies on config-set explosion)."""


def _use_quotient() -> bool:
    """The dense product-space fast path (:mod:`.reach_q`) is on by
    default; ``JEPSEN_TPU_NO_QUOTIENT=1`` forces the sparse rows (used
    by tests that target the sparse walk itself)."""
    import os
    return not os.environ.get("JEPSEN_TPU_NO_QUOTIENT")


# -- device program ----------------------------------------------------------

def _sort_unique_compact(U, F, pack_bits: int = 0):
    """Dedup candidate rows ``U: u32[N, K+1]`` (invalid rows are all-ones):
    sort, adjacent-unique, compact the first ``F`` unique rows to the
    front. Returns ``(C: u32[F, K+1], count)`` where ``count`` may exceed
    ``F`` (overflow — compaction drops the excess, caller must re-run at
    a larger ``F``).

    With ``pack_bits = W > 0`` (feasible when ``K == 1`` and the state id
    fits ``32 - W`` bits — the common case), each row packs into ONE u32
    key ``(state << W) | word`` and the lexicographic multi-key sort
    becomes a single-key sort (~2× cheaper; the all-ones sentinel wraps
    to the all-ones key, so it still sorts last, and clearing a fixed
    bit in every survivor subtracts the same constant from every key, so
    :func:`_project`'s no-re-sort invariant is preserved)."""
    import jax.numpy as jnp
    from jax import lax

    N, K1 = U.shape
    if pack_bits and K1 == 2:
        key = (U[:, 1] << jnp.uint32(pack_bits)) | U[:, 0]
        ks = lax.sort(key)
        valid = ks != jnp.uint32(0xFFFFFFFF)
        differs = ks != jnp.roll(ks, 1)
        differs = differs.at[0].set(True)
        unique = valid & differs
        word = ks & jnp.uint32((1 << pack_bits) - 1)
        state = ks >> jnp.uint32(pack_bits)
        Us = jnp.where(valid[:, None], jnp.stack([word, state], axis=1),
                       jnp.uint32(0xFFFFFFFF))
    else:
        cols = lax.sort(tuple(U[:, i] for i in range(K1)), num_keys=K1)
        Us = jnp.stack(cols, axis=1)                   # u32[N, K+1] sorted
        valid = Us[:, K1 - 1] != jnp.uint32(0xFFFFFFFF)
        differs = jnp.any(Us != jnp.roll(Us, 1, axis=0), axis=1)
        differs = differs.at[0].set(True)
        unique = valid & differs
    count = jnp.sum(unique.astype(jnp.int32))
    pos = jnp.cumsum(unique.astype(jnp.int32)) - 1
    pos = jnp.where(unique & (pos < F), pos, F)        # F = drop row
    C = jnp.full((F, K1), jnp.uint32(0xFFFFFFFF))
    C = C.at[pos].set(Us, mode="drop")
    return C, count


def _extract_bits(U, word_idx, shift):
    """Per-slot fired bits of each row: ``bool[N, W]``."""
    import jax.numpy as jnp

    sel = U[:, word_idx]                               # u32[N, W]
    return ((sel >> shift.astype(jnp.uint32)) & jnp.uint32(1)) > 0


def _pack_bits(bits, bitmat):
    """Inverse of :func:`_extract_bits`: ``u32[N, K]`` mask words."""
    import jax.numpy as jnp

    W, K = bitmat.shape
    words = []
    for k in range(K):
        lo, hi = k * 32, min((k + 1) * 32, W)
        words.append(jnp.sum(bits[:, lo:hi].astype(jnp.uint32)
                             * bitmat[lo:hi, k][None, :], axis=1))
    return jnp.stack(words, axis=1)


def _slot_groups(ops_row, crashed_row):
    """Interchangeability structure at one return, from the pending map:
    ``grouped[w]`` (crashed slots participate), ``same[w, w']`` (same
    group: both crashed, same op id), ``rank[w]`` (w's index within its
    group, by slot order)."""
    import jax.numpy as jnp

    W = ops_row.shape[0]
    grouped = crashed_row & (ops_row >= 0)
    same = (grouped[:, None] & grouped[None, :]
            & (ops_row[:, None] == ops_row[None, :]))  # bool[W, W]
    rank = jnp.sum(same & (jnp.arange(W)[None, :] < jnp.arange(W)[:, None]),
                   axis=1)
    return grouped, same, rank


def _canonicalize(U, grouped, same, rank, word_idx, shift, bitmat):
    """Quotient rows by crashed-op interchangeability: within each group,
    repack the fired bits into the group's lowest-ranked slots (fired
    counts are all that matter — see module docstring). Live slots are
    untouched. Applied once per return: within a return the group
    structure is fixed and expansion preserves canonical form, but a slot
    freed by a live return may later host a *lower-numbered* member of an
    existing crashed group, shifting ranks."""
    import jax.numpy as jnp

    K1 = U.shape[1]
    K = K1 - 1
    valid = U[:, K] != jnp.uint32(0xFFFFFFFF)
    bits = _extract_bits(U, word_idx, shift)
    # counts[n, w] = fired bits in w's group (exact in f32: counts ≤ W)
    counts = jnp.dot(bits.astype(jnp.float32), same.astype(jnp.float32))
    canon = jnp.where(grouped[None, :],
                      rank[None, :].astype(jnp.float32) < counts, bits)
    out = jnp.concatenate([_pack_bits(canon, bitmat), U[:, K:]], axis=1)
    return jnp.where(valid[:, None], out, jnp.uint32(0xFFFFFFFF))


_BLOCK = 8                     # pending slots expanded per dedup round
                               # (sharded path; the single-device walk
                               # sizes rounds adaptively, see _round_blk)

# candidate-row budget for one expand round: at small F the whole slot
# axis fits one round — ONE dedup sort per closure pass instead of
# ceil(W/8) — while large F keeps rounds bounded (memory ~ budget·K1·4B)
_CAND_BUDGET = 1 << 21


def _round_blk(F: int, W: int) -> int:
    return max(_BLOCK, min(W, _CAND_BUDGET // max(F, 1)))


def _expand_block(C, pending, grouped, same, rank, T_flat, bitmat,
                  word_idx, shift, n_cols, lo, canon: bool,
                  blk_size: int = _BLOCK):
    """Canonical single-fire successors of every config through pending
    slots ``[lo, lo+blk_size)``: ``u32[F*blk_size, K+1]`` (illegal ones
    all-ones). Live pending slots fire when their bit is clear; grouped
    (crashed) slots fire only through the group's next canonical member
    (``rank == fired-count``, computed over the FULL slot axis — groups
    span blocks), so every successor of a canonical row is canonical and
    redundant interchangeable fires are never materialized.
    ``T_flat: i32[S*n_cols]`` is the flattened transition table."""
    import jax.numpy as jnp

    F, K1 = C.shape
    K = K1 - 1
    blk = slice(lo, lo + blk_size)
    pend_b = pending[blk]
    state = C[:, K].astype(jnp.int32)                  # -1 when invalid
    cvalid = state >= 0
    op_ok = pend_b >= 0
    o = jnp.where(op_ok, pend_b, 0)
    flat = jnp.clip(state, 0)[:, None] * n_cols + o[None, :]
    tgt = jnp.take(T_flat, flat)                       # i32[F, b]
    bits = _extract_bits(C, word_idx, shift)           # bool[F, W] (full)
    fireable = ~bits[:, blk]                           # live: bit clear
    if canon:
        counts = jnp.dot(bits.astype(jnp.float32),
                         same.astype(jnp.float32))     # f32[F, W]
        next_member = counts[:, blk] == rank[blk][None, :].astype(
            jnp.float32)
        fireable = jnp.where(grouped[blk][None, :], next_member, fireable)
    legal = cvalid[:, None] & op_ok[None, :] & fireable & (tgt >= 0)
    words = C[:, None, :K] | bitmat[None, blk, :]      # u32[F, b, K]
    cand = jnp.concatenate(
        [words, tgt[:, :, None].astype(jnp.uint32)], axis=2)
    cand = jnp.where(legal[:, :, None], cand, jnp.uint32(0xFFFFFFFF))
    return cand.reshape(F * pend_b.shape[0], K1)


def _closure(C, pending, grouped, same, rank, T_flat, bitmat,
             word_idx, shift, n_cols, canon: bool,
             blk_size: int = _BLOCK, pack_bits: int = 0):
    """Fixpoint of fire-expansion ∪ dedup — covers every linearization
    order of any subset of pending ops (the union is monotone, so the
    unique count is stationary exactly at the fixpoint). Each pass
    expands the slot axis in ``blk_size``-sized rounds (adaptively the
    WHOLE axis when ``F·W`` fits the candidate budget — the dedup sort
    is the dominant cost, and one sort of ``F·(W+1)`` rows beats
    ``ceil(W/8)`` sorts of ``F·9``), folding every round into the
    running set with a sort — bounded buffers with TRUE capacity
    semantics: overflow is flagged only when the deduplicated config
    count itself exceeds ``F`` (a candidate buffer can never, since a
    round emits at most ``F·blk_size`` rows). Chained fires missed
    inside a pass are caught by the outer fixpoint. Termination
    compares only DEDUPLICATED pass counts with each other — the
    entering set's count may be stale (canonicalization can merge rows
    without re-deduplicating), so it must not seed the comparison."""
    import jax.numpy as jnp
    from jax import lax

    F = C.shape[0]
    W = pending.shape[0]

    def cond(c):
        _, count, prev, overflow = c
        return (count != prev) & ~overflow

    def body(c):
        C, count, _, _ = c
        C2, count2, overflow = C, count, False
        for lo in range(0, W, blk_size):
            cand = _expand_block(C, pending, grouped, same, rank, T_flat,
                                 bitmat, word_idx, shift, n_cols, lo,
                                 canon, blk_size)
            U = jnp.concatenate([C2, cand], axis=0)
            C2, count2 = _sort_unique_compact(U, F, pack_bits)
            overflow = overflow | (count2 > F)
        return C2, count2, count, overflow

    C, count, _, overflow = lax.while_loop(
        cond, body, (C, jnp.int32(-1), jnp.int32(-2), False))
    return C, count, overflow


def _project(C, count, j):
    """Return of the op in (dynamic) slot ``j``: keep configs that
    linearized it, clearing its bit so the slot can be reused. Clearing
    one fixed bit in every surviving row preserves the sorted-unique
    order, so compaction needs no re-sort."""
    import jax.numpy as jnp

    F, K1 = C.shape
    K = K1 - 1
    wi = j >> 5
    bit = jnp.uint32(1) << (j & 31).astype(jnp.uint32)
    valid = C[:, K] != jnp.uint32(0xFFFFFFFF)
    sel = C[:, wi]
    keep = valid & ((sel & bit) != 0)
    C = C.at[:, wi].set(sel & ~bit)
    C = jnp.where(keep[:, None], C, jnp.uint32(0xFFFFFFFF))
    pos = jnp.cumsum(keep.astype(jnp.int32)) - 1
    pos = jnp.where(keep, pos, F)
    out = jnp.full((F, K1), jnp.uint32(0xFFFFFFFF))
    out = out.at[pos].set(C, mode="drop")
    return out, jnp.sum(keep.astype(jnp.int32))


def _walk(T_flat, n_cols, canon, blk_size, pack_bits,
          ret_slot, slot_ops,
          crashed_slot, bitmat, word_idx, shift, C0, count0):
    """Drive one segment of return events over the sparse frontier
    (callers slice the stream into fixed-size segments — bounded device
    programs keep compilations shape-stable and give the host abort/retry
    points between calls). Returns ``(r, C, count, status)``: status 1 =
    the frontier emptied at segment-local return ``r`` (violation
    witness), 2 = capacity overflow at return ``r``. On a non-running
    exit ``C``/``count`` are the frontier AT ENTRY of return ``r`` (one
    [F, K+1] select per return keeps them), so an overflow resumes
    EXACTLY at the failing return in a 4× buffer — no segment replay."""
    import jax.numpy as jnp
    from jax import lax

    Rn = ret_slot.shape[0]

    def cond(c):
        r, _, _, status = c
        return (r < Rn) & (status == _STATUS_RUNNING)

    def body(c):
        r, C, count, _ = c
        j = ret_slot[r]

        def do(C, count):
            ops_row = slot_ops[r]
            if canon:
                grouped, same, rank = _slot_groups(ops_row, crashed_slot[r])
                Cc = _canonicalize(C, grouped, same, rank, word_idx, shift,
                                   bitmat)
            else:
                grouped = same = rank = None
                Cc = C
            C1, count1, overflow = _closure(
                Cc, ops_row, grouped, same, rank, T_flat, bitmat,
                word_idx, shift, n_cols, canon, blk_size, pack_bits)
            C2, count2 = _project(C1, count1, j)
            status = jnp.where(
                overflow, _STATUS_OVERFLOW,
                jnp.where(count2 == 0, _STATUS_DEAD, _STATUS_RUNNING))
            return C2, count2, status

        def pad(C, count):
            return C, count, jnp.int32(_STATUS_RUNNING)

        C2, count2, status = lax.cond(j >= 0, do, pad, C, count)
        keep = status == _STATUS_RUNNING
        C = jnp.where(keep, C2, C)
        count = jnp.where(keep, count2, count)
        r = jnp.where(keep, r + 1, r)
        return r, C, count, status

    return lax.while_loop(
        cond, body, (jnp.int32(0), C0, count0,
                     jnp.int32(_STATUS_RUNNING)))


@functools.lru_cache(maxsize=None)
def _jitted_walk():
    import jax
    return jax.jit(_walk, static_argnums=(1, 2, 3, 4))


# -- host driver -------------------------------------------------------------

def _slot_geometry(W: int):
    K = (W + 31) // 32
    w = np.arange(W, dtype=np.int32)
    word_idx = w >> 5
    shift = w & 31
    bitmat = np.zeros((W, K), np.uint32)
    bitmat[w, word_idx] = np.uint32(1) << shift
    return K, word_idx, shift, bitmat


def _initial_frontier(F: int, K: int, initial_state: int) -> np.ndarray:
    C0 = np.full((F, K + 1), 0xFFFFFFFF, np.uint32)
    C0[0, :K] = 0
    C0[0, K] = initial_state
    return C0


def _crashed_slots_ref(stream: ev.EventStream, packed: h.PackedHistory,
                       W: int) -> np.ndarray:
    """Readable per-event scan reference for :func:`_crashed_slots`
    (kept as the test oracle)."""
    crashed = np.asarray(packed.crashed, bool)
    n_ret = int(np.sum(stream.kind[:stream.n_events] == ev.KIND_RETURN))
    out = np.zeros((n_ret, W), bool)
    cur = np.full(W, -1, np.int64)
    r = 0
    for e in range(stream.n_events):
        k = stream.kind[e]
        if k == ev.KIND_INVOKE:
            cur[stream.slot[e]] = stream.entry[e]
        elif k == ev.KIND_RETURN:
            active = cur >= 0
            out[r, active] = crashed[cur[active]]
            cur[stream.slot[e]] = -1
            r += 1
    return out


def _crashed_slots(stream: ev.EventStream, packed: h.PackedHistory,
                   W: int) -> np.ndarray:
    """``bool[R, W]`` aligned with :func:`events.returns_view`: whether the
    op pending in slot ``w`` at return ``r`` crashed. Feeds the device-side
    interchangeability grouping (crashed slots sharing an op id).

    Vectorized (O(W·R) numpy, no per-event Python loop): for each slot,
    the occupying entry at a return position is found by a searchsorted
    over that slot's own event positions; the slot is occupied when its
    last event at or before the return is an invoke — or is that very
    return (the returning op is still pending in its snapshot, matching
    ``returns_view``)."""
    crashed = np.asarray(packed.crashed, bool)
    E = stream.n_events
    kind = stream.kind[:E]
    slot = stream.slot[:E]
    entry = stream.entry[:E]
    ret_pos = np.nonzero(kind == ev.KIND_RETURN)[0]
    out = np.zeros((len(ret_pos), W), bool)
    for w in range(W):
        pos_w = np.nonzero(slot == w)[0]
        if len(pos_w) == 0:
            continue
        j = np.searchsorted(pos_w, ret_pos, side="right") - 1
        valid = j >= 0
        jc = np.clip(j, 0, None)
        last = pos_w[jc]
        occupied = valid & ((kind[last] == ev.KIND_INVOKE)
                            | (last == ret_pos))
        out[:, w] = occupied & crashed[entry[last]]
    return out


_SEG = 2048                    # returns per device call: bounded kernels,
                               # one compilation per (W, F), host abort
                               # points. Big segments matter on the dev
                               # tunnel (each host sync is a ~0.13 s
                               # round trip); exact-resume escalation
                               # means a large segment costs nothing
                               # extra on overflow.


def _seg_arrays(rs: ev.ReturnStream, crashed_slot: np.ndarray,
                base: int):
    """Static-shape [_SEG] segment slices starting at return ``base``
    (identity-padded past the end) — resume points land on arbitrary
    return indices, so slices are rebuilt host-side per dispatch."""
    W = rs.slot_ops.shape[1]
    ret_slot = np.full(_SEG, -1, np.int32)
    slot_ops = np.full((_SEG, W), -1, np.int32)
    crashed = np.zeros((_SEG, W), bool)
    n = min(_SEG, rs.R - base)
    ret_slot[:n] = rs.ret_slot[base:base + n]
    slot_ops[:n] = rs.slot_ops[base:base + n]
    crashed[:n] = crashed_slot[base:base + n]
    return ret_slot, slot_ops, crashed, n


def _run_walk(memo: Memo, rs: ev.ReturnStream, crashed_slot: np.ndarray,
              F: int, max_frontier: int, should_abort=None):
    """Drive the whole (padded) return stream in ``_SEG``-sized device
    calls, carrying the frontier across segments. On capacity overflow
    the walk resumes EXACTLY at the failing return — the device carries
    the entry frontier of the current return, so the host re-embeds it
    into a 4× buffer and dispatches from that return (no replay).
    Returns ``(dead_ret, status, C, count, F)``; raises
    :class:`FrontierOverflow` past ``max_frontier``."""
    import jax.numpy as jnp

    W = rs.W
    K, word_idx, shift, bitmat = _slot_geometry(W)
    S, O = memo.table.shape
    T_flat = jnp.asarray(memo.table.reshape(-1))
    bitmat_d = jnp.asarray(bitmat)
    word_idx_d = jnp.asarray(word_idx)
    shift_d = jnp.asarray(shift)
    canon = bool(crashed_slot.any())
    # single-key packed dedup when a whole row fits one u32
    pack_bits = W if (K == 1 and S <= (1 << (32 - W)) - 1) else 0
    C = jnp.asarray(_initial_frontier(F, K, memo.initial))
    count = jnp.int32(1)
    walk = _jitted_walk()
    base = 0
    while base < rs.R:
        if should_abort is not None and should_abort():
            return -1, _STATUS_ABORT, C, count, F
        ret_slot, slot_ops, crashed, n = _seg_arrays(rs, crashed_slot,
                                                     base)
        r, C2, count2, status = walk(
            T_flat, O, canon, _round_blk(F, W), pack_bits,
            jnp.asarray(ret_slot), jnp.asarray(slot_ops),
            jnp.asarray(crashed),
            bitmat_d, word_idx_d, shift_d, C, count)
        status = int(status)
        if status == _STATUS_OVERFLOW:
            F *= 4
            if F > max_frontier:
                raise FrontierOverflow(
                    f"reachable config set exceeds {max_frontier} rows")
            # C2 is the frontier at entry of the failing return
            # (sorted-unique rows): sentinel-pad embeds it in the
            # larger buffer
            C = jnp.asarray(np.pad(
                np.asarray(C2), ((0, F - np.asarray(C2).shape[0]), (0, 0)),
                constant_values=np.uint32(0xFFFFFFFF)))
            count = count2
            base += int(r)              # resume at the failing return
            continue
        if status != _STATUS_RUNNING:
            return base + int(r), status, C2, count2, F
        C, count = C2, count2
        base += n
    return rs.R, _STATUS_RUNNING, C, count, F


def _pad_rows(a: np.ndarray, n: int) -> np.ndarray:
    return np.pad(a, ((0, n - len(a)), (0, 0)))


def _final_configs(memo: Memo, rs: ev.ReturnStream,
                   crashed_slot: np.ndarray, F: int, dead_ret: int,
                   limit: int = 16, should_abort=None
                   ) -> List[Dict[str, Any]]:
    """Decode the configurations alive just before the dead return — the
    knossos ``:final-paths`` analogue (same shape as
    :func:`jepsen_tpu.checkers.reach._final_configs`)."""
    prefix = ev.ReturnStream(
        ret_slot=rs.ret_slot[:dead_ret], slot_ops=rs.slot_ops[:dead_ret],
        ret_event=rs.ret_event[:dead_ret], ret_entry=rs.ret_entry[:dead_ret],
        W=rs.W, n_returns=dead_ret)
    R_pad = -(-max(dead_ret, 1) // _SEG) * _SEG
    prefix = ev.pad_returns(prefix, R_pad)
    _dr, status, C, count, _ = _run_walk(
        memo, prefix, _pad_rows(crashed_slot[:dead_ret], R_pad), F, F,
        should_abort=should_abort)
    if status != _STATUS_RUNNING:
        return []                  # aborted mid-evidence: skip the garnish
    C_np = np.asarray(C)
    pending = rs.slot_ops[dead_ret]
    K = (rs.W + 31) // 32
    out = []
    for row in C_np[:min(int(count), limit)]:
        s = int(np.int32(row[K]))
        if s < 0:
            break
        lin = [str(memo.distinct_ops[pending[w]])
               for w in range(rs.W)
               if (row[w >> 5] >> (w & 31)) & 1 and pending[w] >= 0]
        out.append({"model": str(memo.states[s]),
                    "linearized-pending": lin})
    return out


def check(model: Model, history: Sequence[Op], *,
          max_states: int = 100_000, max_slots: int = MAX_SLOTS,
          frontier0: int = 1 << 10, max_frontier: int = 1 << 17,
          time_limit: Optional[float] = None, should_abort=None,
          devices: Optional[Sequence] = None) -> Dict[str, Any]:
    """Check one history with the sparse frontier engine. Raises
    :class:`FrontierOverflow`,
    :class:`~jepsen_tpu.checkers.events.ConcurrencyOverflow` (needs more
    than ``max_slots`` ≤ 128 pending slots), or
    :class:`~jepsen_tpu.models.memo.StateExplosion` — the facade catches
    these and falls back to the CPU searches. Exceeding ``time_limit`` (or
    ``should_abort()`` returning true between device calls) yields
    ``valid == "unknown"``."""
    return check_packed(model, h.pack(history), max_states=max_states,
                        max_slots=max_slots, frontier0=frontier0,
                        max_frontier=max_frontier, time_limit=time_limit,
                        should_abort=should_abort, devices=devices)


def check_packed(model: Model, packed: h.PackedHistory, *,
                 max_states: int = 100_000, max_slots: int = MAX_SLOTS,
                 frontier0: int = 1 << 10, max_frontier: int = 1 << 17,
                 time_limit: Optional[float] = None, should_abort=None,
                 devices: Optional[Sequence] = None) -> Dict[str, Any]:
    t0 = _time.monotonic()
    if packed.n == 0 or packed.n_ok == 0:
        return {"valid": True, "engine": "frontier", "events": 0,
                "time-s": 0.0}
    deadline = t0 + time_limit if time_limit else None

    def aborted():
        if should_abort is not None and should_abort():
            return True
        return deadline is not None and _time.monotonic() > deadline

    max_slots = min(max_slots, MAX_SLOTS)
    memo = reach._cached_memo(model, packed, max_states)
    stream = ev.build(packed, memo, max_slots=max_slots)
    # round-3 fast path: when the crashed-op quotient's PRODUCT space
    # (state × 2^live-slots × Π per-group counts) is enumerable, walk
    # it densely (reach_q) — microseconds per return and one device
    # dispatch, vs the sparse rows' per-return sort/expand. Budget
    # overflows (many live slots, too many distinct crashed groups, or
    # a huge count product) fall through to the sparse walk below.
    if _use_quotient() and (devices is None or len(devices) <= 1):
        try:
            from jepsen_tpu.checkers import reach_q
        except ImportError:                             # degraded install
            obs.count("engine.skipped.frontier-quotient.unavailable")
            obs.decision("frontier-quotient", "skipped",
                         cause="unavailable")
            reach_q = None
        if reach_q is not None:
            try:
                q = reach_q.check_quotient(memo, stream, packed,
                                           should_abort=aborted)
                elapsed = _time.monotonic() - t0
                if q["valid"] is True:
                    out = reach._result_valid("frontier", stream, memo,
                                              elapsed)
                else:
                    out = reach._result_invalid(
                        "frontier", stream, memo, packed,
                        q["dead-event"], elapsed)
                    for k in ("final-configs", "previous-ok"):
                        if k in q:
                            out[k] = q[k]
                out["quotient"] = "dense-product"
                out["product-space"] = q["product-space"]
                return out
            except reach_q.QuotientOverflow:
                # capacity decline (budgeted product space), not a
                # death: recorded route, sparse walk below decides
                obs.decision("frontier-quotient", "route",
                             cause="quotient-overflow")
            # jtlint: ok fallback — abort cause carried in the returned verdict
            except reach_q.Aborted:
                cause = ("timeout" if deadline is not None
                         and _time.monotonic() > deadline else "aborted")
                return {"valid": "unknown", "cause": cause,
                        "engine": "frontier",
                        "time-s": _time.monotonic() - t0}
            except Exception as e:                      # noqa: BLE001
                reach._warn_pallas_failed(f"reach_q: {e!r}")
    rs = ev.returns_view(stream)
    crashed_slot = _crashed_slots(stream, packed, rs.W)
    R_pad = -(-max(rs.n_returns, 1) // _SEG) * _SEG
    # bucket the slot axis (4 sizes per octave) so jit compilations are
    # shared across histories of similar concurrency
    W_pad = min(max(reach._bucket(rs.W, 4), 4), MAX_SLOTS)
    rs = ev.pad_returns(rs, R_pad, W_pad)
    crashed_slot = np.pad(
        _pad_rows(crashed_slot, R_pad),
        ((0, 0), (0, W_pad - crashed_slot.shape[1])))
    F = max(64, frontier0)
    if devices is not None and len(devices) > 1:
        # SURVEY §7 phase 4: frontier + dedup sharded over the mesh —
        # n× capacity, n parallel dedup sorts, all_to_all row routing
        dead_ret, status, _, _, F = _run_walk_sharded(
            memo, rs, crashed_slot, F, max_frontier, devices,
            should_abort=aborted)
    else:
        dead_ret, status, _, _, F = _run_walk(memo, rs, crashed_slot, F,
                                              max_frontier,
                                              should_abort=aborted)
    if status == _STATUS_ABORT:
        cause = ("timeout" if deadline is not None
                 and _time.monotonic() > deadline else "aborted")
        return {"valid": "unknown", "cause": cause, "engine": "frontier",
                "time-s": _time.monotonic() - t0}
    elapsed = _time.monotonic() - t0
    if status == _STATUS_RUNNING:
        out = reach._result_valid("frontier", stream, memo, elapsed)
        out["frontier-cap"] = F
        return out
    out = reach._result_invalid(
        "frontier", stream, memo, packed, int(rs.ret_event[dead_ret]),
        elapsed)
    out["frontier-cap"] = F
    try:
        out["final-configs"] = _final_configs(memo, rs, crashed_slot, F,
                                              dead_ret,
                                              should_abort=aborted)
        if dead_ret > 0:
            prev = packed.entries[int(rs.ret_entry[dead_ret - 1])]
            out["previous-ok"] = prev.op.to_dict()
    # jtlint: ok fallback — witness evidence is best-effort garnish on a decided verdict
    except Exception:                                   # noqa: BLE001
        pass                            # evidence is best-effort garnish
    return out


# -- mesh-sharded walk (SURVEY.md §7 phase 4: frontier + dedup over ICI) -----
#
# The frontier shards across a 1-D device mesh: each device owns the
# config rows whose hash lands on it (owner = row-hash mod n), giving n×
# the capacity and n parallel dedup sorts. Exactness is preserved by
# construction: a config row deterministically belongs to exactly one
# shard, so after hash-routing (lax.all_to_all over ICI) a LOCAL
# sort-unique is a GLOBAL dedup — no cross-shard duplicate can exist.
# Fire candidates route to their owners each closure round; projection
# and canonicalization change row bits (and therefore owners), so rows
# re-route after each. Termination, death, and overflow are psum-reduced
# so every shard takes identical control-flow decisions (SPMD).

_HASH_A = 0x9E3779B1           # golden-ratio odd constants (uint32 wrap)
_HASH_B = 0x85EBCA77


def _hash_rows_np(rows: np.ndarray, n: int) -> np.ndarray:
    """Owner shard of each row (host mirror of :func:`_hash_rows`)."""
    a = np.uint32(_HASH_A)
    h = np.zeros(len(rows), np.uint32)
    for c in range(rows.shape[1]):
        h = (h ^ rows[:, c].astype(np.uint32)) * a
        h = (h >> np.uint32(16)) ^ (h * np.uint32(_HASH_B))
    return (h % np.uint32(n)).astype(np.int32)


def _hash_rows(rows, n: int):
    """Owner shard of each row (device; must match the host mirror)."""
    import jax.numpy as jnp

    a = jnp.uint32(_HASH_A)
    h = jnp.zeros(rows.shape[0], jnp.uint32)
    for c in range(rows.shape[1]):
        h = (h ^ rows[:, c]) * a
        h = (h >> jnp.uint32(16)) ^ (h * jnp.uint32(_HASH_B))
    return (h % jnp.uint32(n)).astype(jnp.int32)


def _bucket_by_owner(rows, n_dev: int, cap: int):
    """Scatter rows into ``n_dev`` destination buckets of ``cap`` rows
    (invalid-filled). Returns ``(send: u32[n_dev, cap, K1], dropped)``
    where ``dropped`` is true when some bucket overflowed ``cap``."""
    import jax.numpy as jnp

    N, K1 = rows.shape
    valid = rows[:, K1 - 1] != jnp.uint32(0xFFFFFFFF)
    owner = jnp.where(valid, _hash_rows(rows, n_dev), n_dev)
    bufs = []
    dropped = False
    for d in range(n_dev):
        mask = owner == d
        pos = jnp.cumsum(mask.astype(jnp.int32)) - 1
        pos = jnp.where(mask & (pos < cap), pos, cap)
        buf = jnp.full((cap, K1), jnp.uint32(0xFFFFFFFF))
        bufs.append(buf.at[pos].set(rows, mode="drop"))
        dropped = dropped | (jnp.sum(mask.astype(jnp.int32)) > cap)
    return jnp.stack(bufs), dropped


def _route_rows(rows, n_dev: int, cap: int, axis: str):
    """Exchange rows so each lands on its owner shard: bucket by owner,
    ``all_to_all`` over the mesh, flatten. Returns
    ``(recv: u32[n_dev*cap, K1], dropped)``."""
    from jax import lax

    send, dropped = _bucket_by_owner(rows, n_dev, cap)
    recv = lax.all_to_all(send, axis, 0, 0, tiled=False)
    return recv.reshape(n_dev * cap, rows.shape[1]), dropped


def _closure_sharded(C, pending, grouped, same, rank, T_flat,
                     bitmat, word_idx, shift, n_cols, canon: bool,
                     n_dev: int, axis: str):
    """Sharded fixpoint: expand locally in ``_BLOCK``-slot rounds, route
    every round's candidates to their owner shards, fold into the local
    set with a sort-unique (globally deduplicating, by the ownership
    invariant). The fixpoint test and overflow flag are psum-global, and
    — as in :func:`_closure` — compare only deduplicated pass counts."""
    import jax.numpy as jnp
    from jax import lax

    F_l = C.shape[0]
    W = pending.shape[0]
    # per-destination routing depth: a round emits up to F_l·_BLOCK
    # candidate rows (duplicates included, counted on the send side), so
    # small meshes need deeper buckets than uniform hashing alone
    # suggests; skew beyond the cap just flags overflow (sound: the host
    # escalates)
    cap = max(F_l, F_l * _BLOCK // n_dev)

    def cond(c):
        _, gcount, prev, overflow = c
        return (gcount != prev) & ~overflow

    def body(c):
        C, gcount, _, _ = c
        C2, lcount2, overflow = C, jnp.int32(0), False
        for lo in range(0, W, _BLOCK):
            cand = _expand_block(C, pending, grouped, same, rank, T_flat,
                                 bitmat, word_idx, shift, n_cols, lo,
                                 canon)
            recv, dropped = _route_rows(cand, n_dev, cap, axis)
            U = jnp.concatenate([C2, recv], axis=0)
            C2, lcount2 = _sort_unique_compact(U, F_l)
            overflow = overflow | (lcount2 > F_l) | dropped
        gcount2 = lax.psum(lcount2, axis)
        goverflow = lax.psum(overflow.astype(jnp.int32), axis) > 0
        return C2, gcount2, gcount, goverflow

    C, gcount, _, overflow = lax.while_loop(
        cond, body, (C, jnp.int32(-1), jnp.int32(-2), False))
    return C, gcount, overflow


def _reroute_full(C, n_dev: int, axis: str):
    """Re-establish the ownership invariant after rows changed bits
    (canonicalize / projection): route all local rows, then local
    sort-unique (which also merges configs that canonicalization made
    equal). Send buckets are F_l-deep, so sends never drop."""
    import jax.numpy as jnp

    F_l = C.shape[0]
    recv, _ = _route_rows(C, n_dev, F_l, axis)
    return _sort_unique_compact(recv, F_l)


def _walk_sharded(n_cols, canon, n_dev, axis, T_flat, ret_slot, slot_ops,
                  crashed_slot, bitmat, word_idx, shift, C0, count0):
    """Per-shard body of the sharded segment walk (run under
    ``shard_map``); mirrors :func:`_walk` with psum-global liveness."""
    import jax.numpy as jnp
    from jax import lax

    Rn = ret_slot.shape[0]
    F_l = C0.shape[0]

    def cond(c):
        r, _, _, status = c
        return (r < Rn) & (status == _STATUS_RUNNING)

    def body(c):
        r, C, gcount, _ = c
        j = ret_slot[r]

        def do(C, gcount):
            ops_row = slot_ops[r]
            overflow0 = False
            if canon:
                grouped, same, rank = _slot_groups(ops_row, crashed_slot[r])
                C = _canonicalize(C, grouped, same, rank, word_idx, shift,
                                  bitmat)
                C, lcount = _reroute_full(C, n_dev, axis)
                overflow0 = lcount > F_l
            else:
                grouped = same = rank = None
            C1, gcount1, overflow1 = _closure_sharded(
                C, ops_row, grouped, same, rank, T_flat, bitmat,
                word_idx, shift, n_cols, canon, n_dev, axis)
            C2, lcount2 = _project(C1, gcount1, j)
            C2, lcount2b = _reroute_full(C2, n_dev, axis)
            gcount2 = lax.psum(lcount2b, axis)
            goverflow = lax.psum(
                (overflow0 | overflow1 | (lcount2b > F_l))
                .astype(jnp.int32), axis) > 0
            status = jnp.where(
                goverflow, _STATUS_OVERFLOW,
                jnp.where(gcount2 == 0, _STATUS_DEAD, _STATUS_RUNNING))
            return C2, gcount2, status

        def pad(C, gcount):
            return C, gcount, jnp.int32(_STATUS_RUNNING)

        C, gcount, status = lax.cond(j >= 0, do, pad, C, gcount)
        r = jnp.where(status == _STATUS_RUNNING, r + 1, r)
        return r, C, gcount, status

    return lax.while_loop(
        cond, body, (jnp.int32(0), C0, count0,
                     jnp.int32(_STATUS_RUNNING)))


@functools.lru_cache(maxsize=None)
def _jitted_walk_sharded(mesh_devs: tuple, axis: str):
    import jax
    from jax.sharding import PartitionSpec as P

    from jepsen_tpu import parallel as par

    m = par.mesh(axis, list(mesh_devs))
    n_dev = len(mesh_devs)

    def run(T_flat, n_cols, canon, ret_slot, slot_ops, crashed_slot,
            bitmat, word_idx, shift, C0, count0):
        body = functools.partial(_walk_sharded, n_cols, canon, n_dev, axis)
        # check=False: the walk's while_loop mixes replicated and
        # sharded carries, which the static replication checker cannot
        # type on either jax generation (0.4 has no replication rule
        # for `while` at all)
        sm = par.shard_map(
            body, m,
            in_specs=(P(), P(), P(), P(), P(), P(), P(), P(axis), P()),
            out_specs=(P(), P(axis), P(), P()), check=False)
        return sm(T_flat, ret_slot, slot_ops, crashed_slot, bitmat,
                  word_idx, shift, C0, count0)

    return jax.jit(run, static_argnums=(1, 2))


def _initial_frontier_sharded(F_l: int, K: int, initial_state: int,
                              n_dev: int) -> np.ndarray:
    """Global ``u32[n_dev*F_l, K+1]`` with the initial config placed on
    its owner shard (host hash must match the device hash)."""
    C0 = np.full((n_dev * F_l, K + 1), 0xFFFFFFFF, np.uint32)
    row = np.zeros((1, K + 1), np.uint32)
    row[0, K] = initial_state
    owner = int(_hash_rows_np(row, n_dev)[0])
    C0[owner * F_l] = row[0]
    return C0


def _run_walk_sharded(memo: Memo, rs: ev.ReturnStream,
                      crashed_slot: np.ndarray, F: int, max_frontier: int,
                      devices: Sequence, should_abort=None):
    """Sharded analogue of :func:`_run_walk`: ``F`` is the TOTAL frontier
    capacity, split evenly over ``devices``. Escalation re-embeds the
    carried global frontier (host re-hash) into 4× buffers."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from jepsen_tpu import parallel as par

    n_dev = len(devices)
    axis = "shards"
    W = rs.W
    K, word_idx, shift, bitmat = _slot_geometry(W)
    S, O = memo.table.shape
    F_l = max(64, -(-F // n_dev))
    walk = _jitted_walk_sharded(tuple(devices), axis)
    m = par.mesh(axis, list(devices))
    sharded = NamedSharding(m, P(axis))
    T_flat = jnp.asarray(memo.table.reshape(-1))
    bitmat_d, word_idx_d, shift_d = (jnp.asarray(bitmat),
                                     jnp.asarray(word_idx),
                                     jnp.asarray(shift))
    canon = bool(crashed_slot.any())
    C = jax.device_put(
        _initial_frontier_sharded(F_l, K, memo.initial, n_dev), sharded)
    count = jnp.int32(1)
    base = 0
    while base < rs.R:
        if should_abort is not None and should_abort():
            return -1, _STATUS_ABORT, C, count, n_dev * F_l
        sl = slice(base, base + _SEG)
        r, C2, count2, status = walk(
            T_flat, O, canon, jnp.asarray(rs.ret_slot[sl]),
            jnp.asarray(rs.slot_ops[sl]), jnp.asarray(crashed_slot[sl]),
            bitmat_d, word_idx_d, shift_d, C, count)
        status = int(status)
        if status == _STATUS_OVERFLOW:
            # re-embed: collect live rows, re-hash onto bigger shards
            # (keep growing until the most-loaded shard fits too). The
            # fetch must go through _fetch: in a multi-process run C
            # spans non-addressable devices (process_allgather there)
            from jepsen_tpu.checkers.reach import _fetch
            rows = _fetch(C)
            rows = rows[rows[:, K] != np.uint32(0xFFFFFFFF)]
            owners = _hash_rows_np(rows, n_dev)
            load = np.bincount(owners, minlength=n_dev).max() if len(rows) \
                else 0
            F_l *= 4
            while F_l < load:
                F_l *= 4
            # the caller's total cap bounds escalation directly; only the
            # INITIAL allocation may exceed a tiny cap, via the
            # unavoidable n_dev*64 per-shard minimum buffer
            if n_dev * F_l > max_frontier:
                raise FrontierOverflow(
                    f"reachable config set exceeds {max_frontier} rows")
            C_np = np.full((n_dev * F_l, K + 1), 0xFFFFFFFF, np.uint32)
            for d in range(n_dev):
                mine = rows[owners == d]
                C_np[d * F_l:d * F_l + len(mine)] = mine
            C = jax.device_put(C_np, sharded)
            continue
        if status != _STATUS_RUNNING:
            return base + int(r), status, C2, count2, n_dev * F_l
        C, count = C2, count2
        base += _SEG
    return rs.R, _STATUS_RUNNING, C, count, n_dev * F_l
