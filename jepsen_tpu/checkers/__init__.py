"""Checkers — upstream: ``jepsen/src/jepsen/checker.clj`` plus the Knossos
library (SURVEY.md §2.1, §2.2). The façade module
:mod:`jepsen_tpu.checkers.facade` provides the composable ``Checker`` API;
the linearizability engines live in:

- :mod:`jepsen_tpu.checkers.reach` — the TPU-native dense-reachability
  search (the north star; upstream ``knossos.linear`` + ``knossos.wgl``
  recast as a device-resident tensor program).
- :mod:`jepsen_tpu.checkers.reach_chunklock` — one history's chunks
  walked as simultaneous lockstep lane blocks (suffix bounds, seeded
  restricted transfers, on-device fold; one host round trip).
- :mod:`jepsen_tpu.checkers.wgl_ref` — CPU reference Wing-Gong-Lowe search
  (upstream ``knossos.wgl``), the correctness oracle and CPU baseline.
- :mod:`jepsen_tpu.checkers.linear` — sparse just-in-time linearization
  (upstream ``knossos.linear`` with ``knossos.linear.config``'s
  array/set config-set representations).
- :mod:`jepsen_tpu.checkers.brute` — exhaustive permutation checker for
  differential testing of tiny histories (no upstream analogue; replaces
  knossos's recorded-fixture cross-checks at the smallest scale).
- :mod:`jepsen_tpu.checkers.events` — host-side slot/event-stream
  preprocessing feeding the device engines.
"""
from jepsen_tpu.checkers.facade import (  # noqa: F401
    Checker, check_safe, compose, counter, linearizable, noop_checker,
    queue, set_checker, stats, total_queue, unbridled_optimism,
)
