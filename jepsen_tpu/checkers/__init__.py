"""Checkers — upstream: ``jepsen/src/jepsen/checker.clj`` plus the Knossos
library (SURVEY.md §2.1, §2.2). The façade module
:mod:`jepsen_tpu.checkers.facade` provides the composable ``Checker`` API;
the linearizability engines live in:

- :mod:`jepsen_tpu.checkers.wgl_ref` — CPU reference Wing-Gong-Lowe search
  (upstream ``knossos.wgl``), the correctness oracle and CPU baseline.
- :mod:`jepsen_tpu.checkers.brute` — exhaustive permutation checker for
  differential testing of tiny histories (no upstream analogue; replaces
  knossos's recorded-fixture cross-checks at the smallest scale).
- :mod:`jepsen_tpu.checkers.wgl_tpu` — the batched JAX frontier search
  (the north star; upstream ``knossos.wgl`` recast for the MXU).
"""
