"""HTML op timeline — upstream ``jepsen/src/jepsen/checker/timeline.clj``
(SURVEY.md §2.1): one swim-lane per process, each operation a box spanning
its invocation→completion interval, colored by outcome. Written as a
self-contained HTML file (no hiccup, no external assets).
"""
from __future__ import annotations

import html as _html
from typing import Any, Dict, Mapping, Optional, Sequence

from jepsen_tpu.checkers.facade import Checker
from jepsen_tpu.op import FAIL, INFO, INVOKE, OK, Op

_COLORS = {OK: "#6db66d", FAIL: "#d66", INFO: "#d6a76d", "pending": "#aaa"}

_CSS = """
body { font-family: sans-serif; background: #fff; }
.lane { position: relative; height: 26px; border-bottom: 1px solid #eee; }
.lane .label { position: absolute; left: 0; width: 90px; font-size: 12px;
               line-height: 26px; color: #555; }
.ops { position: absolute; left: 100px; right: 0; top: 0; bottom: 0; }
.op { position: absolute; height: 20px; top: 2px; border-radius: 3px;
      font-size: 10px; overflow: hidden; white-space: nowrap;
      color: #fff; padding: 1px 3px; box-sizing: border-box; }
"""


def render(history: Sequence[Op], title: str = "timeline") -> str:
    """Render a history to a standalone HTML string."""
    ops = [op for op in history if op.process != "nemesis"]
    # pair invokes with completions per process
    lanes: Dict[Any, list] = {}
    pending: Dict[Any, Op] = {}
    spans = []
    tmax = 1
    for i, op in enumerate(ops):
        t = op.time if op.time >= 0 else (op.index if op.index >= 0 else i)
        tmax = max(tmax, t)
        if op.type == INVOKE:
            pending[op.process] = op.with_(time=t)
        else:
            inv = pending.pop(op.process, None)
            if inv is not None:
                spans.append((op.process, inv.time, t, op.type, inv.f,
                              op.value if op.type == OK else inv.value))
    for p, inv in pending.items():
        spans.append((p, inv.time, tmax, "pending", inv.f, inv.value))
    for p, *_ in spans:
        lanes.setdefault(p, [])
    rows = []
    for p in sorted(lanes, key=repr):
        boxes = []
        for proc, t0, t1, typ, f, v in spans:
            if proc != p:
                continue
            left = 100.0 * t0 / max(1, tmax)
            width = max(0.4, 100.0 * (t1 - t0) / max(1, tmax))
            label = _html.escape(f"{f} {v!r} [{typ}]")
            boxes.append(
                f'<div class="op" title="{label}" style="left:{left:.3f}%;'
                f'width:{width:.3f}%;background:{_COLORS.get(typ, "#888")}">'
                f'{_html.escape(str(f))}</div>')
        rows.append(f'<div class="lane"><div class="label">process '
                    f'{_html.escape(str(p))}</div>'
                    f'<div class="ops">{"".join(boxes)}</div></div>')
    return (f"<!doctype html><html><head><meta charset='utf-8'>"
            f"<title>{_html.escape(title)}</title><style>{_CSS}</style>"
            f"</head><body><h3>{_html.escape(title)}</h3>"
            f"{''.join(rows)}</body></html>")


class TimelineChecker(Checker):
    """Writes ``timeline.html`` into the test's store directory (upstream
    ``jepsen.checker.timeline/html``)."""
    name = "timeline"

    def check(self, test: Optional[Mapping], history: Sequence[Op],
              opts: Optional[Mapping] = None) -> Dict[str, Any]:
        out_dir = (opts or {}).get("dir") or (test or {}).get("dir") or (test or {}).get("store_dir")
        doc = render(history, title=str((test or {}).get("name", "timeline")))
        if out_dir:
            import os
            path = os.path.join(out_dir, "timeline.html")
            with open(path, "w") as f:
                f.write(doc)
            return {"valid": True, "file": path}
        return {"valid": True, "html-bytes": len(doc)}


def html() -> TimelineChecker:
    return TimelineChecker()
