"""Lockstep-batched lane kernel: H independent histories advance
through the dense-reachability returns walk TOGETHER, one return index
per step, with their config sets side by side along the lane axis.

Why: the single-history walk (``reach_lane``) is a sequential chain of
tiny [M,S]@[S,W*S] matmuls — per-ISSUE latency bound, with the MXU and
VPU almost idle (MFU ~0.04%). Checking a BATCH of histories one after
another pays that latency wall H times. Lockstep batching pays it once:

- config sets live as ONE array ``R [M, H*S]`` (history h owns lane
  block ``h*S:(h+1)*S``);
- the per-return fire matmul becomes ONE ``[M, H*S] @ [H*S, W*H*S]``
  issue against a BLOCK-DIAGONAL transition operand (history h's
  pending ops in rows ``h*S:(h+1)*S``, slot-major column blocks), so
  the off-diagonal zero blocks guarantee no cross-history terms and
  the MXU amortizes one issue over H histories;
- every VPU op (fire blends, projection) operates on ``[M, H*S]``
  lanes — H× the lane utilization of the single-history kernel;
- the pending-count gate ladder (see ``reach_lane._ladder_fire``) is
  gated by ``max_h c_r(h)`` — ≥ each history's own bound, so the walk
  stays EXACT per history (extra passes past a history's fixpoint are
  idempotent).

Projection is per-history (different slots return at the same step, or
none: identity): a pre-expanded per-return lane row ``jv [H*S]``
(lane block h holds ``ret_slot_h`` as f32) turns the W static
projections + identity into W+1 batched blend terms with lane-wise
0/1 indicator multiplies — the same blend trick as the single kernel,
vectorized across the batch.

Death detection mirrors the lane kernel: per-block checkpoints of the
whole batched set, host-side per-history localization, and an exact
single-history block re-walk (``reach_lane._refine_dead``) only for
histories that died. Histories are independent throughout — verdicts
and dead indices are bit-identical to running the single-history walk
H times (differentially tested in ``tests/test_reach_batch.py``).

Upstream analogue: none — knossos checks one history per JVM run; this
is the TPU-native answer to "a Jepsen run produced several large
histories" (e.g. ``test-count > 1`` or per-node sub-histories), and
the engine behind the ``cas-100k x 8`` benchmark rung. Reference
behavior being reproduced: knossos.wgl per-history semantics
(SURVEY.md §2.2).
"""
from __future__ import annotations

import functools
import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

from jepsen_tpu import obs
from jepsen_tpu.checkers import dispatch_core, transfer
from jepsen_tpu.checkers.reach_lane import (_BLOCK, _FAST_PASSES,
                                            _idx_dtype, _refine_dead)

# segments for the put+dispatch pipeline (one fetch; transfers of
# segment i+1 stream while the device walks segment i). The batch
# operand set is H× the single-history one, so it pipelines finer:
# interleaved ablation on 32 × cas-100k measured 8 segments ~8-10%
# faster e2e than the single-history path's 4 (1.54/1.61 vs 1.67/1.78
# best/median), while 12 gave it back on per-dispatch overhead; the
# single-history walk is nseg-neutral (453 KB of operands, measured
# medians equal) and keeps its own 4.
_PIPE_NSEG = 8

# default for the ``interpret`` flag of the marshal/dispatch entry
# points when the caller passes None: tests flip this to route EVERY
# dispatch — including the streaming prep pipeline's, whose scheduler
# never threads an interpret argument — through interpret mode on CPU
_INTERPRET_DEFAULT = False

# SMEM byte budget for the double-buffered slot_ops window
# (B*H*W i32 ×2 buffers). The chip holds 1 MB of SMEM: the H=32,
# B=1024 geometry needed 1.31 MB and failed to compile while 0.655 MB
# fit (BASELINE.md round-4 batch rung) — so the block size shrinks as
# the lockstep width grows instead of capping H at 16.
_SMEM_BUDGET = 840_000


def _adaptive_block(H: int, W: int) -> int:
    """Largest power-of-two block ≤ ``_BLOCK`` whose double-buffered
    slot_ops SMEM window fits the measured budget. B=1024 up to H=16
    at W=5 (the round-4 default geometry), B=512 at H=32, B=256 at
    H=64 — the window stays ~655 KB at every width."""
    cap = max(32, _SMEM_BUDGET // (H * W * 8))
    b = 1 << (cap.bit_length() - 1)
    return min(_BLOCK, b)


def plan_buckets(R_lens, W: int, *, group: int = 32) -> List[List[int]]:
    """Length-bucketed lane packing: partition a ragged batch of return
    streams into lockstep dispatch groups such that no stream walks
    more than ~2x its own padded length. Streams are assigned to
    power-of-two length buckets and each bucket is greedily chunked
    (longest first) into groups of at most ``group`` lanes — so a
    10k-return history no longer forces 200-return co-batched keys to
    walk 10k padded lockstep steps.

    Lengths at or below the dispatch block size (the SMEM-budgeted
    ``_adaptive_block`` floor, which every group pads to anyway) share
    ONE floor bucket: splitting them buys nothing and costs extra
    dispatches + compile geometries. (The floor uses the production
    block size; interpret-mode dispatches use a smaller block, making
    the floor bucket merely coarser there — suboptimal packing, never
    incorrect.) Groups are ordered longest bucket first so the
    pipelined scheduler overlaps later (cheaper) groups' marshalling
    and compiles with the big walk. Returns a partition of
    ``range(len(R_lens))`` — every index appears in exactly one
    group."""
    floor = _adaptive_block(
        max(1, min(group, len(R_lens))), max(W, 1))
    order = sorted(range(len(R_lens)),
                   key=lambda i: (-int(R_lens[i]), i))
    buckets: dict = {}
    for i in order:
        eff = max(int(R_lens[i]), floor, 1)
        buckets.setdefault((eff - 1).bit_length(), []).append(i)
    groups: List[List[int]] = []
    for key in sorted(buckets, reverse=True):
        idxs = buckets[key]
        for j in range(0, len(idxs), group):
            groups.append(idxs[j:j + group])
    return groups


def mesh_lockstep_enabled() -> bool:
    """The device-sharded lockstep lane (lane blocks placed across a
    mesh's devices) is on by default wherever a mesh is supplied;
    ``JEPSEN_TPU_NO_MESH_LOCKSTEP=1`` forces the pre-mesh routes
    (consulted per call — tests toggle it)."""
    return not os.environ.get("JEPSEN_TPU_NO_MESH_LOCKSTEP")


def shard_groups_for_mesh(groups: List[List[int]], n_dev: int
                          ) -> Tuple[List[List[int]], int]:
    """Lane-axis sharding at the planner level: split dispatch groups
    into per-device lane blocks until at least ``n_dev`` groups exist,
    so a batch that packs into fewer groups than the mesh has devices
    still walks on every chip. The widest group splits first, into two
    equal halves — its lane count padded to even by REPLICATING its
    first lane, so both halves share one compiled geometry and the pad
    lane's verdict write is idempotent (it walks the same stream as
    the lane it copies). Returns ``(groups, pad_lanes)``; every input
    index still appears in some group, single-lane groups cannot
    split, so a tiny batch may underfill the mesh."""
    out = [list(g) for g in groups]
    pad = 0
    while len(out) < n_dev:
        widest = max(range(len(out)), key=lambda i: len(out[i]))
        g = out.pop(widest)
        if len(g) < 2:
            out.insert(widest, g)
            break
        if len(g) % 2:
            g = g + [g[0]]
            pad += 1
        half = len(g) // 2
        out[widest:widest] = [g[:half], g[half:]]
    return out, pad


def group_geom(R_max: int, H: int, W: int, *,
               interpret: bool = False) -> Tuple[int, int]:
    """Dispatch block size and padded lockstep step count for a group
    of ``H`` streams whose longest member has ``R_max`` returns — the
    ONE source of the padding formula, shared by
    :func:`pack_batch_operands`, the ``tools/batch_width.py`` ragged
    sweep, and the geometry-bounds tests (a formula drift there would
    otherwise silently misreport pack efficiency)."""
    from jepsen_tpu.checkers.reach import _bucket

    B = min(32, _BLOCK) if interpret else _adaptive_block(H, W)
    R_pad = max(B, _bucket(-(-max(int(R_max), 1) // B) * B, B))
    return B, R_pad


def group_diag(geom, R_lens) -> dict:
    """Per-group geometry + pack-efficiency accounting for one lockstep
    dispatch (bench.py's batch rung): real vs padded returns under this
    group's ``(H, B, W, S, M, R_pad)`` geometry."""
    B, W, M, S, H, O1, R_pad = geom
    real = int(sum(int(r) for r in R_lens))
    return {"H": H, "B": B, "W": W, "S": S, "R_pad": R_pad,
            "real_returns": real, "padded_returns": H * R_pad}


def kernel_cache_info() -> dict:
    """Hit/miss counters of the per-geometry compiled-kernel cache
    (:func:`_batch_call`, keyed on ``(B, W, M, S, H, O1, segment,
    passes, dtype)``): a bucketed ragged batch reuses one compiled
    program per distinct geometry, and the bench batch rung surfaces
    these so a geometry-churn regression is visible."""
    ci = _batch_call.cache_info()
    return {"hits": int(ci.hits), "misses": int(ci.misses),
            "entries": int(ci.currsize)}


def _one_fire_pass_b(R, G_all, W: int, M: int, HS: int):
    """One Jacobi fire pass over the batched set: ONE fused
    ``[M,HS] @ [HS, W*HS]`` matmul (block-diagonal G ⇒ history h's
    image depends only on history h's set), then the per-slot mask
    blends on the M axis — identical math to
    ``reach_pallas._one_fire_pass`` with S widened to H*S lanes.
    Exact in bf16 too: operands are 0/1 (exactly representable), the
    dot accumulates in f32 (``preferred_element_type`` — sums can
    reach P's per-column fan-in, so this is load-bearing), and the
    blend compares > 0.5 on the f32 image before any rounding back."""
    import jax.numpy as jnp

    F = jnp.dot(R, G_all, preferred_element_type=jnp.float32)
    for jj in range(W):
        Fj = F[:, jj * HS:(jj + 1) * HS]
        half, blk = M >> (jj + 1), 1 << jj
        Rr = R.reshape(half, 2, blk, HS)
        Fr = Fj.reshape(half, 2, blk, HS)
        hi = jnp.maximum(
            Rr[:, 1], (Fr[:, 0] > 0.5).astype(R.dtype))
        R = jnp.stack([Rr[:, 0], hi], axis=1).reshape(M, HS)
    return R


def _ladder_fire_b(R_scr, R, pend_c, G_all, n_pass: int, W: int,
                   M: int, HS: int):
    """Gate-ladder closure on the batched set, gated by the batch-max
    pending count (exact per history: extra passes are idempotent)."""
    from jax.experimental import pallas as pl

    R = _one_fire_pass_b(R, G_all, W, M, HS)
    if n_pass <= 1:
        return R
    R_scr[:] = R
    for off in range(1, n_pass):
        def _deep():
            Rd = R_scr[:]
            R_scr[:] = _one_fire_pass_b(Rd, G_all, W, M, HS)
        pl.when(pend_c > off)(_deep)
    return R_scr[:]


def _gather_G_b(slot_ops_ref, P_ref, k: int, W: int, H: int, S: int,
                O1: int, G_scr, buf):
    """Write return ``k``'s H*W pending-op transition tiles onto the
    diagonal blocks of ``G_scr[buf]`` (slot-major column blocks; the
    off-diagonal blocks were zeroed once at step 0 and are never
    written, preserving history independence). Slot -1 → the all-zero
    sentinel row of P."""
    import jax.numpy as jnp

    HS = H * S
    for hh in range(H):
        for jj in range(W):
            o = slot_ops_ref[(k * H + hh) * W + jj]
            o = jnp.where(o < 0, O1 - 1, o)
            G_scr[buf, hh * S:(hh + 1) * S,
                  jj * HS + hh * S:jj * HS + (hh + 1) * S] = P_ref[o]


def _make_batch_kernel(B: int, W: int, M: int, S: int, H: int,
                       O1: int, n_blocks: int, n_pass: int):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    HS = H * S

    def kernel(slot_ops_ref, pendmax_ref, jv_ref, P_ref, R0_ref,
               ckpt_ref, final_ref, R_scr, G_scr):
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _init():
            R_scr[:] = R0_ref[:]
            # zero once: diagonal blocks are overwritten per return,
            # off-diagonal blocks stay zero forever (the independence
            # guarantee of the batched fire matmul)
            G_scr[:] = jnp.zeros_like(G_scr)

        # checkpoints/final stay f32 regardless of the compute dtype
        # (host-side localization reads them with > 0.5 unchanged)
        ckpt_ref[0] = R_scr[:].astype(jnp.float32)  # set at block START
        _gather_G_b(slot_ops_ref, P_ref, 0, W, H, S, O1, G_scr, 0)

        def one(k, R):
            G_all = G_scr[k % 2]
            # prefetch the NEXT return's operand while this return's
            # MXU chain is in flight (G does not depend on R)
            kn = jnp.minimum(k + 1, B - 1)
            _gather_G_b(slot_ops_ref, P_ref, kn, W, H, S, O1, G_scr,
                        (k + 1) % 2)
            R = _ladder_fire_b(R_scr, R, pendmax_ref[k], G_all, n_pass,
                               W, M, HS)
            # per-history projection blend: lane row jv holds each
            # history's returning slot (-1 = none) replicated over its
            # S lanes
            row = jv_ref[k]                      # [HS] f32
            acc = R * (row < 0).astype(R.dtype)
            for jj in range(W):
                half, blk = M >> (jj + 1), 1 << jj
                Rr = R.reshape(half, 2, blk, HS)
                taken = Rr[:, 1]
                proj = jnp.stack([taken, jnp.zeros_like(taken)],
                                 axis=1).reshape(M, HS)
                acc = acc + proj * (row == jj).astype(R.dtype)
            return acc

        def do_return(i, _):
            R_scr[:] = one(i, R_scr[:])
            return 0

        jax.lax.fori_loop(0, B, do_return, 0)

        @pl.when(step == n_blocks - 1)
        def _finish():
            final_ref[:] = R_scr[:].astype(jnp.float32)

    return kernel


# compute dtype for the config sets and transition operand. bf16 is
# EXACT here because every stored value is 0 or 1 (exactly
# representable) and the fire dot ACCUMULATES IN F32 via
# preferred_element_type — column sums can reach the per-column
# fan-in of P (up to S), so the f32 accumulation is the load-bearing
# half of the argument, with the > 0.5 compare reading the f32 image
# before anything is rounded back to bf16. Halves the VMEM footprint
# and traffic of the G operand scratch — the resource that pinned the
# lockstep width at 32 (H=64's f32 geometry exceeded the 16 MB
# scoped-VMEM limit by 212 KB). Checkpoint/final outputs stay f32 so
# host-side localization is unchanged.
_COMPUTE_DTYPE = "bfloat16"


@functools.cache
def _batch_call(B: int, W: int, M: int, S: int, H: int, O1: int,
                R_pad: int, n_pass: int, interpret: bool, dtype: str,
                donate: bool = False):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    cdt = jnp.dtype(dtype)
    HS = H * S
    n_blocks = R_pad // B
    # 1-D SMEM windows must tile to 1024 (Mosaic layout verification
    # fails on a 512-wide window when the adaptive block shrinks below
    # 1024 at H≥32) — BOTH scalar operands pad each per-grid-step
    # block up to a 1024 multiple on device: pendmax's B-block to PB,
    # and slot_ops' B*H*W-block to SOW_P (B=1024 makes B*H*W a 1024
    # multiple for any H*W, but the adaptive block at H≥32 does not —
    # e.g. a tail group of H=21 at W=5, B=512 is 52.5 tiles). The
    # kernel indexes only the first B*H*W (resp. B) entries of each
    # block, so the tail pad is never read.
    PB = max(B, 1024)
    SOW = B * H * W
    SOW_P = -(-SOW // 1024) * 1024
    kernel = _make_batch_kernel(B, W, M, S, H, O1, n_blocks, n_pass)
    call = pl.pallas_call(
        kernel,
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((SOW_P,), lambda i: (i,),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((PB,), lambda i: (i,), memory_space=pltpu.SMEM),
            pl.BlockSpec((B, HS), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((O1, S, S), lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((M, HS), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, M, HS), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((M, HS), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_blocks, M, HS), jnp.float32),
            jax.ShapeDtypeStruct((M, HS), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((M, HS), cdt),
            pltpu.VMEM((2, HS, W * HS), cdt),
        ],
        interpret=interpret,
    )

    def run(slot_ops, ret_slot_rh, P, R0):
        # device-side derivations (the wire carries only narrow ints
        # and bit-packed bools): batch-max pending count per return
        # gates the ladder; the projection lane row expands each
        # history's returning slot over its S lanes
        P = P.astype(cdt)
        if R0.dtype == jnp.uint8:
            # bit-packed config seeds (8 per wire byte), unpacked where
            # bandwidth is free
            R0 = jnp.unpackbits(R0, count=M * HS).reshape(M, HS) \
                    .astype(cdt)
        else:
            R0 = R0.astype(cdt)
        if slot_ops.dtype == jnp.uint8:
            # 6-bit packed ops lane (4 values per 3 wire bytes): the
            # dense narrow format is SIGNED, so uint8 unambiguously
            # marks the packed lane
            slot_ops = transfer.unpack_sextet_jnp(slot_ops,
                                                  R_pad * H * W)
        pend = jnp.sum((slot_ops.reshape(-1, H, W) >= 0)
                       .astype(jnp.int32), axis=2)
        pendmax = jnp.max(pend, axis=1)
        ops32 = slot_ops.astype(jnp.int32)
        if PB != B:                     # pad each B-block to the SMEM tile
            pendmax = jnp.pad(pendmax.reshape(-1, B),
                              ((0, 0), (0, PB - B))).reshape(-1)
        if SOW_P != SOW:                # pad each B*H*W-block likewise
            ops32 = jnp.pad(ops32.reshape(-1, SOW),
                            ((0, 0), (0, SOW_P - SOW)),
                            constant_values=-1).reshape(-1)
        jv = jnp.repeat(ret_slot_rh.astype(jnp.float32), S, axis=1)
        return call(ops32, pendmax, jv, P, R0)

    # donated carried config set: XLA recycles the [M, HS] f32 buffer
    # for the segment's `final` output instead of reallocating per
    # dispatch (pipeline-intermediate carries only — see _pipe_walk_b)
    return jax.jit(run, donate_argnums=(3,)) if donate else jax.jit(run)


def pack_batch_operands(P: np.ndarray, ret_slots: List[np.ndarray],
                        slot_ops: List[np.ndarray], M: int, *,
                        interpret: bool = False):
    """Marshal H per-history return streams into the lockstep layout:
    all padded (identity rows: slot -1) to one bucketed ``R_pad``, then
    interleaved return-major — ``slot_ops_flat[(r*H + h)*W + jj]`` and
    ``ret_slot_rh[r, h]`` — so one SMEM/VMEM block holds a contiguous
    run of lockstep steps. Returns ``(geom, host_args, R_lens)``."""
    O1, S, _ = P.shape
    H = len(ret_slots)
    W = max(int(so.shape[1]) for so in slot_ops)
    R_max = max(1, max(int(r.shape[0]) for r in ret_slots))
    B, R_pad = group_geom(R_max, H, W, interpret=interpret)
    rs_rh = np.full((R_pad, H), -1, np.int8)
    ops_rhw = np.full((R_pad, H, W), -1, np.int32)
    for h in range(H):
        n = int(ret_slots[h].shape[0])
        rs_rh[:n, h] = ret_slots[h]
        ops_rhw[:n, h, :slot_ops[h].shape[1]] = slot_ops[h]
    idx_dt = _idx_dtype(O1)
    R0 = np.zeros((M, H * S), np.float32)
    for h in range(H):
        R0[0, h * S] = 1.0                   # mask 0, state 0 per block
    # the per-lane config seeds cross bit-packed (8 configs per wire
    # byte, unpacked on device — see _batch_call.run) unless opted out
    r0_wire = transfer.pack_bool(R0) if transfer.packed_enabled() \
        else R0
    host_args = (np.ascontiguousarray(ops_rhw.reshape(-1), idx_dt),
                 np.ascontiguousarray(rs_rh),
                 np.ascontiguousarray(P, np.float32),
                 r0_wire)
    geom = (B, W, M, S, H, O1, R_pad)
    return geom, host_args, [int(r.shape[0]) for r in ret_slots]


def _pipe_walk_b(host_args, geom, n_pass: int, interpret: bool,
                 dsegs: dict, device=None):
    """Segmented put+dispatch pipeline for the batch walk (same shape
    as ``reach_lane._pipe_walk``): no intermediate fetch, cached device
    segments for rescue reuse. Transfer diet: the transition tensor is
    cached device-resident across the group sequence
    (:func:`transfer.cached_put` — one upload per batch, not per
    group), the config seeds cross bit-packed, and segments after the
    first donate the carried config set so XLA recycles its HBM buffer
    per dispatch. ``device`` (mesh dispatches) keys the operand cache;
    a diet-path failure records exactly one obs fallback and the walk
    degrades to the round-5 dispatch."""
    import jax
    import jax.numpy as jnp

    from jepsen_tpu.checkers.reach_lane import _pipe_geom

    B, W, M, S, H, O1, R_pad = geom
    ops_flat, rs_rh, P, R0 = host_args
    HS = H * S
    seg, nseg = _pipe_geom(B, R_pad, _PIPE_NSEG)
    # bf16 only at full-lane widths: with H*S below the 128-lane tile
    # the bf16 (16,128) tiling degenerates (measured: 8 × cas-100k at
    # HS=64 runs ~2.0 s in bf16 vs 0.47 s in f32, while HS ≥ 128
    # geometries are 6-8% FASTER in bf16)
    cdt = _COMPUTE_DTYPE if HS >= 128 else "float32"
    run = _batch_call(B, W, M, S, H, O1, seg, n_pass, interpret, cdt)
    run_d = None
    donate = transfer.donate_enabled()
    sextet = transfer.packed_enabled() and transfer.sextet_ok(O1)
    HW = H * W

    def _seg_host(k: int):
        """Segment ``k``'s host operands in the dense narrow format."""
        lo, hi = k * seg, min((k + 1) * seg, R_pad)
        o_seg = ops_flat[lo * HW:hi * HW]
        r_seg = rs_rh[lo:hi]
        if hi - lo < seg:                # ragged tail: identity pad
            o_seg = np.pad(o_seg, (0, (seg - (hi - lo)) * HW),
                           constant_values=-1)
            r_seg = np.pad(r_seg, ((0, seg - (hi - lo)), (0, 0)),
                           constant_values=-1)
        return (np.ascontiguousarray(o_seg),
                np.ascontiguousarray(r_seg))

    fresh = "segs" not in dsegs
    if fresh:
        # cast to the compute dtype BEFORE the wire: bf16 halves the
        # transfer and the in-jit astype then no-ops (leaving it f32
        # here would re-materialize a converted copy on every segment
        # dispatch)
        dsegs["dP"], p_hit = transfer.cached_put(
            P, (cdt, str(device)), lambda: jnp.asarray(P, dtype=cdt))
        if getattr(R0, "dtype", None) == np.uint8:
            dsegs["dR0"] = jax.device_put(R0)     # bit-packed seeds
        else:
            dsegs["dR0"] = jnp.asarray(R0, dtype=cdt)
        dsegs["segs"] = []
        p_bytes = P.size * (2 if cdt == "bfloat16" else 4)
        # the ops lane crosses 6-bit packed per segment when the
        # alphabet fits the sextet (see the upload loop below)
        ops_wire_b = (nseg * transfer.sextet_bytes(seg * HW)
                      if sextet else int(ops_flat.nbytes))
        # a seed that arrived as a DEVICE array (chunklock phase B
        # hands over _glue_call's output) never crosses the link —
        # count it on neither side of the actual/baseline pair
        r0_host = isinstance(R0, np.ndarray)
        actual = (ops_wire_b + int(rs_rh.nbytes)
                  + (int(dsegs["dR0"].nbytes) if r0_host else 0)
                  + (0 if p_hit else p_bytes))
        baseline = (R_pad * H * W * 4 + R_pad * H * 4 + int(P.nbytes)
                    + (M * HS * 4 if r0_host else 0))
        dsegs["xfer"] = (actual, baseline)
        obs.count("lockstep.transfer_bytes", actual)
        transfer.count_put(actual, baseline)
    R_cur = dsegs["dR0"]
    ckpts = []
    # double-buffered wire: with pipelining on, segment i+1's host pack
    # and device_put are issued BEFORE segment i's dispatch returns
    # control, so the pack/transfer rides under segment i's device walk
    # instead of serializing between launches.  JEPSEN_TPU_NO_PIPELINE
    # restores the strict build-then-dispatch order.
    prefetch = fresh and dispatch_core.pipeline_enabled()

    def _seg_dev(k: int):
        """Segment ``k``'s device operands, built and uploaded on
        first use (cached in ``dsegs`` so rescue re-walks and the
        dense-recover rebuild see prefetched segments identically)."""
        while len(dsegs["segs"]) <= k:
            o_seg, r_seg = _seg_host(len(dsegs["segs"]))
            dsegs["segs"].append(jax.device_put(
                (transfer.pack_sextet(o_seg) if sextet else o_seg,
                 r_seg)))
        return dsegs["segs"][k]

    for i in range(nseg):
        if fresh:
            _seg_dev(i)
            if prefetch and i + 1 < nseg:
                _seg_dev(i + 1)
        a, b = dsegs["segs"][i]
        # dR0 is never donated (the rescue walk re-reads it); only the
        # pipeline-intermediate carried sets are
        use_donate = donate and i > 0
        try:
            if use_donate:
                if run_d is None:
                    run_d = _batch_call(B, W, M, S, H, O1, seg, n_pass,
                                        interpret, cdt, True)
                ck, R_cur = run_d(a, b, dsegs["dP"], R_cur)
                obs.count("donate.reuse")
            else:
                ck, R_cur = run(a, b, dsegs["dP"], R_cur)
        except Exception as e:                          # noqa: BLE001
            # packedness of what's actually resident, not the env gate:
            # a rescue re-entry may carry dense segments from a prior
            # call's fallback while the gate still reads open
            packed_wire = (
                getattr(dsegs["dR0"], "dtype", None) == np.uint8
                or getattr(a, "dtype", None) == np.uint8)

            def _dense_recover(exc):
                """ONE `packed-xfer` record: re-materialize the round-5
                dense format host-side (f32 seed, signed narrow ops —
                every built segment too, so the record covers the rest
                of the walk), account the re-uploads, and re-walk
                segments 0..i undonated from the seed. The record lands
                only after the dense re-walk succeeds — a failure that
                persists dense was never the packed wire's fault."""
                nonlocal sextet
                extra = 0
                if getattr(dsegs["dR0"], "dtype", None) == np.uint8:
                    dense = transfer.unpack_bool_host(
                        np.asarray(dsegs["dR0"]), M * HS)
                    dsegs["dR0"] = jnp.asarray(
                        dense.reshape(M, HS).astype(np.float32),
                        dtype=cdt)
                    extra += M * HS * (2 if cdt == "bfloat16" else 4)
                if getattr(dsegs["segs"][i][0], "dtype",
                           None) == np.uint8:
                    n_built = len(dsegs["segs"])
                    dsegs["segs"] = [jax.device_put(_seg_host(k))
                                     for k in range(n_built)]
                    # dense rebuilds of the built segments re-cross the
                    # link, and the segments still to come now cross
                    # dense instead of sextet-packed
                    o_b = seg * HW * ops_flat.dtype.itemsize
                    extra += n_built * (o_b + seg * H
                                        * rs_rh.dtype.itemsize)
                    extra += (nseg - n_built) * (
                        o_b - transfer.sextet_bytes(seg * HW))
                sextet = False
                # the counters AND this walk's diag must see what the
                # link actually carried, or the fallback run would
                # report a diet it did not get
                transfer.count_put(extra, 0)
                obs.count("lockstep.transfer_bytes", extra)
                a0, b0 = dsegs["xfer"]
                dsegs["xfer"] = (a0 + extra, b0)
                R = dsegs["dR0"]
                for k in range(i):
                    _c, R = run(*dsegs["segs"][k], dsegs["dP"], R)
                out = run(*dsegs["segs"][i], dsegs["dP"], R)
                obs.engine_fallback("packed-xfer", type(exc).__name__)
                return out

            if use_donate:
                # exactly one `donate` record; the donated carry may
                # already have been consumed by the failed dispatch:
                # recompute it from the never-donated seed through the
                # undonated jit
                obs.engine_fallback("donate", type(e).__name__)
                donate = False
                try:
                    R_cur = dsegs["dR0"]
                    for k in range(i):
                        _ck, R_cur = run(*dsegs["segs"][k],
                                         dsegs["dP"], R_cur)
                    ck, R_cur = run(a, b, dsegs["dP"], R_cur)
                except Exception as e2:                 # noqa: BLE001
                    # not donation after all: the packed wire itself
                    # fails on this backend — degrade it to dense
                    if not packed_wire:
                        raise
                    ck, R_cur = _dense_recover(e2)
            elif packed_wire:
                ck, R_cur = _dense_recover(e)
            else:
                raise
        ckpts.append(ck)
    return ckpts, R_cur


class BatchInflight:
    """A dispatched-but-unfetched lockstep walk: every device program
    is queued, no result has crossed the wire. Produced by
    :func:`dispatch_returns_batch`, consumed by
    :func:`collect_returns_batch` — the split lets a scheduler queue
    the NEXT group's walk (and pay its marshalling/compile host time)
    before fetching the previous group's verdicts, overlapping host
    work with device walks across bucket groups. ``device`` (when set)
    is the mesh device this group's lane block walks on. ``body``
    records the kernel body this group walked (``dense`` = the Pallas
    batch kernel, ``word`` = the vmapped word-packed scan); a word
    walk carries its queued device results in ``word_out``."""
    __slots__ = ("P", "geom", "host_args", "R_lens", "dsegs",
                 "ckpts", "final", "interpret", "device", "degraded",
                 "body", "word_out")

    def __init__(self, P, geom, host_args, R_lens, dsegs, ckpts,
                 final, interpret, device=None):
        self.P = P
        self.geom = geom
        self.host_args = host_args
        self.R_lens = R_lens
        self.dsegs = dsegs
        self.ckpts = ckpts
        self.final = final
        self.interpret = interpret
        self.device = device
        # set by collect_returns_batch when a lazy-fetch fallback
        # degraded this walk's collect to eager full-array fetches
        self.degraded = False
        self.body = "dense"
        self.word_out = None


class BatchPrepared:
    """Marshalled-but-undispatched lockstep operands for one group:
    the output of :func:`prepare_returns_batch` (pure host work — numpy
    interleaving plus geometry; safe to run on the streaming prep
    thread, no jax calls), consumed by :func:`dispatch_prepared` on the
    dispatching thread. The prepare/dispatch split is what lets the
    streaming pipeline pack group g+1 while group g walks on device.
    A mesh scheduler sets ``device`` before dispatching to pin this
    group's lane block to one chip (None = jax's default device).
    ``body`` (None = resolve at dispatch: autotune winner, force
    gate, else dense) selects the kernel body this group walks."""
    __slots__ = ("P", "geom", "host_args", "R_lens", "interpret",
                 "device", "body")

    def __init__(self, P, geom, host_args, R_lens, interpret,
                 device=None, body=None):
        self.P = P
        self.geom = geom
        self.host_args = host_args
        self.R_lens = R_lens
        self.interpret = interpret
        self.device = device
        self.body = body


def prepare_returns_batch(P: np.ndarray, ret_slots: List[np.ndarray],
                          slot_ops: List[np.ndarray], M: int, *,
                          interpret: Optional[bool] = None
                          ) -> BatchPrepared:
    """Host-only half of :func:`dispatch_returns_batch`: marshal H
    return streams into the lockstep layout without touching jax."""
    if interpret is None:
        interpret = _INTERPRET_DEFAULT
    geom, host_args, R_lens = pack_batch_operands(
        P, ret_slots, slot_ops, M, interpret=interpret)
    return BatchPrepared(P, geom, host_args, R_lens, interpret)


def _pipe_walk_on(device, host_args, geom, n_pass: int, interpret: bool,
                  dsegs: dict):
    """:func:`_pipe_walk_b` with every put/compile/dispatch committed to
    ``device`` (None = default device): the single-chip kernel is the
    per-shard body of the mesh lockstep lane — jax routes the jitted
    walk to wherever its operands are committed, so N shards queued on
    N devices walk concurrently."""
    if device is None:
        return _pipe_walk_b(host_args, geom, n_pass, interpret, dsegs)
    import jax
    with jax.default_device(device):
        return _pipe_walk_b(host_args, geom, n_pass, interpret, dsegs,
                            device=device)


def _lockstep_body(geom) -> str:
    """Kernel-body selection for one lockstep dispatch group: the
    persisted autotune table first (a ``lockstep`` winner recorded by
    ``tools/batch_width.py --bodies``), then the
    ``JEPSEN_TPU_WORD_POSTHOC=1`` force, else the Pallas batch kernel
    (``dense``). ``word`` only where the word body admits."""
    from jepsen_tpu.checkers import autotune, reach_word

    _B, W, M, S, H, _O1, _R_pad = geom
    if not (reach_word.enabled() and reach_word.admits(S, W, M)):
        return "dense"
    if os.environ.get("JEPSEN_TPU_WORD_POSTHOC"):
        return "word"
    w = autotune.winner("lockstep", autotune.lockstep_key(S, W, M, H))
    return w if w in ("word", "dense") else "dense"


def _dispatch_words(prep: BatchPrepared) -> BatchInflight:
    """Queue the word-packed lockstep walk (the ``reach_word`` body):
    one shared transition table derived from P, per-lane word-vector
    frontiers, the whole group as ONE vmapped scan — nothing fetched
    (the queued device results ride ``word_out`` into the collect)."""
    import jax
    import jax.numpy as jnp

    from jepsen_tpu.checkers import reach_word

    _B, W, M, S, H, _O1, R_pad = prep.geom
    ops_flat, rs_rh, P, _R0 = prep.host_args
    Tpad = reach_word.pad_table(reach_word.table_from_P(P))
    NW = reach_word.n_words(M)
    R0w = np.zeros((H, S, NW), np.uint32)
    R0w[:, 0, 0] = 1                     # mask 0, state 0 per lane
    rs_hr = np.ascontiguousarray(rs_rh.T.astype(np.int32))
    so_hrw = np.ascontiguousarray(np.swapaxes(
        np.asarray(ops_flat).reshape(R_pad, H, W), 0, 1)
        .astype(np.int32))
    transfer.count_put(
        int(Tpad.nbytes + R0w.nbytes + rs_hr.nbytes + so_hrw.nbytes),
        int(Tpad.nbytes + H * S * M * 4
            + (rs_hr.size + so_hrw.size) * 4))

    def _go():
        return reach_word._jitted_walk_words_batch()(
            jnp.asarray(Tpad), jnp.asarray(R0w), jnp.asarray(rs_hr),
            jnp.asarray(so_hrw))

    if prep.device is not None:
        with jax.default_device(prep.device):
            out = _go()
    else:
        out = _go()
    obs.count("lockstep.word_groups")
    fl = BatchInflight(prep.P, prep.geom, prep.host_args, prep.R_lens,
                       {}, [], None, prep.interpret,
                       device=prep.device)
    fl.body = "word"
    fl.word_out = out
    return fl


def dispatch_prepared(prep: BatchPrepared) -> BatchInflight:
    """Queue a prepared group's walk (device puts + compiles +
    dispatches — all jax work) without fetching anything. Pair with
    :func:`collect_returns_batch`. The kernel body is resolved here
    (:func:`_lockstep_body` unless the caller pinned ``prep.body``);
    a word-body dispatch failure records exactly one ``word-walk``
    obs fallback and the group walks the dense Pallas kernel."""
    body = prep.body if prep.body in ("word", "dense") \
        else _lockstep_body(prep.geom)
    if body == "word":
        try:
            return _dispatch_words(prep)
        except Exception as e:                          # noqa: BLE001
            obs.engine_fallback("word-walk", type(e).__name__,
                                lanes=prep.geom[4])
    W = prep.geom[1]
    n_fast = min(W, _FAST_PASSES)
    dsegs: dict = {}
    ckpts, final = _pipe_walk_on(prep.device, prep.host_args, prep.geom,
                                 n_fast, prep.interpret, dsegs)
    return BatchInflight(prep.P, prep.geom, prep.host_args, prep.R_lens,
                         dsegs, ckpts, final, prep.interpret,
                         device=prep.device)


def dispatch_returns_batch(P: np.ndarray, ret_slots: List[np.ndarray],
                           slot_ops: List[np.ndarray], M: int, *,
                           interpret: Optional[bool] = None
                           ) -> BatchInflight:
    """Marshal + queue the lockstep walk of H return streams without
    fetching anything. Pair with :func:`collect_returns_batch`."""
    return dispatch_prepared(prepare_returns_batch(
        P, ret_slots, slot_ops, M, interpret=interpret))


@functools.cache
def _alive_lanes_call(H: int, S: int):
    """On-device verdict reduction for the lockstep walk: H alive bits
    cross the wire instead of the full [M, H*S] f32 config set — the
    fixed few-byte summary the valid-history path fetches; the full
    arrays (final set, block checkpoints) cross only when a lane is
    invalid and witness localization needs them."""
    import jax
    import jax.numpy as jnp
    return jax.jit(
        lambda f: jnp.max(f.reshape(f.shape[0], H, S), axis=(0, 2))
        > 0.5)


def collect_returns_batch(fl: BatchInflight) -> np.ndarray:
    """Fetch an in-flight lockstep walk's verdicts: ``dead[H]`` — per
    history, the first return index at which its config set emptied,
    or -1 if linearizable (exact rescue + localization as
    :func:`walk_returns_batch`). With lazy fetch (the default) the
    valid path fetches only H on-device-reduced alive bits; eager
    (``JEPSEN_TPU_NO_LAZY_FETCH=1``) fetches the full final set as in
    round 5 — verdicts are bit-identical either way."""
    P, interpret = fl.P, fl.interpret
    geom, host_args, R_lens, dsegs = (fl.geom, fl.host_args, fl.R_lens,
                                      fl.dsegs)
    B, W, M, S, H, O1, R_pad = geom
    if fl.body == "word":
        try:
            _R, any_dead, first = fl.word_out
            any_np = np.asarray(any_dead)
            first_np = np.asarray(first)
            dead = np.full(H, -1, np.int64)
            for h in np.nonzero(any_np)[0]:
                # exact per-step death (identity pads cannot kill a
                # live set), clamped to the lane's real length
                dead[int(h)] = min(int(first_np[int(h)]),
                                   max(int(R_lens[int(h)]) - 1, 0))
            return dead
        except Exception as e:                          # noqa: BLE001
            # the queued word walk died at fetch (jax dispatch is
            # async — errors surface at first consumption): one
            # record, then the group re-walks the dense body from the
            # retained host operands
            obs.engine_fallback("word-walk", type(e).__name__,
                                lanes=H, collect=True)
            redo = BatchPrepared(P, geom, host_args, R_lens,
                                 interpret, device=fl.device,
                                 body="dense")
            return collect_returns_batch(dispatch_prepared(redo))
    n_fast = min(W, _FAST_PASSES)
    ckpts, final = fl.ckpts, fl.final
    HS = H * S
    lazy = transfer.lazy_fetch_enabled()

    def _alive_of(fin) -> np.ndarray:
        nonlocal lazy

        def _eager(fn):
            obs.count("fetch.eager")
            return np.array([fn[:, h * S:(h + 1) * S].any()
                             for h in range(H)])

        if lazy:
            try:
                a = np.asarray(_alive_lanes_call(H, S)(fin))
                obs.count("fetch.lazy")
                return a
            except Exception as e:                      # noqa: BLE001
                # fetch the final set FIRST: jax dispatch is async, so
                # a walk error also surfaces at first consumption — a
                # poisoned result propagates here and is NOT recorded
                # as a lazy-fetch failure. Otherwise exactly one
                # fallback; this collect degrades to eager
                fn = np.asarray(fin)
                obs.engine_fallback("lazy-fetch", type(e).__name__)
                lazy = False
                # the schedulers' diag reports the protocol the
                # verdicts ACTUALLY crossed on, not the env gate
                fl.degraded = True
                return _eager(fn)
        return _eager(np.asarray(fin))

    alive = _alive_of(final)                     # the ONE round-trip
    if not alive.all() and n_fast < W:
        # capped-ladder deaths may be false: decide with the exact
        # W-pass walk (reuses the uploaded device segments)
        obs.count("lockstep.exact_rescue")
        ckpts, final = _pipe_walk_on(fl.device, host_args, geom, W,
                                     interpret, dsegs)
        alive = _alive_of(final)
    dead = np.full(H, -1, np.int64)
    if alive.all():
        return dead
    # localization: fetch the block checkpoints once, then re-walk the
    # death block of each dead history in ITS OWN geometry
    ckpt_np = np.concatenate([np.asarray(c) for c in ckpts])
    n_blocks = R_pad // B
    ckpt_np = ckpt_np[:n_blocks]                 # [blocks, M, HS]
    ops_rhw = np.asarray(host_args[0]).reshape(R_pad, H, W)
    rs_rh = host_args[1]
    for h in np.nonzero(~alive)[0]:
        col = ckpt_np[:, :, h * S:(h + 1) * S]   # [blocks, M, S]
        occ = col.reshape(n_blocks, -1).any(axis=1)
        first_empty = int(np.argmin(occ)) if not occ.all() else n_blocks
        blk = max(0, first_empty - 1)
        dead[h] = _refine_dead(
            P, W, M,
            np.ascontiguousarray(rs_rh[:, h].astype(np.int32)),
            np.ascontiguousarray(ops_rhw[:, h, :]),
            col[blk].T > 0.5, blk * B,
            min(B, max(1, R_lens[h] - blk * B)))
    return dead


def walk_returns_batch(P: np.ndarray, ret_slots: List[np.ndarray],
                       slot_ops: List[np.ndarray], M: int, *,
                       interpret: bool = False) -> np.ndarray:
    """Walk H independent return streams in lockstep; returns
    ``dead[H]`` — per history, the first return index at which its
    config set emptied, or -1 if linearizable. Exact: capped fast
    ladder first (sound for "valid"), per-history exact rescue +
    block-checkpoint refinement on death, identical verdicts and
    indices to H single-history walks. One-shot form of the
    :func:`dispatch_returns_batch` / :func:`collect_returns_batch`
    pair."""
    return collect_returns_batch(dispatch_returns_batch(
        P, ret_slots, slot_ops, M, interpret=interpret))


def walk_returns_batch_sharded(P: np.ndarray,
                               ret_slots: List[np.ndarray],
                               slot_ops: List[np.ndarray], M: int,
                               devices: Sequence, *,
                               interpret: Optional[bool] = None
                               ) -> np.ndarray:
    """Walk H return streams in lockstep with the LANE axis sharded
    over ``devices``: the lane blocks split per device
    (:func:`shard_groups_for_mesh` — the count padded to even splits
    by replicating a lane), each block's walk queued on its own chip
    with the single-chip kernel as the per-shard body, and ALL shards
    dispatched before any verdict is fetched — so N devices walk
    concurrently. Verdicts are bit-identical to
    :func:`walk_returns_batch`: every lane walks exactly the stream it
    would walk single-chip, just on its own device."""
    devs = list(devices)
    H = len(ret_slots)
    groups, pad = shard_groups_for_mesh([list(range(H))], len(devs))
    inflight = []
    for k, g in enumerate(groups):
        prep = prepare_returns_batch(
            P, [ret_slots[h] for h in g], [slot_ops[h] for h in g], M,
            interpret=interpret)
        prep.device = devs[k % len(devs)]
        inflight.append((g, dispatch_prepared(prep)))
    dead = np.full(H, -1, np.int64)
    for g, fl in inflight:
        dead[np.asarray(g, np.int64)] = collect_returns_batch(fl)
    if pad:
        # counted after the collect loop: once per COMPLETED walk, the
        # same contract as the schedulers' _lockstep_accounting
        obs.count("lockstep.mesh.pad_lanes", pad)
    return dead
