"""Exhaustive linearizability check for tiny histories.

A deliberately independent implementation used only for differential testing
of the real checkers (SURVEY.md §4: replaces knossos's recorded-fixture
cross-checks at the smallest scale): enumerate every permutation of every
admissible subset of operations (all ``ok`` ops, any subset of crashed ops),
filter by the real-time order (if ``ret(x) < inv(y)`` then x precedes y),
and replay the model. Exponential — refuse histories beyond ``max_n`` ops.
"""
from __future__ import annotations

from itertools import combinations, permutations
from typing import Any, Dict, Sequence

from jepsen_tpu import history as h
from jepsen_tpu.models import Model, is_inconsistent
from jepsen_tpu.op import Op


def check(model: Model, history: Sequence[Op], *, max_n: int = 9
          ) -> Dict[str, Any]:
    entries = h.analysis_entries(history)
    n = len(entries)
    if n > max_n:
        raise ValueError(f"brute checker limited to {max_n} ops, got {n}")
    ok_entries = [e for e in entries if not e.crashed]
    info_entries = [e for e in entries if e.crashed]
    tried = 0
    for k in range(len(info_entries) + 1):
        for extra in combinations(info_entries, k):
            chosen = ok_entries + list(extra)
            for perm in permutations(chosen):
                tried += 1
                if _real_time_ok(perm) and _model_ok(model, perm):
                    return {"valid": True, "perms-tried": tried}
    return {"valid": False, "perms-tried": tried}


def _real_time_ok(perm) -> bool:
    for i in range(len(perm)):
        for j in range(i + 1, len(perm)):
            # perm[i] precedes perm[j]; illegal if perm[j] returned before
            # perm[i] was invoked.
            if perm[j].ret_ev < perm[i].inv_ev:
                return False
    return True


def _model_ok(model: Model, perm) -> bool:
    s = model
    for e in perm:
        s = s.step(e.op)
        if is_inconsistent(s):
            return False
    return True
