"""Dense product-space walk with the crashed-op quotient — the
frontier engine's fast path for crash-seasoned histories.

Upstream knossos explores crashed (``info``) ops exactly, paying the
``2^k`` "info ops are expensive" blowup; this framework's sparse
frontier (:mod:`.frontier`) quotients interchangeable crashed ops to
per-group fired COUNTS but still pays sort-based dedup per return. This
engine takes the quotient to its logical conclusion: since two pending
crashed ops with the same op id are interchangeable (neither returns;
firing either steps the model identically) and a crashed op never needs
a live slot (it never returns, so no projection ever targets it), the
reachable configuration space is exactly the PRODUCT

    state × 2^L × Π_g (k_g + 1)

where ``L`` counts only concurrently-pending RETURNING ops (small — the
client concurrency) and ``k_g`` is the size of crashed group ``g`` (one
group per distinct op id). For the crash-heavy benchmark row this is a
few thousand cells — a dense boolean tensor the device walks at
microseconds per return, where the sparse frontier pays ~0.3-0.7 ms of
per-return sort/expand work and knossos pays ``2^k``.

Semantics per return event (fire passes run to a monotone fixpoint):

- live fires: exactly the dense engine's mask-axis update
  (:mod:`.reach`), batched over the flat count axis;
- group fires: configs with ``count_g < cap_g(r)`` step the model
  through the group's op and increment the count — a precomputed
  gather along the mixed-radix flat count axis. ``cap_g(r)`` is the
  number of group members invoked before return ``r`` (host-known): a
  crashed op may linearize anywhere after its invocation, or never;
- projection on the returning live slot, as the dense engine.

Exactness: the quotient map (forget WHICH group members fired, keep the
count) is a bisimulation on the dense engine's config graph — fires and
projections commute with it — so emptiness at each return is preserved
exactly. No fingerprint hashing anywhere.

Two walks share the quotient (round-4 widening):

- **dense** — the full ``2^L`` mask axis in one tensor; gated by
  ``L <= 16`` and ``S·2^L·Π(k_g+1) <= max_dense``;
- **sparse-live** — rows keyed by live mask (uint32, ``L <= 31``),
  each carrying a dense ``[S, C]`` count payload, so group fires never
  create rows and the crashed-count product stays folded. Capacity
  escalates through ``_SQ_CAPS``; the envelope is the reachable-MASK
  count (bursts of ~14 distinct concurrent live ops fit; sustained
  20+-wide concurrency reaches ~2^20 masks and overflows honestly —
  collapsing same-op-id live bursts would need the frontier's rank
  canonicalization, a future lever).

``G <= _MAX_GROUPS`` (16) bounds the unrolled group fires; histories
beyond every budget stay on the sparse frontier rows
(:class:`QuotientOverflow`).
"""
from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from jepsen_tpu import history as h
from jepsen_tpu.checkers import events as ev
from jepsen_tpu.models.memo import Memo

_MAX_GROUPS = 16
# live-slot caps: the DENSE product walk holds the full 2^L mask axis
# in one tensor (budget-gated), while the SPARSE-LIVE walk below keys
# rows by mask (uint32) and so admits up to 31 un-crashed concurrent
# ops — the round-4 widening that moves the former ~1 s
# sparse-frontier-fallback family onto a quotient path
_MAX_LIVE_DENSE = 16
_MAX_LIVE_SPARSE = 31
# returns per device dispatch: bounded programs, shape-stable compiles
# (the tail segment bucket-pads), and host abort points between
_SEG = 32768
# sparse-live row capacities (distinct live masks per frontier;
# escalates through the ladder before overflowing to the sparse
# frontier engine). The reachable-mask count is the real boundary:
# c_r concurrently-pending DISTINCT live ops reach up to 2^c_r masks,
# so bursts up to ~14 distinct concurrent ops fit the top rung while
# sustained 20+-wide concurrency overflows honestly (collapsing
# same-op-id live bursts needs rank canonicalization — a future
# lever; crashed bursts are already count-quotiented).
_SQ_CAPS = (256, 1024, 4096, 16384)
# absolute resource budgets for the sparse-live walk (independent of
# the caller's dense-product budget, which gates a DIFFERENT tensor):
# payload bools per frontier and entries of the per-pass candidate
# einsum intermediate [F, W, S, C] (f32)
_SQ_PAYLOAD_MAX = 1 << 25
_SQ_EINSUM_MAX = 1 << 26


class QuotientOverflow(RuntimeError):
    """The product space exceeds the budget; callers fall back to the
    sparse frontier rows."""


class Aborted(RuntimeError):
    """The caller's ``should_abort`` fired between segments."""


# -- host geometry -----------------------------------------------------------

def _mixed_radix(sizes: List[int]) -> Tuple[np.ndarray, np.ndarray]:
    """For count-axis sizes ``k_g + 1``: per-group digit table
    ``digit[G, C]`` and shift-source table ``src[G, C]`` (the flat index
    whose count_g is one lower, -1 where digit_g == 0)."""
    C = int(np.prod(sizes)) if sizes else 1
    G = len(sizes)
    digit = np.zeros((max(G, 1), C), np.int32)
    src = np.full((max(G, 1), C), -1, np.int32)
    flat = np.arange(C)
    stride = 1
    for g in range(G):
        digit[g] = (flat // stride) % sizes[g]
        src[g] = np.where(digit[g] > 0, flat - stride, -1)
        stride *= sizes[g]
    return digit, src


def _prep_quotient(memo: Memo, stream: ev.EventStream,
                   packed: h.PackedHistory,
                   max_live: int = _MAX_LIVE_DENSE):
    """Split the event stream into live events (slotted over returning
    ops only) and crashed groups, and build the walk's operands."""
    crashed = np.asarray(packed.crashed, bool)
    E = stream.n_events
    kind = stream.kind[:E]
    entry = stream.entry[:E]
    opid = stream.opid[:E]
    is_crash_ev = (kind == ev.KIND_INVOKE) & crashed[entry]
    # live slot assignment over the filtered (non-crashed) events
    from jepsen_tpu.checkers import preproc_native
    live_pos = np.nonzero(~is_crash_ev)[0].astype(np.int32)
    lkind = np.ascontiguousarray(kind[live_pos])
    lentry = np.ascontiguousarray(entry[live_pos])
    native = preproc_native.assign_slots(lkind, lentry, packed.n,
                                         max_live)
    if native is None:
        raise QuotientOverflow("native preproc unavailable")
    lslot, L = native
    if L < 0:
        raise QuotientOverflow(f"live concurrency > {max_live}")
    L = max(L, 1)
    lopid = np.ascontiguousarray(opid[live_pos])
    rv = preproc_native.returns_view(lkind, lslot, lopid, lentry, L,
                                     len(lkind))
    if rv is None:
        raise QuotientOverflow("native preproc unavailable")
    ret_slot, slot_ops, ret_event_l, ret_entry, R = rv
    # ret_event_l indexes the FILTERED stream; map back to stream events
    ret_event = live_pos[ret_event_l]

    def epochs() -> Tuple[np.ndarray, np.ndarray]:
        # lazy: only the sparse-live walk consumes the epoch tables,
        # and building them eagerly cost O(E) host time plus O(R*L*L)
        # temporaries on every dense-path check
        return _live_epochs(lkind, lslot, lentry, lopid, packed, L, R)
    # crashed groups by op id (noop-crashed were already dropped by
    # events.build before this stream was built)
    crash_pos = np.nonzero(is_crash_ev)[0]
    crash_ops = opid[crash_pos]
    gids, ginv = np.unique(crash_ops, return_inverse=True)
    G = len(gids)
    if G > _MAX_GROUPS:
        raise QuotientOverflow(f"{G} crashed groups > {_MAX_GROUPS}")
    sizes = [int((ginv == g).sum()) + 1 for g in range(G)]
    C = int(np.prod(sizes)) if sizes else 1
    # cap_g(r): group members invoked before return r's event
    caps = np.zeros((max(R, 1), max(G, 1)), np.int32)
    for g in range(G):
        inv_ranks = np.sort(crash_pos[ginv == g])
        caps[:R, g] = np.searchsorted(inv_ranks, ret_event[:R])
    digit, src = _mixed_radix(sizes)
    return (L, ret_slot, slot_ops, ret_event, ret_entry, R,
            gids.astype(np.int32), sizes, C, caps, digit, src,
            epochs)


def _live_epochs(lkind: np.ndarray, lslot: np.ndarray,
                 lentry: np.ndarray, lopid: np.ndarray,
                 packed: h.PackedHistory, L: int, R: int
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """Live EPOCH groups for the sparse walk's rank canonicalization
    (round-5): two live pending ops are exactly interchangeable when
    they share an op id AND were invoked within the same inter-return
    window (no closure point falls between their invokes, so every
    fire opportunity postdates both invokes and any fired-subset
    designation among them is legal — a bisimulation; ops straddling a
    return are NOT collapsed, which keeps this sound where a naive
    same-op-id quotient would not be). Returns per-return tables over
    live slots: ``ep_gid[R, L]`` int8 — the min-slot representative of
    the slot's epoch group (equal = same group, -1 empty slot; small
    ints on purpose — jax without x64 silently truncates wider
    codes) — and ``ep_rank[R, L]`` int8 — the slot's rank within its
    group by RETURN order, so the returning slot is always rank 0 and
    canonical masks survive its projection."""
    E = len(lkind)
    occ_entry = np.full(L, -1, np.int64)
    inv_code = np.zeros(L, np.int64)        # epoch code of occupant
    n_rets_seen = 0
    code = np.full((max(R, 1), L), -1, np.int64)
    occ_ret = np.full((max(R, 1), L), 0, np.int64)
    r = 0
    ret_ev_arr = np.asarray(packed.ret_ev, np.int64)
    for e in range(E):
        s = lslot[e]
        if lkind[e] == ev.KIND_INVOKE:
            occ_entry[s] = lentry[e]
            inv_code[s] = (np.int64(lopid[e]) << np.int64(32)
                           | np.int64(n_rets_seen))
        else:                               # return
            n_rets_seen += 1
            if r < R:
                live = occ_entry >= 0
                code[r, live] = inv_code[live]
                occ_ret[r, live] = ret_ev_arr[occ_entry[live]]
                r += 1
            occ_entry[s] = -1
    # rank within equal-code groups by (occupant return event, slot),
    # and per-row min-slot group representatives (int8 — wide codes
    # would be silently truncated by jax without x64). Chunked over R:
    # the [chunk, L, L] pairwise broadcasts stay a few MB where the
    # full [R, L, L] form allocated gigabytes on long histories.
    Rr = max(R, 1)
    rank = np.zeros((Rr, L), np.int8)
    gid = np.full((Rr, L), -1, np.int8)
    slots = np.arange(L)
    chunk = max(1, (1 << 22) // max(L * L, 1))
    for lo in range(0, Rr, chunk):
        hi = min(lo + chunk, Rr)
        c = code[lo:hi]
        o = occ_ret[lo:hi]
        same = (c[:, :, None] == c[:, None, :]) & (c[:, :, None] >= 0)
        earlier = (o[:, :, None] > o[:, None, :]) | (
            (o[:, :, None] == o[:, None, :])
            & (slots[None, :, None] > slots[None, None, :]))
        rank[lo:hi] = (same & earlier).sum(axis=2).astype(np.int8)
        gid[lo:hi] = np.where(c >= 0,
                              np.argmax(same, axis=2).astype(np.int8),
                              np.int8(-1))
    return gid, rank


# -- device walk -------------------------------------------------------------

def _q_fire_once(P, xor_cols, bitmask, digit, src, R, Glive, cap_row,
                 gop_ids):
    """One monotone fire pass on ``R`` bool[S, M, C]: every live slot
    plus every crashed group."""
    import jax.numpy as jnp

    n_groups = gop_ids.shape[0]
    # live fires: gather bit-clear halves, step, OR into bit-set
    Rx = R[:, xor_cols]                         # [S, W, M, C]
    contrib = jnp.einsum("sjmc,jst->tjmc",
                         Rx.astype(jnp.float32), Glive)
    add = ((contrib > 0.5) & bitmask[None, :, :, None]).any(axis=1)
    R = R | add
    # group fires: step the model, +1 on the group's count digit
    for g in range(n_groups):
        fired = jnp.einsum("smc,st->tmc",
                           R.astype(jnp.float32), P[gop_ids[g]])
        fired = fired > 0.5
        # shift along the flat count axis (digit_g += 1), gated on the
        # result count staying within the invoked availability cap
        shifted = jnp.where((src[g] >= 0)[None, None, :],
                            fired[:, :, jnp.clip(src[g], 0)], False)
        gate = (digit[g] <= cap_row[g])[None, None, :]
        R = R | (shifted & gate)
    return R


def _q_step(P, xor_cols, bitmask, digit, src, R, j, ops_row, cap_row,
            gop_ids):
    """One return event: fire to the monotone fixpoint, then project
    on live slot ``j`` (``j = -1`` is the identity pad)."""
    import jax.numpy as jnp
    from jax import lax

    W, M = xor_cols.shape
    n_ops_pad = P.shape[0] - 1
    Glive = P[jnp.where(ops_row < 0, n_ops_pad, ops_row)]    # [W, S, S]

    def once(Rv):
        return _q_fire_once(P, xor_cols, bitmask, digit, src, Rv,
                            Glive, cap_row, gop_ids)

    def cond(c):
        prev, cur = c
        return jnp.any(prev != cur)

    def body(c):
        _, cur = c
        return cur, once(cur)

    _, R = lax.while_loop(cond, body, (R, once(R)))
    jj = jnp.maximum(j, 0)
    idx = jnp.arange(M)
    bit = jnp.int32(1) << jj
    srcm = idx | bit
    clear = (idx & bit) == 0
    Rp = jnp.where(clear[None, :, None], R[:, srcm], False)
    return jnp.where(j >= 0, Rp, R)


def _q_walk(P, xor_cols, bitmask, digit, src, gop_ids, ret_slot,
            slot_ops, caps, R0):
    """Drive all return events; returns ``(ptr, R, alive)`` — dead at
    return ``ptr - 1`` when ``alive`` is false."""
    import jax.numpy as jnp
    from jax import lax

    Rn = ret_slot.shape[0]

    def cond(c):
        i, R, alive = c
        return (i < Rn) & alive

    def body(c):
        i, R, _ = c
        R = _q_step(P, xor_cols, bitmask, digit, src, R, ret_slot[i],
                    slot_ops[i], caps[i], gop_ids)
        return i + 1, R, R.any()

    return lax.while_loop(cond, body, (jnp.int32(0), R0, R0.any()))


@functools.cache
def _jitted_q_walk():
    import jax
    return jax.jit(_q_walk)


# -- entry -------------------------------------------------------------------

def _run_segments(P_np, xor_cols, bitmask, digit, src, gids, ret_slot,
                  slot_ops, caps, R0, R_n: int, should_abort):
    """Drive the walk in ``_SEG``-return bucket-padded segments (shape
    cache stays small; the set carries across dispatches); raises
    :class:`Aborted` between segments when the hook fires. Returns the
    device ``(global_ptr, R, alive)``."""
    import jax
    import jax.numpy as jnp

    from jepsen_tpu.checkers import reach

    walk = _jitted_q_walk()
    dP = jax.device_put(np.asarray(P_np))
    dxc, dbm = jax.device_put(xor_cols), jax.device_put(bitmask)
    ddig, dsrc = jax.device_put(digit), jax.device_put(src)
    dg = jax.device_put(np.ascontiguousarray(gids, np.int32))
    R_cur = jnp.asarray(R0)
    base = 0
    while base < R_n:
        if should_abort is not None and should_abort():
            raise Aborted()
        n = min(_SEG, R_n - base)
        L_pad = max(64, reach._bucket(n, 8))
        seg_slot = np.full(L_pad, -1, np.int32)
        seg_slot[:n] = ret_slot[base:base + n]
        W = slot_ops.shape[1]
        seg_ops = np.full((L_pad, W), -1, np.int32)
        seg_ops[:n] = slot_ops[base:base + n]
        G = caps.shape[1]
        seg_caps = np.zeros((L_pad, G), np.int32)
        seg_caps[:n] = caps[base:base + n]
        # identity-padded tail rows (slot -1, ops -1) still execute
        # crashed-group fires gated by the LAST REAL return's caps.
        # This is sound and load-bearing: caps are non-decreasing and
        # group fires are monotone, so anything a pad-row fire adds is
        # a subset of the next real return's fixpoint closure — pad
        # fires can never flip emptiness nor resurrect an empty set.
        seg_caps[n:] = caps[base + n - 1]
        ptr, R_cur, alive = walk(
            dP, dxc, dbm, ddig, dsrc, dg, jnp.asarray(seg_slot),
            jnp.asarray(seg_ops), jnp.asarray(seg_caps), R_cur)
        if not bool(alive):
            return base + int(ptr), R_cur, False
        base += n
    return R_n, R_cur, True


# -- sparse-live walk: rows keyed by live mask, dense count payload ----------
#
# The dense product walk above holds the full 2^L mask axis in one
# tensor, capping live concurrency at _MAX_LIVE_DENSE. For higher
# concurrency the REACHABLE masks are few even when 2^L is astronomical,
# so this walk keeps a sparse row per distinct live mask (uint32 key,
# L <= 31) and folds the whole crashed-count product into a dense
# [S, C] payload per row. Group fires then never create rows (counts
# live inside the payload; the mask is untouched) — only live fires
# spawn candidates — which is exactly why this beats the sparse
# frontier on crash-heavy shapes: the frontier's row count multiplies
# by count combinations, while here F counts distinct masks only.
# Exactness: same bisimulation argument as the dense walk; rows merge
# by OR-ing payloads (set union), no hashing. Capacity overflow
# escalates through _SQ_CAPS and finally falls back to the sparse
# frontier engine (QuotientOverflow) — an overflow run's results are
# discarded entirely (clipped dedup would over-approximate).

_SQ_SENT = np.uint32(0xFFFFFFFF)


def _sq_fire_groups(payload, P, gop_ids, digit, src, cap_row):
    """Group fires on the [F, S, C] payloads (same math as
    :func:`_q_fire_once`'s group part with the mask axis replaced by
    the sparse row axis): step the model through the group op and
    advance the count digit, gated on the invoked-availability cap."""
    import jax.numpy as jnp

    for g in range(gop_ids.shape[0]):
        fired = jnp.einsum("fsc,st->ftc",
                           payload.astype(jnp.float32),
                           P[gop_ids[g]]) > 0.5
        shifted = jnp.where((src[g] >= 0)[None, None, :],
                            fired[:, :, jnp.clip(src[g], 0)], False)
        gate = (digit[g] <= cap_row[g])[None, None, :]
        payload = payload | (shifted & gate)
    return payload


def _sq_dedup(masks, payload, Fcap: int):
    """Sort rows by mask, OR payloads of equal masks, compact to the
    first ``Fcap`` slots. Returns ``(masks, payload, n_unique)`` —
    ``n_unique > Fcap`` means rows were clipped (caller must discard
    the walk and escalate; the clipped state over-approximates)."""
    import jax.numpy as jnp

    order = jnp.argsort(masks)
    masks_s = masks[order]
    payload_s = payload[order]
    valid = masks_s != _SQ_SENT
    newseg = jnp.concatenate(
        [valid[:1], (masks_s[1:] != masks_s[:-1]) & valid[1:]])
    seg = jnp.cumsum(newseg.astype(jnp.int32)) - 1
    segc = jnp.clip(seg, 0, Fcap - 1)
    m_out = jnp.full((Fcap,), _SQ_SENT, jnp.uint32).at[segc].min(
        jnp.where(valid, masks_s, _SQ_SENT))
    p_out = jnp.zeros((Fcap,) + payload.shape[1:], jnp.bool_)
    p_out = p_out.at[segc].max(payload_s & valid[:, None, None])
    return m_out, p_out, jnp.sum(newseg)


def _sq_canon(masks, gid_row, rank_row, W: int):
    """Live epoch-rank canonicalization (round-5): repack each epoch
    group's fired bits into its earliest-RETURNING members. Two live
    pending ops sharing an op id and an invocation window (equal
    ``code_row`` entries) are exactly interchangeable — every fire
    opportunity postdates both invokes — so masks differing only in
    WHICH epoch members fired collapse to one canonical row (the
    2^burst blowup of same-op concurrent bursts becomes burst+1 rows),
    and the returning slot is rank 0 of its group, so projection sees
    canonical masks unchanged. Sentinel rows pass through."""
    import jax.numpy as jnp

    valid = masks != _SQ_SENT
    bits = ((masks[:, None] >> jnp.arange(W, dtype=jnp.uint32)[None, :])
            & jnp.uint32(1)).astype(jnp.int32)           # [F, W]
    grouped = gid_row >= 0
    same = ((gid_row[:, None] == gid_row[None, :])
            & grouped[:, None] & grouped[None, :])       # [W, W]
    cnt = bits @ same.astype(jnp.int32)                  # [F, W]
    newbit = jnp.where(grouped[None, :],
                       (rank_row[None, :] < cnt).astype(jnp.int32),
                       bits)
    m2 = jnp.sum(newbit.astype(jnp.uint32)
                 << jnp.arange(W, dtype=jnp.uint32)[None, :], axis=1)
    return jnp.where(valid, m2, masks)


def _sq_step(P, digit, src, gop_ids, masks, payload, j, ops_row,
             cap_row, code_row, rank_row, Fcap: int, W: int):
    """One return event on the sparse rows: fire to the monotone
    fixpoint (groups in place, live fires spawning candidate rows,
    epoch-rank canonicalization folding symmetric rows), then project
    on live slot ``j``. Returns ``(masks, payload, over)``."""
    import jax.numpy as jnp
    from jax import lax

    n_ops_pad = P.shape[0] - 1
    Gl = P[jnp.where(ops_row < 0, n_ops_pad, ops_row)]     # [W, S, S]
    bits = (jnp.uint32(1) << jnp.arange(W, dtype=jnp.uint32))

    def one(c):
        masks, payload, over = c
        payload = _sq_fire_groups(payload, P, gop_ids, digit, src,
                                  cap_row)
        valid_row = (masks != _SQ_SENT)[:, None]
        bitclear = (masks[:, None] & bits[None, :]) == 0
        cand_ok = valid_row & bitclear & (ops_row >= 0)[None, :]
        stepped = jnp.einsum("fsc,wst->fwtc",
                             payload.astype(jnp.float32), Gl) > 0.5
        cand_masks = jnp.where(cand_ok, masks[:, None] | bits[None, :],
                               _SQ_SENT)
        S, C = payload.shape[1], payload.shape[2]
        cand_payload = (stepped.reshape(-1, S, C)
                        & cand_ok.reshape(-1)[:, None, None])
        all_masks = jnp.concatenate([masks, cand_masks.reshape(-1)])
        all_masks = _sq_canon(all_masks, code_row, rank_row, W)
        all_payload = jnp.concatenate([payload, cand_payload])
        masks, payload, n = _sq_dedup(all_masks, all_payload, Fcap)
        return masks, payload, over | (n > Fcap)

    def cond(c):
        prev_bits, cur = c
        _m, p, over = cur
        return (jnp.sum(p) != prev_bits) & ~over

    def body(c):
        _prev, cur = c
        return jnp.sum(cur[1]), one(cur)

    state = (masks, payload, jnp.bool_(False))
    _, (masks, payload, over) = lax.while_loop(
        cond, body, (jnp.int32(-1), state))
    # projection on the returning live slot (j = -1: identity pad)
    bit = jnp.uint32(1) << jnp.uint32(jnp.maximum(j, 0))
    has = (masks != _SQ_SENT) & ((masks & bit) != 0)
    masks_p = jnp.where(has, masks & ~bit, _SQ_SENT)
    payload_p = payload & has[:, None, None]
    masks_p, payload_p, n = _sq_dedup(masks_p, payload_p, Fcap)
    over = over | (n > Fcap)
    keep = j >= 0
    masks = jnp.where(keep, masks_p, masks)
    payload = jnp.where(keep, payload_p, payload)
    return masks, payload, over


def _sq_walk(P, digit, src, gop_ids, ret_slot, slot_ops, caps,
             ep_code, ep_rank, masks0, payload0, Fcap: int, W: int):
    """Drive all return events; returns
    ``(ptr, masks, payload, alive, over)``."""
    import jax.numpy as jnp
    from jax import lax

    Rn = ret_slot.shape[0]

    def cond(c):
        i, _m, _p, alive, over = c
        return (i < Rn) & alive & ~over

    def body(c):
        i, masks, payload, _a, over = c
        masks, payload, o2 = _sq_step(
            P, digit, src, gop_ids, masks, payload, ret_slot[i],
            slot_ops[i], caps[i], ep_code[i], ep_rank[i], Fcap, W)
        return i + 1, masks, payload, payload.any(), over | o2

    return lax.while_loop(
        cond, body,
        (jnp.int32(0), masks0, payload0, payload0.any(),
         jnp.bool_(False)))


@functools.cache
def _jitted_sq_walk(Fcap: int, W: int):
    import functools as _ft

    import jax
    return jax.jit(_ft.partial(_sq_walk, Fcap=Fcap, W=W))


class _SqOverflow(RuntimeError):
    """Row capacity exceeded at the current Fcap rung."""


def _sq_run_segments(P_np, digit, src, gids, ret_slot, slot_ops, caps,
                     ep_code, ep_rank, S_pad: int, C: int, L: int,
                     R_n: int, Fcap: int, should_abort):
    """Segmented drive of the sparse-live walk at one capacity rung;
    raises :class:`_SqOverflow` (caller escalates and restarts — an
    overflowed walk's rows are over-approximate and unusable)."""
    import jax
    import jax.numpy as jnp

    from jepsen_tpu.checkers import reach

    walk = _jitted_sq_walk(Fcap, L)
    dP = jax.device_put(np.asarray(P_np))
    ddig, dsrc = jax.device_put(digit), jax.device_put(src)
    dg = jax.device_put(np.ascontiguousarray(gids, np.int32))
    masks0 = np.full(Fcap, _SQ_SENT, np.uint32)
    masks0[0] = 0
    payload0 = np.zeros((Fcap, S_pad, C), bool)
    payload0[0, 0, 0] = True
    m_cur = jnp.asarray(masks0)
    p_cur = jnp.asarray(payload0)
    base = 0
    while base < R_n:
        if should_abort is not None and should_abort():
            raise Aborted()
        n = min(_SEG, R_n - base)
        L_pad = max(64, reach._bucket(n, 8))
        seg_slot = np.full(L_pad, -1, np.int32)
        seg_slot[:n] = ret_slot[base:base + n]
        seg_ops = np.full((L_pad, L), -1, np.int32)
        seg_ops[:n] = slot_ops[base:base + n]
        G = caps.shape[1]
        seg_caps = np.zeros((L_pad, G), np.int32)
        seg_caps[:n] = caps[base:base + n]
        seg_caps[n:] = caps[base + n - 1]    # idempotent pads (above)
        # pad rows carry empty epoch tables (gid -1 = no grouping);
        # canonicalization is the identity there
        seg_code = np.full((L_pad, L), -1, np.int8)
        seg_code[:n] = ep_code[base:base + n]
        seg_rank = np.zeros((L_pad, L), np.int8)
        seg_rank[:n] = ep_rank[base:base + n]
        ptr, m_cur, p_cur, alive, over = walk(
            dP, ddig, dsrc, dg, jnp.asarray(seg_slot),
            jnp.asarray(seg_ops), jnp.asarray(seg_caps),
            jnp.asarray(seg_code), jnp.asarray(seg_rank), m_cur, p_cur)
        if bool(over):
            raise _SqOverflow(f"> {Fcap} live-mask rows")
        if not bool(alive):
            return base + int(ptr), m_cur, p_cur, False
        base += n
    return R_n, m_cur, p_cur, True


def check_quotient(memo: Memo, stream: ev.EventStream,
                   packed: h.PackedHistory, *,
                   max_dense: int = 1 << 22,
                   should_abort=None) -> Dict[str, Any]:
    """Run the product-space walk — dense when ``2^L`` fits the budget,
    else the sparse-live walk (rows per reachable mask, L ≤ 31).
    Raises :class:`QuotientOverflow` when neither fits (callers fall
    back to the sparse frontier rows) or :class:`Aborted` when
    ``should_abort`` fires between segments. Returns the same verdict
    dict shape as the other engines (the caller brands the engine
    name)."""
    from jepsen_tpu.checkers import reach

    (L, ret_slot, slot_ops, ret_event, ret_entry, R_n, gids, sizes, C,
     caps, digit, src, epochs) = _prep_quotient(
         memo, stream, packed, max_live=_MAX_LIVE_SPARSE)
    S = memo.n_states
    S_pad = max(2, reach._next_pow2(S))
    dense_ok = (L <= _MAX_LIVE_DENSE
                and S_pad * (1 << L) * C <= max_dense)
    sparse_ok = (S_pad * C * _SQ_CAPS[0] <= _SQ_PAYLOAD_MAX
                 and _SQ_CAPS[0] * L * S_pad * C <= _SQ_EINSUM_MAX)
    if not dense_ok and not sparse_ok:
        raise QuotientOverflow(
            f"product space {S_pad}x2^{L}x{C} exceeds budgets")
    if R_n == 0:
        return {"valid": True, "product-space": [S_pad, 1 << L, C],
                "live-slots": L, "crash-groups": len(sizes)}
    P_np = reach._build_P(memo, S_pad)
    rsl = np.ascontiguousarray(ret_slot, np.int32)
    ops = np.ascontiguousarray(slot_ops, np.int32)
    cps = np.ascontiguousarray(caps[:R_n], np.int32)
    if dense_ok:
        M = 1 << L
        xor_cols, bitmask = reach._xor_bitmask(L, M)
        R0 = np.zeros((S_pad, M, C), bool)
        R0[0, 0, 0] = True

        def drive(rs, so, cp, rn):
            return _run_segments(P_np, xor_cols, bitmask, digit, src,
                                 gids, rs, so, cp, R0, rn, should_abort)

        ptr, R_fin, alive = drive(rsl, ops, cps, R_n)
        walk_kind = "dense"
    else:
        ep_gid, ep_rank = epochs()      # lazy: sparse-live path only
        ecs = np.ascontiguousarray(ep_gid[:max(R_n, 1)])
        ers = np.ascontiguousarray(ep_rank[:max(R_n, 1)])

        def drive(rs, so, cp, rn, ec=ecs, er=ers):
            last = None
            for Fcap in _SQ_CAPS:
                if (S_pad * C * Fcap > _SQ_PAYLOAD_MAX
                        or Fcap * L * S_pad * C > _SQ_EINSUM_MAX):
                    break
                try:
                    ptr, m, p, alive = _sq_run_segments(
                        P_np, digit, src, gids, rs, so, cp, ec, er,
                        S_pad, C, L, rn, Fcap, should_abort)
                    return ptr, (m, p), alive
                # jtlint: ok fallback — re-raised as QuotientOverflow after the sizing ladder
                except _SqOverflow as e:
                    last = e
            raise QuotientOverflow(str(last or "sparse-live overflow"))

        ptr, R_fin, alive = drive(rsl, ops, cps, R_n)
        walk_kind = "sparse-live"
    if bool(alive):
        return {"valid": True, "product-space": [S_pad, 1 << L, C],
                "live-slots": L, "crash-groups": len(sizes),
                "walk": walk_kind}
    dead_ret = int(ptr) - 1
    out = {"valid": False, "product-space": [S_pad, 1 << L, C],
           "live-slots": L, "crash-groups": len(sizes),
           "walk": walk_kind,
           "op": packed.entries[int(ret_entry[dead_ret])].op.to_dict(),
           "dead-event": int(ret_event[dead_ret]),
           "max-linearized": dead_ret}
    if dead_ret > 0:
        out["previous-ok"] = packed.entries[
            int(ret_entry[dead_ret - 1])].op.to_dict()
    # witness: re-walk the prefix for the surviving configs
    try:
        _p2, R_prev, _ = drive(rsl[:dead_ret], ops[:dead_ret],
                               cps[:max(dead_ret, 1)], dead_ret)
        if walk_kind == "dense":
            out["final-configs"] = _decode(
                memo, np.asarray(R_prev), slot_ops[dead_ret], gids,
                sizes, digit)
        else:
            m_prev, p_prev = R_prev
            out["final-configs"] = _decode_sparse(
                memo, np.asarray(m_prev), np.asarray(p_prev),
                slot_ops[dead_ret], gids, sizes, digit)
    # jtlint: ok fallback — witness evidence is best-effort garnish on a decided verdict
    except Exception:                                   # noqa: BLE001
        pass                            # evidence is best-effort garnish
    return out


def _decode_sparse(memo: Memo, masks: np.ndarray, payload: np.ndarray,
                   pending_row, gids, sizes, digit,
                   limit: int = 16) -> List[Dict[str, Any]]:
    out = []
    for f in np.nonzero(masks != _SQ_SENT)[0]:
        m = int(masks[f])
        for s, c in np.argwhere(payload[f]):
            if len(out) >= limit:
                return out
            lin = [str(memo.distinct_ops[pending_row[j]])
                   for j in range(len(pending_row))
                   if (m >> j) & 1 and pending_row[j] >= 0]
            for g in range(len(sizes)):
                cnt = int(digit[g, c])
                if cnt:
                    lin.append(f"{cnt}x crashed "
                               f"{memo.distinct_ops[int(gids[g])]}")
            out.append({"model": str(memo.states[s]),
                        "linearized-pending": lin})
    return out


def _decode(memo: Memo, R: np.ndarray, pending_row, gids, sizes,
            digit, limit: int = 16) -> List[Dict[str, Any]]:
    S_pad, M, C = R.shape
    alive = np.argwhere(R)
    out = []
    for s, m, c in alive[:limit]:
        lin = [str(memo.distinct_ops[pending_row[j]])
               for j in range(len(pending_row))
               if (int(m) >> j) & 1 and pending_row[j] >= 0]
        for g in range(len(sizes)):
            cnt = int(digit[g, c])
            if cnt:
                lin.append(f"{cnt}x crashed "
                           f"{memo.distinct_ops[int(gids[g])]}")
        out.append({"model": str(memo.states[s]),
                    "linearized-pending": lin})
    return out
