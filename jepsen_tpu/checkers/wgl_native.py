"""ctypes bridge to the native C++ WGL search (``native/wgl.cpp``) — the
fast CPU engine raced against the device engine in ``competition`` and
used for large-n cross-validation (upstream's knossos.wgl ran on the JVM;
here the equivalent hot loop is C++, built on demand with g++).

Result dicts mirror :mod:`jepsen_tpu.checkers.wgl_ref` so the facade can
route to either interchangeably. An :class:`AbortFlag` lets a competition
thread stop the search from Python (upstream ``knossos.search/abort!``).
"""
from __future__ import annotations

import ctypes
from typing import Any, Dict, Optional, Sequence

import numpy as np

from jepsen_tpu import history as h
from jepsen_tpu.checkers._native_build import NativeLib
from jepsen_tpu.models import Model
from jepsen_tpu.models.memo import memo as build_memo
from jepsen_tpu.op import Op

INF = 1 << 60
_CAUSES = {0: None, 1: "timeout", 2: "config-set-explosion", 3: "aborted"}


def _declare(lib: ctypes.CDLL) -> None:
    lib.wgl_check.restype = ctypes.c_int64
    lib.wgl_check.argtypes = [
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int32, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_uint8),
        ctypes.c_int32, ctypes.c_int64, ctypes.c_double,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int32, ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_int32)]


_NATIVE = NativeLib("wgl.cpp", "libjepsen_wgl.so", _declare)
load = _NATIVE.load


def available() -> bool:
    return _NATIVE.available()


def build_error() -> Optional[str]:
    load()
    return _NATIVE.error


class AbortFlag:
    """Shared abort flag the search polls (upstream
    ``knossos.search/abort!``)."""

    def __init__(self) -> None:
        self._flag = ctypes.c_int32(0)

    def abort(self) -> None:
        self._flag.value = 1

    @property
    def pointer(self):
        return ctypes.byref(self._flag)


def check(model: Model, history: Sequence[Op], *,
          time_limit: Optional[float] = None,
          max_configs: int = 50_000_000,
          max_states: int = 1_000_000,
          abort_flag: Optional[AbortFlag] = None) -> Dict[str, Any]:
    return check_packed(model, h.pack(history), time_limit=time_limit,
                        max_configs=max_configs, max_states=max_states,
                        abort_flag=abort_flag)


def check_packed(model: Model, packed: h.PackedHistory, *,
                 time_limit: Optional[float] = None,
                 max_configs: int = 50_000_000,
                 max_states: int = 1_000_000,
                 abort_flag: Optional[AbortFlag] = None) -> Dict[str, Any]:
    lib = load()
    if lib is None:
        raise RuntimeError(f"native WGL unavailable: {_NATIVE.error}")
    n = packed.n
    if n == 0 or packed.n_ok == 0:
        return {"valid": True, "engine": "wgl-native",
                "configs-explored": 0}
    memo = build_memo(model, packed, max_states=max_states)

    table = np.ascontiguousarray(memo.table, np.int32)
    inv_ev = np.ascontiguousarray(packed.inv_ev, np.int32)
    ret_ev = np.ascontiguousarray(packed.ret_ev, np.int64)
    op_id = np.ascontiguousarray(packed.op_id, np.int32)
    crashed = np.ascontiguousarray(packed.crashed, np.uint8)
    out = np.zeros(4, np.int32)
    # failure-evidence buffers: up to _CFG_CAP deepest dead-end configs
    # as (state id, linearized-mask words) — knossos :final-paths
    words = (n + 63) // 64 + 1
    cfg_sid = np.zeros(_CFG_CAP, np.int32)
    cfg_mask = np.zeros((_CFG_CAP, words), np.uint64)
    n_cfg = np.zeros(1, np.int32)

    def ptr(a, t):
        return a.ctypes.data_as(ctypes.POINTER(t))

    explored = lib.wgl_check(
        ptr(table, ctypes.c_int32), memo.n_states, memo.n_ops,
        ptr(inv_ev, ctypes.c_int32), ptr(ret_ev, ctypes.c_int64),
        ptr(op_id, ctypes.c_int32), ptr(crashed, ctypes.c_uint8),
        n, max_configs, -1.0 if time_limit is None else float(time_limit),
        abort_flag.pointer if abort_flag is not None else None,
        ptr(out, ctypes.c_int32),
        _CFG_CAP, ptr(cfg_sid, ctypes.c_int32),
        ptr(cfg_mask, ctypes.c_uint64), ptr(n_cfg, ctypes.c_int32))

    verdict, stuck, cover, cause = (int(x) for x in out)
    if verdict == 1:
        return {"valid": True, "engine": "wgl-native",
                "configs-explored": int(explored),
                "states-materialized": memo.n_states}
    if verdict == 0:
        res = {"valid": False, "engine": "wgl-native",
               "op": packed.entries[stuck].op.to_dict(),
               "max-linearized": cover,
               "configs-explored": int(explored)}
        res["final-configs"] = _decode_configs(
            memo, packed, cfg_sid, cfg_mask, int(n_cfg[0]))
        return res
    return {"valid": "unknown", "engine": "wgl-native",
            "cause": _CAUSES.get(cause, cause),
            "configs-explored": int(explored)}


_CFG_CAP = 16


def _decode_configs(memo, packed: h.PackedHistory, cfg_sid: np.ndarray,
                    cfg_mask: np.ndarray, n_cfg: int):
    """Decode the C engine's (state id, linearized-mask) dead-end
    configurations into the witness shape every other engine reports —
    model state plus the linearized ops CONCURRENT with that config's
    own stuck op (the same pending-window scope as
    :mod:`jepsen_tpu.checkers.wgl_ref`)."""
    n = packed.n
    ok_idx = np.nonzero(~packed.crashed)[0]
    final = []
    for c in range(n_cfg):
        bits = np.unpackbits(cfg_mask[c].view(np.uint8),
                             bitorder="little")[:n].astype(bool)
        not_lin_ok = ok_idx[~bits[ok_idx]]
        stuck2 = int(not_lin_ok[0]) if len(not_lin_ok) else -1
        lin_idx = np.nonzero(bits)[0]
        if stuck2 >= 0:
            lin = [str(packed.entries[i].op) for i in lin_idx
                   if i != stuck2
                   and int(packed.ret_ev[i]) > int(packed.inv_ev[stuck2])]
        else:
            lin = []
        if not lin:             # fully-sequential window: show the tail
            lin = [str(packed.entries[i].op) for i in lin_idx][-8:]
        final.append({"model": str(memo.states[int(cfg_sid[c])]),
                      "linearized-pending": lin})
    return final
