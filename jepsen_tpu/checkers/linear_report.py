"""SVG rendering of a non-linearizable window — upstream
``knossos/src/knossos/linear/report.clj`` (SURVEY.md §2.2): the famous
timeline diagrams Jepsen analyses embed, showing each process's op bars
around the operation that could not be linearized.

Independent implementation: plain SVG text, no dependencies. The rendered
window spans every op whose interval overlaps the failing op's invocation
(the ops the search could still reorder at the point of death), so a
reader can trace why no linearization order exists.
"""
from __future__ import annotations

import html
from typing import Any, Dict, List, Mapping, Optional, Sequence

from jepsen_tpu import history as h
from jepsen_tpu.op import INFO, OK, Op

_LANE_H = 34
_BAR_H = 22
_LEFT = 110
_WIDTH = 900
_COLORS = {OK: "#7fb77f", INFO: "#d6a76d", "stuck": "#d66a6a",
           "other": "#9db4c9"}


def _fmt(op: Op) -> str:
    v = op.value
    return f"{op.f} {v!r}" if v is not None else f"{op.f}"


def render_analysis(history: Sequence[Op], result: Mapping[str, Any],
                    path: Optional[str] = None) -> str:
    """Render the failing window of ``result`` (a ``{"valid": False, "op":
    ...}`` verdict from any linearizability engine) over ``history``.
    Returns the SVG text; writes it to ``path`` when given."""
    if result.get("valid") is not False or not result.get("op"):
        raise ValueError("result is not a non-linearizable verdict with op")
    entries = h.analysis_entries(history)
    stuck_idx = result["op"].get("index")
    stuck = next((e for e in entries if e.op.index == stuck_idx), None)
    if stuck is None:                       # fall back: match on content
        key = (result["op"].get("process"), result["op"].get("f"))
        stuck = next((e for e in entries
                      if (e.op.process, e.op.f) == key), entries[0])
    # window: entries overlapping the stuck op's interval
    lo, hi = stuck.inv_ev, stuck.ret_ev
    window = [e for e in entries
              if e.inv_ev <= hi and e.ret_ev >= lo]
    if not window:
        window = [stuck]
    t0 = min(e.inv_ev for e in window)
    t1 = max(min(e.ret_ev, hi + 2) for e in window) + 1
    span = max(1, t1 - t0)
    procs = sorted({e.process for e in window}, key=repr)
    rows = {p: i for i, p in enumerate(procs)}
    height = _LANE_H * len(procs) + 70

    def x(ev: int) -> float:
        return _LEFT + (min(ev, t1) - t0) / span * (_WIDTH - _LEFT - 20)

    parts: List[str] = [
        f'<svg xmlns="http://www.w3.org/2000/svg" width="{_WIDTH}" '
        f'height="{height}" font-family="sans-serif" font-size="12">',
        f'<text x="{_LEFT}" y="18" font-size="14" fill="#333">'
        f'Non-linearizable: {html.escape(_fmt(stuck.op))} '
        f'(process {html.escape(str(stuck.process))}) cannot be '
        f'linearized</text>']
    for p in procs:
        y = 40 + rows[p] * _LANE_H
        parts.append(f'<text x="8" y="{y + _BAR_H - 6}" fill="#555">'
                     f'process {html.escape(str(p))}</text>')
        parts.append(f'<line x1="{_LEFT}" y1="{y + _LANE_H - 4}" '
                     f'x2="{_WIDTH - 10}" y2="{y + _LANE_H - 4}" '
                     f'stroke="#eee"/>')
    for e in window:
        y = 40 + rows[e.process] * _LANE_H
        x0 = x(e.inv_ev)
        x1 = x(e.ret_ev if e.ret_ev <= t1 else t1)
        wdt = max(6.0, x1 - x0)
        if e is stuck:
            color = _COLORS["stuck"]
        elif e.crashed:
            color = _COLORS[INFO]
        else:
            color = _COLORS[OK]
        label = html.escape(_fmt(e.op))
        parts.append(
            f'<rect x="{x0:.1f}" y="{y}" width="{wdt:.1f}" '
            f'height="{_BAR_H}" rx="3" fill="{color}">'
            f'<title>{label}</title></rect>')
        parts.append(f'<text x="{x0 + 3:.1f}" y="{y + _BAR_H - 7}" '
                     f'fill="#fff">{label}</text>')
        if e.crashed:
            parts.append(f'<text x="{x1 + 2:.1f}" y="{y + _BAR_H - 7}" '
                         f'fill="#999">&#8230;</text>')
    parts.append(
        f'<text x="{_LEFT}" y="{height - 12}" fill="#888">window events '
        f'{t0}&#8211;{t1}; green = completed, orange = crashed '
        f'(forever pending), red = the operation the search got stuck '
        f'on</text>')
    parts.append("</svg>")
    svg = "\n".join(parts)
    if path:
        with open(path, "w") as f:
            f.write(svg)
    return svg
