"""SVG rendering of a non-linearizable window — upstream
``knossos/src/knossos/linear/report.clj`` (SURVEY.md §2.2): the famous
timeline diagrams Jepsen analyses embed, showing each process's op bars
around the operation that could not be linearized.

Independent implementation: plain SVG text, no dependencies. The rendered
window spans every op whose interval overlaps the failing op's invocation
(the ops the search could still reorder at the point of death), so a
reader can trace why no linearization order exists. Round-4 parity pass
(VERDICT round 3 item 8): an event-time axis with tick marks, a legend,
crashed-op tails fading off the right edge (upstream draws crashed ops
running to infinity), and hover titles carrying the op, its process, and
its event interval.
"""
from __future__ import annotations

import html
from typing import Any, Dict, List, Mapping, Optional, Sequence

from jepsen_tpu import history as h
from jepsen_tpu.op import INFO, OK, Op

_LANE_H = 34
_BAR_H = 22
_LEFT = 110
_WIDTH = 900
_COLORS = {OK: "#7fb77f", INFO: "#d6a76d", "stuck": "#d66a6a",
           "other": "#9db4c9"}
_LEGEND = [("completed", _COLORS[OK]),
           ("crashed (forever pending)", _COLORS[INFO]),
           ("stuck — cannot linearize", _COLORS["stuck"])]


def _fmt(op: Op) -> str:
    v = op.value
    return f"{op.f} {v!r}" if v is not None else f"{op.f}"


def _axis_ticks(t0: int, t1: int, n: int = 6) -> List[int]:
    """Round-ish tick positions across [t0, t1] (event indices — the
    diagram's time base is the history's total event order)."""
    span = max(1, t1 - t0)
    step = max(1, span // n)
    # snap the step to 1/2/5 x 10^k like a plot axis would
    mag = 1
    while step >= mag * 10:
        mag *= 10
    for nice in (1, 2, 5, 10):
        if step <= nice * mag:
            step = nice * mag
            break
    first = ((t0 + step - 1) // step) * step
    return list(range(first, t1 + 1, step))


def render_analysis(history: Sequence[Op], result: Mapping[str, Any],
                    path: Optional[str] = None) -> str:
    """Render the failing window of ``result`` (a ``{"valid": False, "op":
    ...}`` verdict from any linearizability engine) over ``history``.
    Returns the SVG text; writes it to ``path`` when given."""
    if result.get("valid") is not False or not result.get("op"):
        raise ValueError("result is not a non-linearizable verdict with op")
    entries = h.analysis_entries(history)
    stuck_idx = result["op"].get("index")
    stuck = next((e for e in entries if e.op.index == stuck_idx), None)
    if stuck is None:                       # fall back: match on content
        key = (result["op"].get("process"), result["op"].get("f"))
        stuck = next((e for e in entries
                      if (e.op.process, e.op.f) == key), entries[0])
    # window: entries overlapping the stuck op's interval
    lo, hi = stuck.inv_ev, stuck.ret_ev
    window = [e for e in entries
              if e.inv_ev <= hi and e.ret_ev >= lo]
    if not window:
        window = [stuck]
    t0 = min(e.inv_ev for e in window)
    t1 = max(min(e.ret_ev, hi + 2) for e in window) + 1
    span = max(1, t1 - t0)
    procs = sorted({e.process for e in window}, key=repr)
    rows = {p: i for i, p in enumerate(procs)}
    axis_y = 40 + _LANE_H * len(procs) + 8
    height = axis_y + 46
    right = _WIDTH - 20

    def x(ev: int) -> float:
        return _LEFT + (min(ev, t1) - t0) / span * (right - _LEFT)

    parts: List[str] = [
        f'<svg xmlns="http://www.w3.org/2000/svg" width="{_WIDTH}" '
        f'height="{height}" font-family="sans-serif" font-size="12">',
        # crashed-op tail fade (upstream draws crashed bars running to
        # infinity; here they fade off the window's right edge)
        '<defs>'
        f'<linearGradient id="crashfade" x1="0" y1="0" x2="1" y2="0">'
        f'<stop offset="0" stop-color="{_COLORS[INFO]}" '
        'stop-opacity="1"/>'
        f'<stop offset="1" stop-color="{_COLORS[INFO]}" '
        'stop-opacity="0"/>'
        '</linearGradient></defs>',
        f'<text x="{_LEFT}" y="18" font-size="14" fill="#333">'
        f'Non-linearizable: {html.escape(_fmt(stuck.op))} '
        f'(process {html.escape(str(stuck.process))}) cannot be '
        f'linearized</text>']
    for p in procs:
        y = 40 + rows[p] * _LANE_H
        parts.append(f'<text x="8" y="{y + _BAR_H - 6}" fill="#555">'
                     f'process {html.escape(str(p))}</text>')
        parts.append(f'<line x1="{_LEFT}" y1="{y + _LANE_H - 4}" '
                     f'x2="{_WIDTH - 10}" y2="{y + _LANE_H - 4}" '
                     f'stroke="#eee"/>')
    for e in window:
        y = 40 + rows[e.process] * _LANE_H
        x0 = x(e.inv_ev)
        open_ended = e.crashed or e.ret_ev > t1
        x1 = right if open_ended else x(e.ret_ev)
        wdt = max(6.0, x1 - x0)
        if e is stuck:
            color = _COLORS["stuck"]
        elif e.crashed:
            color = _COLORS[INFO]
        else:
            color = _COLORS[OK]
        label = html.escape(_fmt(e.op))
        ret_txt = "&#8734;" if e.crashed else str(e.ret_ev)
        title = (f'{label} &#8212; process {html.escape(str(e.process))}, '
                 f'events {e.inv_ev}&#8211;{ret_txt}')
        if e.crashed:
            # solid bar for the known-pending span, then the fade tail
            solid_w = max(6.0, wdt * 0.55)
            parts.append(
                f'<rect x="{x0:.1f}" y="{y}" width="{solid_w:.1f}" '
                f'height="{_BAR_H}" rx="3" fill="{color}">'
                f'<title>{title}</title></rect>')
            parts.append(
                f'<rect x="{x0 + solid_w:.1f}" y="{y}" '
                f'width="{max(0.0, x1 - x0 - solid_w):.1f}" '
                f'height="{_BAR_H}" fill="url(#crashfade)">'
                f'<title>{title}</title></rect>')
        else:
            # Python < 3.12 rejects backslashes inside f-string
            # expressions, so the conditional attribute is hoisted out
            stroke = ' stroke="#a33" stroke-width="2"' if e is stuck else ""
            parts.append(
                f'<rect x="{x0:.1f}" y="{y}" width="{wdt:.1f}" '
                f'height="{_BAR_H}" rx="3" fill="{color}"{stroke}>'
                f'<title>{title}</title></rect>')
        parts.append(f'<text x="{x0 + 3:.1f}" y="{y + _BAR_H - 7}" '
                     f'fill="#fff"><title>{title}</title>{label}</text>')
    # event-time axis with tick marks
    parts.append(f'<line x1="{_LEFT}" y1="{axis_y}" x2="{right}" '
                 f'y2="{axis_y}" stroke="#999"/>')
    for tick in _axis_ticks(t0, t1):
        tx = x(tick)
        parts.append(f'<line x1="{tx:.1f}" y1="{axis_y}" x2="{tx:.1f}" '
                     f'y2="{axis_y + 5}" stroke="#999"/>')
        parts.append(f'<text x="{tx:.1f}" y="{axis_y + 17}" fill="#777" '
                     f'text-anchor="middle">{tick}</text>')
    parts.append(f'<text x="{right}" y="{axis_y + 17}" fill="#777" '
                 f'text-anchor="end" font-style="italic">event index'
                 f'</text>')
    # legend
    lx = _LEFT
    ly = axis_y + 28
    for name, color in _LEGEND:
        parts.append(f'<rect x="{lx}" y="{ly - 10}" width="12" '
                     f'height="12" rx="2" fill="{color}"/>')
        parts.append(f'<text x="{lx + 16}" y="{ly}" fill="#555">'
                     f'{name}</text>')
        lx += 16 + 7 * len(name) + 24
    parts.append("</svg>")
    svg = "\n".join(parts)
    if path:
        with open(path, "w") as f:
            f.write(svg)
    return svg
