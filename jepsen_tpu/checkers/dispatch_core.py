"""Shared lockstep dispatch/collect core (ROADMAP item 1 down-payment).

The keyed lockstep schedulers (:func:`reach._dispatch_lockstep_groups`
and :func:`reach._dispatch_lockstep_stream`) and the chunk-lockstep
engine (:func:`reach_chunklock.walk_chunklock`) each grew their own copy
of the same pack→dispatch→fallback→recovery state machine. This module
is that seam extracted ONCE, so engine variants — including the
multi-host chunk-sharded path — parameterize it instead of adding a
sixth choreography:

- :class:`DispatchState` — round-robin device placement over the mesh,
  pad-lane dedup accounting, the in-flight window and FIFO drain
  (previously ``reach._LockstepDispatchState``; reach keeps an alias).
- :func:`dispatch_packed` — the bit-packed 0/1 seed upload with the
  dense retry and the exactly-one-fallback record (previously inlined
  in ``walk_chunklock`` phase A; the multi-host phase-A dispatch is the
  second caller).
- :func:`rescue_once` — host-side exact recovery under the ordinary
  contract: the ONE ``engine.fallback`` record lands only AFTER the
  recovery succeeds, so a failure that persists through recovery
  propagates unrecorded (it was not the degraded path's fault).
"""
from __future__ import annotations

import os
import time as _time
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

from jepsen_tpu import obs
from jepsen_tpu.checkers import transfer

# in-flight lockstep dispatch groups beyond the one being collected.
# Depth 1 queues the NEXT group's device programs — paying its
# marshalling, compile (on a fresh geometry), and transfer host time —
# while the device walks the current group; the same K-deep dispatch
# trick bench.py's kernel probe validates. Deeper pipelines pin more
# operand sets in HBM for ~no added overlap (the host stage is the
# bottleneck, and it is already fully hidden at depth 1).
PIPE_DEPTH = 1

# default in-flight window for the SERVE lanes (groups staged per lane,
# including the one being collected): deep enough to hide host
# pack+fetch behind device walks across whole admission groups, shallow
# enough that at most K operand sets are pinned per lane. The autotune
# table can override per geometry bucket (kind "pipeline").
SERVE_PIPE_K = 4


def pipeline_enabled() -> bool:
    """The stage/collect dispatch pipeline gate.
    ``JEPSEN_TPU_NO_PIPELINE=1`` forces K=1 everywhere — every group
    is collected before the next is staged, the bit-identical
    degenerate mode (consulted per call: tests toggle it)."""
    return not os.environ.get("JEPSEN_TPU_NO_PIPELINE")


def pipeline_k(geom_key: Optional[str] = None, *,
               default: int = SERVE_PIPE_K) -> int:
    """Resolve the in-flight window K (groups staged per lane,
    including the one being collected). Precedence: the
    ``JEPSEN_TPU_NO_PIPELINE=1`` opt-out (K=1), the
    ``JEPSEN_TPU_PIPE_K=<n>`` override, a measured autotune winner
    for this geometry bucket (kind ``pipeline``, recorded by
    ``tools/ablate_lane.py --pipeline``; staleness-guarded like every
    other entry), else ``default``. Always >= 1."""
    if not pipeline_enabled():
        return 1
    env = os.environ.get("JEPSEN_TPU_PIPE_K")
    if env:
        try:
            return max(1, int(env))
        # jtlint: ok fallback — a malformed override reads as the default depth
        except ValueError:
            pass
    if geom_key:
        from jepsen_tpu.checkers import autotune
        w = autotune.winner("pipeline", geom_key)
        if w is not None:
            try:
                return max(1, int(w))
            # jtlint: ok fallback — a malformed table entry reads as the default depth
            except (TypeError, ValueError):
                pass
    return max(1, int(default))


def poll_ready(x) -> bool:
    """True when a dispatched device value's result is resident (its
    fetch would not block). Conservative: anything without an
    ``is_ready`` probe — numpy results, degenerate staged handles —
    reads as ready, so readiness polling can only make a collect
    eager, never skip one.  The probe itself lives with the rest of
    the wire knowledge in :func:`transfer.device_ready`."""
    return transfer.device_ready(x)


def inflight_ready(fl) -> bool:
    """Readiness of one dispatched-but-unfetched lockstep group
    (:class:`reach_batch.BatchInflight`): the word body's queued
    results, or the dense body's final carried config set."""
    out = getattr(fl, "word_out", None)
    if out is not None:
        return all(poll_ready(o) for o in out)
    final = getattr(fl, "final", None)
    return poll_ready(final) if final is not None else True


class DispatchState:
    """Shared per-dispatch bookkeeping of the synchronous and streaming
    lockstep schedulers: round-robin device placement over the mesh,
    pad-lane dedup accounting (mesh pad lanes are cross-group
    duplicates — their returns must not count as real work), the
    in-flight window, and the FIFO drain. ONE implementation so the two
    schedulers' diag/obs output — which the stream-vs-sync differential
    tests treat as equivalent — cannot drift."""

    __slots__ = ("devs", "n_dev", "depth", "dead", "seen", "dev_groups",
                 "inflight", "inflight_hwm", "fetch_s",
                 "fetch_degraded")

    def __init__(self, devices: Optional[Sequence], dead: np.ndarray,
                 k: Optional[int] = None):
        self.devs = list(devices) if devices else None
        self.n_dev = len(self.devs) if self.devs else 1
        # K groups in flight per device lane (K includes the one being
        # collected, so the drain limit is n_dev*K - 1); the default
        # K = PIPE_DEPTH+1 is the historical one-walking-plus-one-
        # queued window, and JEPSEN_TPU_NO_PIPELINE=1 collapses to the
        # collect-after-every-dispatch degenerate mode
        if k is None:
            k = pipeline_k(default=PIPE_DEPTH + 1)
        self.depth = self.n_dev * max(1, int(k)) - 1
        self.dead = dead
        self.seen: set = set()
        self.dev_groups = [0] * self.n_dev
        self.inflight: list = []
        self.inflight_hwm = 0
        self.fetch_s = 0.0
        self.fetch_degraded = False

    def place(self, gi: int, g, prep) -> Tuple[int, Dict[str, Any]]:
        """Pin group ``gi`` to its round-robin device; returns the
        device index and the dispatch span args."""
        di = gi % self.n_dev
        sp: Dict[str, Any] = {"lanes": len(g)}
        if self.devs:
            prep.device = self.devs[di]
            self.dev_groups[di] += 1
            sp["device"] = di
        return di, sp

    def admit(self, g, fl, di: int) -> dict:
        """Group diag (with pad-lane dedup) + in-flight append."""
        from jepsen_tpu.checkers import reach_batch

        gd = reach_batch.group_diag(fl.geom, fl.R_lens)
        x = fl.dsegs.get("xfer")
        if x is not None:
            # wire bytes this group actually moved vs the blanket
            # int32/f32 format — summed by _lockstep_accounting
            gd["put_bytes"], gd["put_bytes_unpacked"] = x
        if self.devs:
            gd["device"] = di
            dup = sum(int(fl.R_lens[j]) for j, k in enumerate(g)
                      if k in self.seen)
            self.seen.update(g)
            if dup:
                gd["pad_lane_returns"] = dup
        self.inflight.append((g, fl, di))
        self.inflight_hwm = max(self.inflight_hwm, len(self.inflight))
        obs.count("pipeline.staged")
        return gd

    def stage(self, gi: int, g, prep, dispatch_fn) -> dict:
        """The pipeline's STAGE half for one group: device placement +
        ``dispatch_fn(prep)`` (host pack already done by the caller's
        prepare; this queues the puts/compiles/kernel launch, fetching
        nothing) + in-flight admission. Returns the group diag."""
        di, sp = self.place(gi, g, prep)
        with obs.span("lockstep.dispatch", **sp):
            fl = dispatch_fn(prep)
        return self.admit(g, fl, di)

    def collect(self, limit: int = 0) -> None:
        """The pipeline's COLLECT half: FIFO-fetch verdicts until at
        most ``limit`` groups remain in flight (0 = drain all)."""
        self.drain(limit)

    def collect_ready(self, limit: int = 0) -> None:
        """Readiness-polled collect: FIFO-fetch only groups whose
        device results are already resident, stopping at the first
        still-walking group (never past ``limit`` remaining). A lane
        thread calls this between stages so finished predecessors
        drain without blocking the next stage."""
        from jepsen_tpu.checkers import reach_batch  # noqa: F401

        while (len(self.inflight) > limit
               and inflight_ready(self.inflight[0][1])):
            self.drain(len(self.inflight) - 1)

    def drain(self, limit: int) -> None:
        from jepsen_tpu.checkers import reach_batch

        while len(self.inflight) > limit:
            g0, fl0, di0 = self.inflight.pop(0)
            t0 = _time.monotonic()
            sp: Dict[str, Any] = {"lanes": len(g0)}
            if self.devs:
                sp["device"] = di0
            with obs.span("lockstep.collect", **sp):
                self.dead[np.asarray(g0, np.int64)] = \
                    reach_batch.collect_returns_batch(fl0)
            if getattr(fl0, "degraded", False):
                self.fetch_degraded = True
            self.fetch_s += _time.monotonic() - t0

    def mesh_info(self, pad_lanes: int) -> Optional[dict]:
        if not self.devs:
            return None
        return {"n_devices": self.n_dev,
                "per_device_groups": self.dev_groups,
                "inflight_max": self.inflight_hwm,
                "pad_lanes": pad_lanes}


def dispatch_packed(run, dense_args: Sequence[np.ndarray],
                    seed: np.ndarray, base_bytes: int, *,
                    stage: str = "packed-xfer"):
    """Dispatch ``run(*dense_args, seed_wire)`` with the exactly-0/1
    ``seed`` operand bit-packed on the wire (8 per byte, unpacked on
    device) when the transfer diet allows. A packed dispatch failure
    retries ONCE with the dense seed and records exactly one
    ``engine.fallback`` — AFTER the dense retry succeeds, because a
    failure that persists dense (e.g. Pallas unsupported on this
    backend) was not the packed wire's fault and must propagate
    unrecorded. ``base_bytes`` is the blanket int32/f32 wire baseline
    for the put accounting."""
    import jax.numpy as jnp

    dense_bytes = sum(int(a.nbytes) for a in dense_args)
    if transfer.packed_enabled():
        seed_w = transfer.pack_bool(seed)
        transfer.count_put(dense_bytes + seed_w.nbytes, base_bytes)
        try:
            return run(*dense_args, seed_w)
        except Exception as e:                          # noqa: BLE001
            # the dense retry re-crosses the whole operand set
            transfer.count_put(dense_bytes + seed.nbytes, 0)
            out = run(*dense_args, jnp.asarray(seed))
            obs.engine_fallback(stage, type(e).__name__)
            return out
    transfer.count_put(dense_bytes + seed.nbytes, base_bytes)
    return run(*dense_args, jnp.asarray(seed))


def rescue_once(stage: str, cause: str, fn, **fields):
    """Run host-side exact recovery ``fn()`` under the exactly-one-
    fallback contract: the single ``engine.fallback(stage, cause)``
    record lands only once ``fn`` has succeeded. Shared by the
    multi-host gather rescue and any future engine variant's recovery
    ladder, so the contract is written (and tested) once."""
    out = fn()
    obs.engine_fallback(stage, cause, **fields)
    return out
