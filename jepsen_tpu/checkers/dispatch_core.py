"""Shared lockstep dispatch/collect core (ROADMAP item 1 down-payment).

The keyed lockstep schedulers (:func:`reach._dispatch_lockstep_groups`
and :func:`reach._dispatch_lockstep_stream`) and the chunk-lockstep
engine (:func:`reach_chunklock.walk_chunklock`) each grew their own copy
of the same pack→dispatch→fallback→recovery state machine. This module
is that seam extracted ONCE, so engine variants — including the
multi-host chunk-sharded path — parameterize it instead of adding a
sixth choreography:

- :class:`DispatchState` — round-robin device placement over the mesh,
  pad-lane dedup accounting, the in-flight window and FIFO drain
  (previously ``reach._LockstepDispatchState``; reach keeps an alias).
- :func:`dispatch_packed` — the bit-packed 0/1 seed upload with the
  dense retry and the exactly-one-fallback record (previously inlined
  in ``walk_chunklock`` phase A; the multi-host phase-A dispatch is the
  second caller).
- :func:`rescue_once` — host-side exact recovery under the ordinary
  contract: the ONE ``engine.fallback`` record lands only AFTER the
  recovery succeeds, so a failure that persists through recovery
  propagates unrecorded (it was not the degraded path's fault).
"""
from __future__ import annotations

import time as _time
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

from jepsen_tpu import obs
from jepsen_tpu.checkers import transfer

# in-flight lockstep dispatch groups beyond the one being collected.
# Depth 1 queues the NEXT group's device programs — paying its
# marshalling, compile (on a fresh geometry), and transfer host time —
# while the device walks the current group; the same K-deep dispatch
# trick bench.py's kernel probe validates. Deeper pipelines pin more
# operand sets in HBM for ~no added overlap (the host stage is the
# bottleneck, and it is already fully hidden at depth 1).
PIPE_DEPTH = 1


class DispatchState:
    """Shared per-dispatch bookkeeping of the synchronous and streaming
    lockstep schedulers: round-robin device placement over the mesh,
    pad-lane dedup accounting (mesh pad lanes are cross-group
    duplicates — their returns must not count as real work), the
    in-flight window, and the FIFO drain. ONE implementation so the two
    schedulers' diag/obs output — which the stream-vs-sync differential
    tests treat as equivalent — cannot drift."""

    __slots__ = ("devs", "n_dev", "depth", "dead", "seen", "dev_groups",
                 "inflight", "inflight_hwm", "fetch_s",
                 "fetch_degraded")

    def __init__(self, devices: Optional[Sequence], dead: np.ndarray):
        self.devs = list(devices) if devices else None
        self.n_dev = len(self.devs) if self.devs else 1
        # one walking plus one queued group per device; FIFO collection
        # drains the oldest shard while the rest keep walking
        self.depth = self.n_dev * (PIPE_DEPTH + 1) - 1
        self.dead = dead
        self.seen: set = set()
        self.dev_groups = [0] * self.n_dev
        self.inflight: list = []
        self.inflight_hwm = 0
        self.fetch_s = 0.0
        self.fetch_degraded = False

    def place(self, gi: int, g, prep) -> Tuple[int, Dict[str, Any]]:
        """Pin group ``gi`` to its round-robin device; returns the
        device index and the dispatch span args."""
        di = gi % self.n_dev
        sp: Dict[str, Any] = {"lanes": len(g)}
        if self.devs:
            prep.device = self.devs[di]
            self.dev_groups[di] += 1
            sp["device"] = di
        return di, sp

    def admit(self, g, fl, di: int) -> dict:
        """Group diag (with pad-lane dedup) + in-flight append."""
        from jepsen_tpu.checkers import reach_batch

        gd = reach_batch.group_diag(fl.geom, fl.R_lens)
        x = fl.dsegs.get("xfer")
        if x is not None:
            # wire bytes this group actually moved vs the blanket
            # int32/f32 format — summed by _lockstep_accounting
            gd["put_bytes"], gd["put_bytes_unpacked"] = x
        if self.devs:
            gd["device"] = di
            dup = sum(int(fl.R_lens[j]) for j, k in enumerate(g)
                      if k in self.seen)
            self.seen.update(g)
            if dup:
                gd["pad_lane_returns"] = dup
        self.inflight.append((g, fl, di))
        self.inflight_hwm = max(self.inflight_hwm, len(self.inflight))
        return gd

    def drain(self, limit: int) -> None:
        from jepsen_tpu.checkers import reach_batch

        while len(self.inflight) > limit:
            g0, fl0, di0 = self.inflight.pop(0)
            t0 = _time.monotonic()
            sp: Dict[str, Any] = {"lanes": len(g0)}
            if self.devs:
                sp["device"] = di0
            with obs.span("lockstep.collect", **sp):
                self.dead[np.asarray(g0, np.int64)] = \
                    reach_batch.collect_returns_batch(fl0)
            if getattr(fl0, "degraded", False):
                self.fetch_degraded = True
            self.fetch_s += _time.monotonic() - t0

    def mesh_info(self, pad_lanes: int) -> Optional[dict]:
        if not self.devs:
            return None
        return {"n_devices": self.n_dev,
                "per_device_groups": self.dev_groups,
                "inflight_max": self.inflight_hwm,
                "pad_lanes": pad_lanes}


def dispatch_packed(run, dense_args: Sequence[np.ndarray],
                    seed: np.ndarray, base_bytes: int, *,
                    stage: str = "packed-xfer"):
    """Dispatch ``run(*dense_args, seed_wire)`` with the exactly-0/1
    ``seed`` operand bit-packed on the wire (8 per byte, unpacked on
    device) when the transfer diet allows. A packed dispatch failure
    retries ONCE with the dense seed and records exactly one
    ``engine.fallback`` — AFTER the dense retry succeeds, because a
    failure that persists dense (e.g. Pallas unsupported on this
    backend) was not the packed wire's fault and must propagate
    unrecorded. ``base_bytes`` is the blanket int32/f32 wire baseline
    for the put accounting."""
    import jax.numpy as jnp

    dense_bytes = sum(int(a.nbytes) for a in dense_args)
    if transfer.packed_enabled():
        seed_w = transfer.pack_bool(seed)
        transfer.count_put(dense_bytes + seed_w.nbytes, base_bytes)
        try:
            return run(*dense_args, seed_w)
        except Exception as e:                          # noqa: BLE001
            # the dense retry re-crosses the whole operand set
            transfer.count_put(dense_bytes + seed.nbytes, 0)
            out = run(*dense_args, jnp.asarray(seed))
            obs.engine_fallback(stage, type(e).__name__)
            return out
    transfer.count_put(dense_bytes + seed.nbytes, base_bytes)
    return run(*dense_args, jnp.asarray(seed))


def rescue_once(stage: str, cause: str, fn, **fields):
    """Run host-side exact recovery ``fn()`` under the exactly-one-
    fallback contract: the single ``engine.fallback(stage, cause)``
    record lands only once ``fn`` has succeeded. Shared by the
    multi-host gather rescue and any future engine variant's recovery
    ladder, so the contract is written (and tested) once."""
    out = fn()
    obs.engine_fallback(stage, cause, **fields)
    return out
