"""Pallas TPU kernel for the dense-reachability returns walk.

The XLA fast path (:func:`jepsen_tpu.checkers.reach._walk_returns`)
executes each return event as ~25 separate tiny fused HLO ops inside a
``lax.while_loop`` — at the headline config (S=8 states, W=5 slots,
M=32 masks) the walk is pure dispatch overhead: every op touches ≤1 KB.
This kernel runs the ENTIRE walk as one ``pallas_call``: the config set
``R`` (laid out ``[M, S]`` f32 0/1) lives in a VMEM scratch register
across a sequential grid; return-slot / pending-op metadata streams in
as SMEM blocks; each fire pass is ONE fused MXU matmul
``R[M, S] @ G_all[S, W·S]`` applying every pending op at once.

Semantics are identical to ``_walk_returns`` (upstream analogue:
``knossos/src/knossos/linear.clj``'s per-event config-set advance):

- per return, monotone Jacobi fire passes run to the between-returns
  fixpoint, detected by popcount stability and capped at W;
- firing slot ``j`` maps configs with bit j clear into their bit-set
  images through ``G = P[slot_ops[r, j]]`` — expressed as static
  half-splits (no scatters/gathers on the mask axis);
- the return projection keeps configs that fired the returning slot and
  clears its bit — a blend of the W static projections by scalar 0/1
  indicator multiplies (Mosaic cannot legalize scalar-predicate vector
  selects);
- an emptied config set at return ``r`` is a linearizability violation;
  the kernel records the first such ``r`` in an SMEM cell (the set
  stays empty from then on — firing and projection preserve emptiness —
  so no early exit is needed and the answer is exact).

The kernel is exact (no fingerprint hashing) like the rest of the
engine. ``interpret=True`` runs it on CPU for differential tests.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np

from jepsen_tpu import obs
from jepsen_tpu.checkers import transfer


def _gather_G(slot_ops_ref, P_ref, k: int, W: int, O1: int):
    """Concatenate the W pending ops' transition matrices for return ``k``
    into one [S, W·S] operand (slot -1 → the all-zero sentinel row)."""
    import jax.numpy as jnp

    Gs = []
    for jj in range(W):
        o = slot_ops_ref[k * W + jj]
        o = jnp.where(o < 0, O1 - 1, o)
        Gs.append(P_ref[o])                       # [S, S] f32
    return jnp.concatenate(Gs, axis=1)            # [S, W*S]


def _one_fire_pass(R, G_all, W: int, M: int, S: int):
    """One Jacobi fire pass: ONE fused [M,S]@[S,W·S] matmul computes every
    config's image under every slot's op; the per-slot loop then only
    reshuffles halves (VPU). No scatter in Mosaic: rebuild via stacked
    halves. Semantics match ``reach._ret_step``'s einsum."""
    import jax.numpy as jnp

    F = jnp.dot(R, G_all, preferred_element_type=jnp.float32)
    for jj in range(W):
        Fj = F[:, jj * S:(jj + 1) * S]
        half, blk = M >> (jj + 1), 1 << jj
        Rr = R.reshape(half, 2, blk, S)
        Fr = Fj.reshape(half, 2, blk, S)
        hi = jnp.maximum(
            Rr[:, 1], (Fr[:, 0] > 0.5).astype(jnp.float32))
        R = jnp.stack([Rr[:, 0], hi], axis=1).reshape(M, S)
    return R


def _fire_and_project(R, G_all, j, W: int, M: int, S: int):
    """One return event on the dense config set ``R`` [M, S] f32:

    - fire passes run to the between-returns fixpoint (fire is monotone,
      so popcount stability == fixpoint), capped at W total (a fire chain
      sets ≥1 new bit per pass). The projected set from the previous
      return is already closed under its still-pending ops, so 2 passes
      almost always suffice — and Mosaic's ``while_loop`` carry costs
      more than a tiny matmul here (measured ~1.5× on the headline
      config), so the first two passes are UNROLLED unconditionally and
      the loop runs only in the rare case the second pass still grew the
      set;
    - projection on the (dynamic) returning slot ``j``: scalar-predicate
      vector selects don't legalize in Mosaic, so blend all W static
      projections with scalar 0/1 indicator multiplies — exactly one is
      hot (or none for j = -1 padding → identity).
    """
    import jax
    import jax.numpy as jnp

    if W <= 2:
        for _ in range(W):                  # W passes ARE the fixpoint
            R = _one_fire_pass(R, G_all, W, M, S)
    else:
        R = _one_fire_pass(R, G_all, W, M, S)
        s1 = jnp.sum(R)
        R = _one_fire_pass(R, G_all, W, M, S)

        def fire_cond(c):
            Rv, prev, it = c
            return jnp.logical_and(it < W, jnp.sum(Rv) > prev)

        def fire_body(c):
            Rv, prev, it = c
            s = jnp.sum(Rv)
            return _one_fire_pass(Rv, G_all, W, M, S), s, it + 1

        R, _, _ = jax.lax.while_loop(fire_cond, fire_body, (R, s1, 2))

    acc = R * (j < 0).astype(jnp.float32)
    for jj in range(W):
        half, blk = M >> (jj + 1), 1 << jj
        Rr = R.reshape(half, 2, blk, S)
        taken = Rr[:, 1]
        proj = jnp.stack([taken, jnp.zeros_like(taken)],
                         axis=1).reshape(M, S)
        acc = acc + proj * (j == jj).astype(jnp.float32)
    return acc


def _make_kernel(B: int, W: int, M: int, S: int, O1: int):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def kernel(rlim_ref, ret_slot_ref, slot_ops_ref, R0_ref, P_ref,
               Rout_ref, dead_ref, R_scr, dead_scr):
        step = pl.program_id(0)
        nsteps = pl.num_programs(0)

        @pl.when(step == 0)
        def _init():
            R_scr[:] = R0_ref[:]
            dead_scr[0] = jnp.int32(-1)

        def do_return(k, _):
            r = step * B + k
            j = ret_slot_ref[k]
            G_all = _gather_G(slot_ops_ref, P_ref, k, W, O1)
            R = _fire_and_project(R_scr[:], G_all, j, W, M, S)

            @pl.when(jnp.logical_and(dead_scr[0] < 0,
                                     jnp.logical_and(jnp.sum(R) < 0.5,
                                                     r < rlim_ref[0])))
            def _mark_dead():
                dead_scr[0] = r

            R_scr[:] = R
            return 0

        jax.lax.fori_loop(0, B, do_return, 0)

        @pl.when(step == nsteps - 1)
        def _finish():
            Rout_ref[:] = R_scr[:]
            dead_ref[0] = dead_scr[0]

    return kernel


@functools.cache
def _walk_call(B: int, W: int, M: int, S: int, O1: int, R_pad: int,
               interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    kernel = _make_kernel(B, W, M, S, O1)
    call = pl.pallas_call(
        kernel,
        grid=(R_pad // B,),
        in_specs=[
            # the real (unpadded) return count, as a runtime scalar so
            # histories of different length share one compiled kernel
            pl.BlockSpec((1,), lambda i: (0,),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((B,), lambda i: (i,),
                         memory_space=pltpu.SMEM),
            # flat [B*W] — a 2-D SMEM window pads each row to the 1 KB
            # tile and blows the 1 MB SMEM budget
            pl.BlockSpec((B * W,), lambda i: (i,),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((M, S), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((O1, S, S), lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((M, S), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1,), lambda i: (0,),
                         memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((M, S), jnp.float32),
            jax.ShapeDtypeStruct((1,), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((M, S), jnp.float32),
            pltpu.SMEM((1,), jnp.int32),
        ],
        interpret=interpret,
    )

    def run(rlim, ret_slot, slot_ops, R0, P):
        # narrow wire, int32 on device: the upcasts live inside the
        # jitted program so the link carries only the narrow bytes;
        # the R0 seed may arrive bit-packed (8 configs per byte)
        if R0.dtype == jnp.uint8:
            R0 = jnp.unpackbits(R0, count=M * S).reshape(M, S) \
                    .astype(jnp.float32)
        return call(rlim, ret_slot.astype(jnp.int32),
                    slot_ops.astype(jnp.int32), R0, P)

    return jax.jit(run)


_BLOCK = 1024     # XLA tiles 1-D s32 SMEM operands at T(1024); the block
                  # shape must match or Mosaic rejects the layout


def walk_returns(P: np.ndarray, ret_slot: np.ndarray,
                 slot_ops: np.ndarray, R0_sm: np.ndarray, *,
                 interpret: bool = False,
                 fetch_R: bool = True) -> Tuple[int, Optional[np.ndarray]]:
    """Run the full returns walk in one kernel.

    ``P`` f32[O1, S, S] (last row all-zero sentinel); ``ret_slot``
    i32[R]; ``slot_ops`` i32[R, W]; ``R0_sm`` bool[S, M] (the engine's
    native layout). Returns ``(dead, R_final[S, M] bool)`` where
    ``dead`` is the first return index at which the config set emptied,
    or -1 if the history prefix is linearizable. With ``fetch_R=False``
    the final config set is not copied back (``None``) — the verdict
    needs only ``dead``, and on a tunneled device each host fetch is a
    blocking round-trip.
    """
    import jax

    O1, S, _ = P.shape
    R_real = int(ret_slot.shape[0])
    W = int(slot_ops.shape[1])
    M = R0_sm.shape[1]
    from jepsen_tpu.checkers.reach import _bucket

    B = _BLOCK
    # bucket the padded length (8 shapes per octave) so same-sized
    # histories share a compiled kernel; pad rows are cheap identities
    R_pad = max(B, _bucket(-(-R_real // B) * B, B))
    if R_pad != R_real:
        ret_slot = np.pad(ret_slot, (0, R_pad - R_real),
                          constant_values=-1)
        slot_ops = np.pad(slot_ops, ((0, R_pad - R_real), (0, 0)),
                          constant_values=-1)
    call = _walk_call(B, W, M, S, O1, R_pad, interpret)
    # one batched host->device transfer, not five round-trips — on the
    # narrow/bit-packed wire format (in-jit upcasts; round-5 int32/f32
    # with the diet opted out)
    def _dense_args():
        return (
            np.array([R_real], np.int32),
            np.ascontiguousarray(ret_slot, np.int32),
            np.ascontiguousarray(slot_ops.reshape(-1), np.int32),
            np.ascontiguousarray(R0_sm.T, np.float32),
            np.ascontiguousarray(P, np.float32))

    packed = transfer.packed_enabled()
    if packed:
        host_args = (
            np.array([R_real], np.int32),
            np.ascontiguousarray(ret_slot, transfer.idx_dtype(W)),
            np.ascontiguousarray(slot_ops.reshape(-1),
                                 transfer.idx_dtype(O1)),
            transfer.pack_bool(R0_sm.T),
            np.ascontiguousarray(P, np.float32))
    else:
        host_args = _dense_args()
    transfer.count_put(sum(a.nbytes for a in host_args),
                       4 + R_pad * 4 + R_pad * W * 4 + M * S * 4
                       + P.nbytes)
    args = jax.device_put(host_args)
    try:
        R_out, dead = call(*args)
    except Exception as e:                              # noqa: BLE001
        if not packed:
            raise
        # a packed-wire dispatch failed: retry the dense round-5 format
        # (same contract as the other engines); the re-upload's bytes
        # are counted — they really crossed. The ONE fallback record
        # lands only after the dense retry succeeds: a failure that
        # persists dense (backend capability, geometry) was never the
        # packed wire's fault and propagates unrecorded
        host_args = _dense_args()
        transfer.count_put(sum(a.nbytes for a in host_args), 0)
        R_out, dead = call(*jax.device_put(host_args))
        obs.engine_fallback("packed-xfer", type(e).__name__)
    return int(dead[0]), (np.asarray(R_out, bool).T if fetch_R else None)


# -- keyed batch: many independent keys in one kernel ------------------------
#
# The per-key (`jepsen.independent`) hot path. Instead of vmapping the
# walk with every key padded to the longest return stream (the XLA batch
# path), all keys' REAL returns are concatenated into one flat stream
# tagged with key ids; the kernel walks it sequentially, resetting the
# VMEM config set at each key boundary and recording each key's first
# death index into a K-sized SMEM output. Zero padding waste for skewed
# key sizes, one kernel launch total, and exact per-key dead indices
# (the vmapped XLA walk only brackets death within an unroll block).
# All keys share one transition tensor P: history-dependent per-key op
# alphabets are remapped into a union alphabet by the caller
# (``reach._union_alphabet``); only a union too large for the budgets
# falls back to the XLA path.

def _make_keyed_kernel(B: int, W: int, M: int, S: int, O1: int, K: int):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def kernel(ret_slot_ref, slot_ops_ref, key_ref, P_ref,
               dead_ref, R_scr, prev_scr):
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _init():
            prev_scr[0] = jnp.int32(-1)

            def ini(k, _):
                dead_ref[k] = jnp.int32(-1)
                return 0

            jax.lax.fori_loop(0, K, ini, 0)

        rows = jax.lax.broadcasted_iota(jnp.int32, (M, S), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (M, S), 1)
        R0 = jnp.logical_and(rows == 0, cols == 0).astype(jnp.float32)

        def do_return(b, _):
            r = step * B + b
            j = ret_slot_ref[b]
            key = key_ref[b]
            is_real = key >= 0

            @pl.when(jnp.logical_and(is_real, key != prev_scr[0]))
            def _new_key():
                R_scr[:] = R0
                prev_scr[0] = key

            G_all = _gather_G(slot_ops_ref, P_ref, b, W, O1)
            R = _fire_and_project(R_scr[:], G_all, j, W, M, S)

            kk = jnp.maximum(key, 0)

            @pl.when(jnp.logical_and(
                    is_real,
                    jnp.logical_and(jnp.sum(R) < 0.5, dead_ref[kk] < 0)))
            def _mark_dead():
                dead_ref[kk] = r

            R_scr[:] = R
            return 0

        jax.lax.fori_loop(0, B, do_return, 0)

    return kernel


@functools.cache
def _keyed_call(B: int, W: int, M: int, S: int, O1: int, N_pad: int,
                K_pad: int, interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    kernel = _make_keyed_kernel(B, W, M, S, O1, K_pad)
    call = pl.pallas_call(
        kernel,
        grid=(N_pad // B,),
        in_specs=[
            pl.BlockSpec((B,), lambda i: (i,),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((B * W,), lambda i: (i,),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((B,), lambda i: (i,),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((O1, S, S), lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            # constant index map: the block stays resident across the
            # sequential grid, accumulating per-key verdicts
            pl.BlockSpec((K_pad,), lambda i: (0,),
                         memory_space=pltpu.SMEM),
        ],
        out_shape=[jax.ShapeDtypeStruct((K_pad,), jnp.int32)],
        scratch_shapes=[
            pltpu.VMEM((M, S), jnp.float32),
            pltpu.SMEM((1,), jnp.int32),
        ],
        interpret=interpret,
    )

    def run(ret_slot, slot_ops, key_id, P):
        # in-jit upcasts off the narrow wire (see _walk_call.run)
        return call(ret_slot.astype(jnp.int32),
                    slot_ops.astype(jnp.int32),
                    key_id.astype(jnp.int32), P)

    return jax.jit(run)


def walk_returns_keyed(P: np.ndarray, ret_slot: np.ndarray,
                       slot_ops: np.ndarray, key_id: np.ndarray,
                       n_keys: int, M: int, *,
                       interpret: bool = False) -> np.ndarray:
    """Walk the concatenation of ``n_keys`` return streams in one kernel.

    ``ret_slot`` i32[N] / ``slot_ops`` i32[N, W] / ``key_id`` i32[N]
    (non-decreasing, the key owning each return) are the flat
    concatenation of all keys' real returns. Returns ``dead[n_keys]``:
    for each key the FLAT index of the first return at which its config
    set emptied, or -1 if that key's history is linearizable.
    """
    import jax

    from jepsen_tpu.checkers.reach import _bucket

    O1, S, _ = P.shape
    N = int(ret_slot.shape[0])
    W = int(slot_ops.shape[1])
    B = _BLOCK
    N_pad = max(B, _bucket(-(-max(N, 1) // B) * B, B))
    K_pad = max(8, _bucket(n_keys, 8))
    if N_pad != N:
        ret_slot = np.pad(ret_slot, (0, N_pad - N), constant_values=-1)
        slot_ops = np.pad(slot_ops, ((0, N_pad - N), (0, 0)),
                          constant_values=-1)
        key_id = np.pad(key_id, (0, N_pad - N), constant_values=-1)
    call = _keyed_call(B, W, M, S, O1, N_pad, K_pad, interpret)
    def _dense_args():
        return (
            np.ascontiguousarray(ret_slot, np.int32),
            np.ascontiguousarray(slot_ops.reshape(-1), np.int32),
            np.ascontiguousarray(key_id, np.int32),
            np.ascontiguousarray(P, np.float32))

    packed = transfer.packed_enabled()
    if packed:
        host_args = (
            np.ascontiguousarray(ret_slot, transfer.idx_dtype(W)),
            np.ascontiguousarray(slot_ops.reshape(-1),
                                 transfer.idx_dtype(O1)),
            np.ascontiguousarray(key_id, transfer.idx_dtype(K_pad)),
            np.ascontiguousarray(P, np.float32))
    else:
        host_args = _dense_args()
    transfer.count_put(sum(a.nbytes for a in host_args),
                       N_pad * 4 + N_pad * W * 4 + N_pad * 4 + P.nbytes)
    args = jax.device_put(host_args)
    try:
        (dead,) = call(*args)
    except Exception as e:                              # noqa: BLE001
        if not packed:
            raise
        # same packed-wire contract as walk_returns: dense retry with
        # re-upload bytes counted, ONE fallback record only once the
        # dense retry succeeds (a dense failure too means the packed
        # wire was not at fault — propagate unrecorded)
        host_args = _dense_args()
        transfer.count_put(sum(a.nbytes for a in host_args), 0)
        (dead,) = call(*jax.device_put(host_args))
        obs.engine_fallback("packed-xfer", type(e).__name__)
    return np.asarray(dead)[:n_keys]
