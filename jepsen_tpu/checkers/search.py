"""Search control — upstream ``knossos/src/knossos/search.clj``
(SURVEY.md §2.2): deadline and abort management plus the memory watchdog
that aborts a search before the process dies of heap exhaustion (the
upstream watches JVM heap; here ``/proc/meminfo`` MemAvailable).

Engines poll :meth:`SearchControl.should_abort` (the Python search) or
share the ctypes flag (:class:`~jepsen_tpu.checkers.wgl_native.AbortFlag`)
via :meth:`SearchControl.bind_native`.
"""
from __future__ import annotations

import threading
import time as _time
from typing import Any, Callable, Dict, List, Optional


def mem_available_bytes() -> Optional[int]:
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable:"):
                    return int(line.split()[1]) * 1024
    # jtlint: ok fallback — meminfo probe: None disables the watchdog, checking unaffected
    except OSError:
        pass
    return None


class SearchControl:
    """Cooperative abort: deadline, explicit abort, low-memory watchdog."""

    def __init__(self, time_limit: Optional[float] = None,
                 min_free_bytes: int = 256 << 20,
                 watchdog_interval: float = 0.5):
        self._deadline = (None if time_limit is None
                          else _time.monotonic() + time_limit)
        self._aborted = threading.Event()
        self._cause: Optional[str] = None
        self._min_free = min_free_bytes
        self._natives: List[Any] = []
        self._watchdog: Optional[threading.Thread] = None
        self._interval = watchdog_interval
        self._stop = threading.Event()

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "SearchControl":
        if self._watchdog is None:
            self._watchdog = threading.Thread(
                target=self._watch, daemon=True, name="jepsen-search-watchdog")
            self._watchdog.start()
        return self

    def close(self) -> None:
        self._stop.set()

    def __enter__(self) -> "SearchControl":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # -- abort surface -------------------------------------------------------
    def abort(self, cause: str = "aborted") -> None:
        if not self._aborted.is_set():
            self._cause = cause
            self._aborted.set()
            for flag in self._natives:
                flag.abort()

    def should_abort(self) -> bool:
        if self._aborted.is_set():
            return True
        if (self._deadline is not None
                and _time.monotonic() > self._deadline):
            self.abort("timeout")
            return True
        return False

    @property
    def cause(self) -> Optional[str]:
        return self._cause

    def bind_native(self, flag: Any) -> Any:
        """Register a native AbortFlag to be tripped on abort."""
        self._natives.append(flag)
        if self._aborted.is_set():
            flag.abort()
        return flag

    # -- watchdog ------------------------------------------------------------
    def _watch(self) -> None:
        while not self._stop.wait(self._interval):
            if self.should_abort():
                return
            free = mem_available_bytes()
            if free is not None and free < self._min_free:
                self.abort("low-memory")
                return
