"""Online (live) linearizability monitoring — no upstream analogue.

Upstream Jepsen is strictly post-hoc: the history is analyzed after the
run ends (``jepsen.core/run!`` → ``checker/check-safe``, SURVEY.md §3.1),
so a test that violated linearizability in its first second still runs to
completion before anyone finds out. The TPU engine is fast enough
(~400k ops verified/s — BASELINE.md) to simply re-check the ENTIRE
recorded prefix on a cadence while the test is still running, failing
fast the moment a violation appears.

Soundness:

- *No false alarms.* A flush checks the prefix of ops recorded so far;
  still-running invocations enter the analysis as crashed ops (they may
  linearize at any point or never — both explored), and unresolved read
  values are ``None`` wildcards. Both are over-approximations of the
  constraints the finished history will impose, so the linearizations
  considered form a superset of the true ones: a prefix reported invalid
  is genuinely invalid.
- *Fail-fast is permanent.* Linearizability is prefix-closed: any
  linearization of the full history restricted to a prefix linearizes
  that prefix (later-invoked ops cannot fire before earlier returns). An
  invalid prefix can never be repaired by more ops, so the monitor stops
  looking after the first violation and the runner may abort the test.
- *Eventually exact.* Constraints a flush under-applied (pending values)
  are applied by later flushes and by the final post-hoc check, which
  remains the source of truth.
"""
from __future__ import annotations

import logging
import threading
import time as _time
from typing import Any, Callable, Dict, List, Optional

from jepsen_tpu.models import Model
from jepsen_tpu.op import Op

log = logging.getLogger("jepsen.online")


class OnlineLinearizable:
    """Background prefix re-checker. Wire :meth:`observe` as the history
    observer (``core.History(observer=...)``), :meth:`start` /
    :meth:`stop` around the run, and pass ``on_violation`` to abort the
    test early (the runner sets its stop flag there)."""

    def __init__(self, model: Model, *,
                 interval_s: float = 1.0,
                 min_new_ops: int = 128,
                 on_violation: Optional[Callable[[Dict[str, Any]], None]]
                 = None,
                 **checker_kw: Any):
        self.model = model
        self.interval_s = interval_s
        self.min_new_ops = min_new_ops
        self.on_violation = on_violation
        self.checker_kw = checker_kw
        self._ops: List[Op] = []
        self._lock = threading.Lock()
        self._flush_lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._checked_upto = 0          # longest CONCLUSIVELY checked prefix
        self._inconclusive_tail = 0
        self._flushes = 0
        self.violation: Optional[Dict[str, Any]] = None

    # -- producer side (worker threads, via History observer) ---------------

    def observe(self, op: Op) -> None:
        with self._lock:
            self._ops.append(op)
        if len(self._ops) - self._checked_upto >= self.min_new_ops:
            self._wake.set()

    # -- checking ------------------------------------------------------------

    def flush(self) -> Optional[Dict[str, Any]]:
        """Check the current prefix; returns the violation dict once one
        is found (then sticky — no further work happens). Serialized: the
        monitor thread and a caller's stop() may both land here."""
        with self._flush_lock:
            return self._flush_locked()

    def _flush_locked(self) -> Optional[Dict[str, Any]]:
        if self.violation is not None:
            return self.violation
        with self._lock:
            prefix = list(self._ops)
        if (len(prefix) <= self._checked_upto
                and not self._inconclusive_tail):
            return None
        from jepsen_tpu.checkers.facade import check_safe, linearizable

        kw = dict(self.checker_kw)
        if "algorithm" not in kw:
            # low-latency default: the C++ WGL engine has no per-shape
            # compile cost, so flushes keep up with fast op streams; a
            # time limit bounds its exponential worst case ("unknown"
            # flushes are retried at the next cadence tick). The device
            # engine remains the post-hoc source of truth.
            from jepsen_tpu.checkers import wgl_native
            if wgl_native.available():
                kw["algorithm"] = "wgl-native"
                kw.setdefault("time_limit", max(5.0, 5 * self.interval_s))
            else:
                kw["algorithm"] = "auto"
        checker = linearizable(self.model, **kw)
        res = check_safe(checker, None, prefix)
        self._flushes += 1
        if res.get("valid") is True:
            self._checked_upto = len(prefix)
            self._inconclusive_tail = 0
        elif res.get("valid") is False:
            self._checked_upto = len(prefix)
            self._inconclusive_tail = 0
            res["prefix-ops"] = len(prefix)
            res["detected-at-flush"] = self._flushes
            self.violation = res
            log.warning("online check: violation after %d ops (%s)",
                        len(prefix), res.get("op"))
            if self.on_violation is not None:
                try:
                    self.on_violation(res)
                except Exception:                       # noqa: BLE001
                    pass
        else:
            # inconclusive (engine timeout / overflow): do NOT advance —
            # these ops are re-checked next flush, and result() must not
            # claim them verified
            self._inconclusive_tail = len(prefix) - self._checked_upto
        return self.violation

    # -- thread lifecycle ----------------------------------------------------

    def start(self) -> "OnlineLinearizable":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="jepsen-online-check")
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set() and self.violation is None:
            self._wake.wait(self.interval_s)
            self._wake.clear()
            if self._stop.is_set():
                break
            try:
                self.flush()
            except Exception as e:                      # noqa: BLE001
                log.warning("online check flush failed: %s", e)

    def stop(self) -> Dict[str, Any]:
        """Stop the thread, run one final flush, and return
        :meth:`result`."""
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(30)
        try:
            self.flush()
        except Exception as e:                          # noqa: BLE001
            log.warning("online check final flush failed: %s", e)
        return self.result()

    def result(self) -> Dict[str, Any]:
        if self.violation is not None:
            out = dict(self.violation)
            out["valid"] = False
            return out
        out: Dict[str, Any] = {"valid": True,
                               "ops-checked": self._checked_upto,
                               "flushes": self._flushes}
        if self._inconclusive_tail:
            # the last flush(es) were inconclusive: the tail was never
            # verified, so the monitor's verdict is only "no violation
            # SEEN", not a clean bill
            out["valid"] = "unknown"
            out["unchecked-tail-ops"] = self._inconclusive_tail
        return out
