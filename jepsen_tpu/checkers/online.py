"""Online (live) linearizability monitoring — no upstream analogue.

Upstream Jepsen is strictly post-hoc: the history is analyzed after the
run ends (``jepsen.core/run!`` → ``checker/check-safe``, SURVEY.md §3.1),
so a test that violated linearizability in its first second still runs to
completion before anyone finds out. This monitor verifies the history
WHILE it streams, failing fast the moment a violation appears.

Two flush strategies:

- ``mode="incremental"`` (default): the monitor carries the dense
  reachability config set ``R[S, M]`` (exactly the state of
  :mod:`jepsen_tpu.checkers.reach`'s walk) across flushes and advances
  it only through NEW return events, making total monitoring work O(n)
  over the whole run instead of the O(n²) of re-checking every prefix.
  The carried advance is restricted to the *settled* prefix — return
  events whose entire pending map is resolved (completed with a known
  value, failed, or crashed) — because an op's transition is not known
  until its value is (a concurrent read may linearize before its return,
  but only with the value it eventually returns). The unsettled tail is
  usually the in-flight window (≤ concurrency ops) — though one
  long-pending op queues every later return behind it — and a bounded
  prefix of it is checked each flush from a copy of the carried set
  with unresolved ops treated as crashed: an over-approximation, so a
  tail alarm is still sound. On
  anything the dense representation cannot hold (slot overflow, state
  explosion, model without a finite memo) the monitor permanently falls
  back to the re-check strategy below. Each flush's settled batch is
  walked by the bit-packed C++ engine (``native/preproc.cpp
  jt_walk_dense``, ~1 µs/return). Measured: a 100k-op cas stream
  monitors end-to-end in ~1.2 s of host time (~86k ops/s sustained at
  a 256-event flush cadence, each return walked exactly once; round 2's
  per-return NumPy walk took ~8.8 s), where prefix re-checking at a
  128-op cadence does ~39M op-re-checks plus a device round-trip per
  flush.
- ``mode="recheck"``: re-check the entire recorded prefix on each
  cadence tick with the production engines. Simple and exact, but total
  work grows quadratically with history length.

Soundness (both modes):

- *No false alarms.* Still-running invocations enter the analysis as
  crashed ops (they may linearize at any point or never — both
  explored), and unresolved read values are ``None`` wildcards. Both
  over-approximate the constraints the finished history will impose, so
  a prefix reported invalid is genuinely invalid.
- *Fail-fast is permanent.* Linearizability is prefix-closed: an
  invalid prefix can never be repaired by more ops, so the monitor
  stops after the first violation and the runner may abort the test.
- *Eventually exact.* At :meth:`OnlineLinearizable.stop` every op has
  resolved (run over: still-pending means crashed), so the incremental
  monitor's final verdict is the exact full-history verdict; in
  recheck mode the final post-hoc check remains the source of truth
  for any inconclusive tail.
"""
from __future__ import annotations

import heapq
import logging
import threading
import time as _time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from jepsen_tpu.models import Model
from jepsen_tpu.op import FAIL, INFO, INVOKE, OK, Op
from jepsen_tpu.util import hashable

log = logging.getLogger("jepsen.online")


class _Binding:
    """One invocation's lifetime: its slot, invoke op (for reporting),
    and resolution status. The op's transition id is internable only
    once its value is known (reads carry the value on the completion)."""

    __slots__ = ("slot", "inv", "status", "value", "oid")

    def __init__(self, slot: int, inv: Op):
        self.slot = slot
        self.inv = inv
        self.status = "pending"         # pending | ok | fail | crashed
        self.value = inv.value          # Entry rule: completion value wins
        self.oid = -1                   # interned op id once resolved
                                        # (alphabet ids are append-only)

    def resolve(self, kind: str, value: Any) -> None:
        self.status = kind
        if kind == "ok" and value is not None:
            self.value = value

    @property
    def resolved(self) -> bool:
        return self.status != "pending"


class _Overflow(Exception):
    """The dense representation cannot hold this run — permanent fallback
    to recheck mode."""


def _walk_return(R: np.ndarray, rows: np.ndarray, jr: int,
                 P: np.ndarray) -> np.ndarray:
    """One return event on the dense config set, NumPy edition of
    :mod:`jepsen_tpu.checkers.reach`'s fire-to-fixpoint + projection:
    ``R`` bool[S, M]; ``rows[j]`` the pending op in slot j (or -1);
    ``jr`` the returning slot; ``P`` bool[O, S, S]."""
    M = R.shape[1]
    m = np.arange(M)
    while True:
        new = R.copy()
        for j, o in enumerate(rows):
            if o < 0:
                continue
            bit = 1 << j
            clear = np.nonzero((m & bit) == 0)[0]
            img = P[o].T @ R[:, clear]          # fired images of bit-clear
            new[:, clear | bit] |= img
        if (new == R).all():
            break
        R = new
    bit = 1 << jr
    kept = np.nonzero((m & bit) != 0)[0]
    out = np.zeros_like(R)
    out[:, kept ^ bit] = R[:, kept]
    return out


class IncrementalEngine:
    """O(n) streaming linearizability state: the dense config set carried
    across flushes, advanced through settled return events only (module
    docstring). A flush's settleable returns are walked in ONE call to
    the bit-packed C++ walk (:meth:`_walk_batch_native`,
    ``native/preproc.cpp jt_walk_dense`` — ~1 µs/return with zero
    dispatch cost; the accelerator is never involved: the [S, M] set is
    a few machine words and one tunnel round-trip costs more than a
    whole flush). Without the native lib the per-return NumPy fixpoint
    (:func:`_walk_return`) remains, and doubles as the differential
    reference in ``tests/test_online.py``."""

    def __init__(self, model: Model, *, max_states: int = 100_000,
                 max_slots: int = 20, max_dense: int = 1 << 22):
        self.model = model
        self.max_states = max_states
        self.max_slots = max_slots
        self.max_dense = max_dense
        self.alphabet: Dict[Tuple[Any, Any], int] = {}
        self.alpha_ops: List[Op] = []
        self.memo = None
        self.P: Optional[np.ndarray] = None      # bool [O, S, S]
        self.W = 1
        self.R: Optional[np.ndarray] = None      # bool [S, 2^W]
        self._free: List[int] = []
        self._hi = 0
        self._proc: Dict[Any, _Binding] = {}     # live invocations
        self._crashed: List[_Binding] = []       # forever-pending
        # FIFO of return events awaiting settlement, in real-time order:
        # (returning binding, pending-map snapshot of binding refs)
        self._queue: deque = deque()
        self.settled_returns = 0
        self.walked_events = 0                   # O(n) telemetry for tests
        self.violation: Optional[Dict[str, Any]] = None

    # -- alphabet / memo ------------------------------------------------------

    def _intern_batch(self, keys) -> None:
        """Add every unseen ``(f, value)`` to the alphabet with ONE memo
        rebuild + state re-encode for the whole batch (a flush that
        surfaces k new pairs must not pay k O(S²·O) rebuilds).
        Transient wildcard entries from the tail alarm (an unresolved
        read's ``(f, None)``) are bounded — one per function name, the
        same entry a genuinely crashed read would intern."""
        fresh = []
        seen = set()
        for f, v in keys:
            k = (f, hashable(v))
            if k not in self.alphabet and k not in seen:
                seen.add(k)
                fresh.append((k, f, v))
        if not fresh:
            return
        from jepsen_tpu.models.memo import StateExplosion, memo_ops
        from jepsen_tpu.op import invoke as mk_invoke
        for k, f, v in fresh:
            self.alphabet[k] = len(self.alpha_ops)
            self.alpha_ops.append(mk_invoke(0, f, v))
        old_memo, old_R = self.memo, self.R
        try:
            self.memo = memo_ops(self.model, tuple(self.alpha_ops),
                                 max_states=self.max_states)
        except StateExplosion as e:
            raise _Overflow(str(e)) from e
        S = self.memo.n_states
        if S * (1 << self.W) > self.max_dense:
            raise _Overflow(f"dense config space {S}x{1 << self.W}")
        T = self.memo.table
        P = np.zeros((len(self.alpha_ops), S, S), bool)
        s = np.arange(S)
        for o in range(T.shape[1]):
            okc = T[:, o] >= 0
            P[o, s[okc], T[okc, o]] = True
        self.P = P
        R = np.zeros((S, 1 << self.W), bool)
        if old_R is None:
            R[0, 0] = True
        else:
            # re-encode carried states: the wider-alphabet BFS reaches
            # a superset of the old states
            new_id = {st: i for i, st in enumerate(self.memo.states)}
            for sid in np.nonzero(old_R.any(axis=1))[0]:
                R[new_id[old_memo.states[sid]]] |= old_R[sid]
        self.R = R

    def _intern_rows(self, b: _Binding, snap: List[_Binding],
                     n_crashed: int) -> np.ndarray:
        """Materialize a return event's pending map to op-id rows —
        called only once every binding in it is resolved (or, for the
        tail alarm, with unresolved ops as crashed wildcards).
        ``n_crashed`` is the crashed-list length at the return's feed
        time (crashes recorded later were invoked later and are NOT in
        this event's pending map). Interning happens BEFORE any caller
        copies ``self.R``: it may rebuild the state coding."""
        members = snap + self._crashed[:n_crashed] + [b]
        self._intern_batch([(x.inv.f, x.value)
                            for x in members
                            if x.status != "fail" and x.oid < 0])
        rows = np.full(self.W, -1, np.int64)
        for x in members:
            if x.status == "fail":
                continue            # stripped, exactly like post-hoc
            if x.oid >= 0:
                rows[x.slot] = x.oid
                continue
            oid = self.alphabet[(x.inv.f, hashable(x.value))]
            if x.resolved:
                # ids are append-only, so a resolved binding's id is
                # final; unresolved tail-alarm wildcards stay uncached
                # (their value may change at resolution)
                x.oid = oid
            rows[x.slot] = oid
        return rows

    def _grow_slots(self, slot: int) -> None:
        if slot < self.W:
            return
        if slot >= self.max_slots:
            raise _Overflow(f"history needs > {self.max_slots} slots")
        W2 = slot + 1
        S = self.R.shape[0] if self.R is not None else 2
        if S * (1 << W2) > self.max_dense:
            raise _Overflow(f"dense config space {S}x{1 << W2}")
        if self.R is not None:
            # zero-embed: new slots are free, their bits 0 in every config
            R2 = np.zeros((self.R.shape[0], 1 << W2), bool)
            R2[:, :self.R.shape[1]] = self.R
            self.R = R2
        self.W = W2

    # -- ingestion ------------------------------------------------------------

    def feed(self, op: Op) -> None:
        if op.process == "nemesis":
            return
        if op.type == INVOKE:
            if op.process in self._proc:
                raise _Overflow(f"double invoke by {op.process}")
            slot = heapq.heappop(self._free) if self._free else self._hi
            if slot == self._hi:
                self._hi += 1
            self._grow_slots(slot)
            self._proc[op.process] = _Binding(slot, op)
            return
        b = self._proc.pop(op.process, None)
        if b is None:
            return                      # completion without invoke: ignore
        if op.type == OK:
            b.resolve("ok", op.value)
            # pending at this return: live invocations + the
            # forever-crashed ops so far. The crashed list only appends,
            # so its membership at THIS moment is captured by its length
            # alone — an O(1) snapshot instead of copying an ever-growing
            # list per return. The slot frees NOW (walk order still
            # projects it correctly: a reused slot's new op cannot fire
            # before this return's event is walked, so its bit is still
            # clear then)
            self._queue.append((b, list(self._proc.values()),
                                len(self._crashed)))
            heapq.heappush(self._free, b.slot)
        elif op.type == FAIL:
            # definitely no effect: stripped. The carried set holds no
            # trace of it — settlement requires every snapshot binding
            # resolved, so no return event that saw this op pending has
            # been walked yet; those still queued skip it at settlement
            # (exactly the post-hoc strip)
            b.resolve("fail", None)
            heapq.heappush(self._free, b.slot)
        elif op.type == INFO:
            # crashed: resolved (fires anytime or never), holds its slot
            # forever like the post-hoc walk's forever-pending entries
            b.resolve("crashed", op.value)
            self._crashed.append(b)

    # -- the walk -------------------------------------------------------------

    def _intern_items(self, items) -> List[np.ndarray]:
        """Intern every member of every queued item in ONE batch (the
        memo may rebuild once, not per return), then materialize each
        item's pending-op rows."""
        keys = []
        for b, snap, n_crashed in items:
            keys.extend((x.inv.f, x.value)
                        for x in snap + self._crashed[:n_crashed] + [b]
                        if x.status != "fail" and x.oid < 0)
        self._intern_batch(keys)
        return [self._intern_rows(b, snap, n_crashed)
                for b, snap, n_crashed in items]

    def _walk_batch_native(self, R0: np.ndarray, rows_list, slots
                           ) -> Optional[Tuple[np.ndarray, int]]:
        """Walk a batch of return events through the bit-packed C++
        walk (``preproc_native.walk_dense``): the [S, M] set packs to
        S·M/64 machine words, so word-parallel C++ does ~1 µs/return
        with zero dispatch or compile cost (the per-return NumPy
        fixpoint is ~170 µs/return, and an XLA CPU walk pays ~ms of
        dispatch per flush plus a compile per geometry). Returns
        ``(R_final, dead_idx)`` (``dead_idx = -1`` when the set
        survived — the exact index comes straight from the walk), or
        None when the native lib is unavailable."""
        from jepsen_tpu.checkers import preproc_native

        if not preproc_native.available():
            return None
        L = len(rows_list)
        W, M = self.W, 1 << self.W
        R_words = _pack_words(R0, M)
        rows_arr = np.asarray(rows_list, np.int32).reshape(L, W)
        dead = preproc_native.walk_dense(
            self.memo.table, R_words, W,
            np.asarray(slots, np.int32), rows_arr)
        if dead is None:
            return None
        return _unpack_words(R_words, M), int(dead)

    def advance(self, run_over: bool = False) -> Optional[Dict[str, Any]]:
        """Walk the settled prefix of queued returns; with ``run_over``
        every still-pending op resolves as crashed first (the run is
        over — the verdict becomes the exact full-history one). Returns
        the violation, if one is found."""
        if self.violation is not None:
            return self.violation
        if run_over:
            for p, b in list(self._proc.items()):
                b.resolve("crashed", b.inv.value)
                del self._proc[p]
                self._crashed.append(b)
        # collect every currently-settleable return, then walk them in
        # one XLA call (per-return NumPy below the dispatch break-even)
        items = []
        while self._queue:
            b, snap, n_crashed = self._queue[0]
            if not all(x.resolved for x in snap):
                break
            self._queue.popleft()
            items.append((b, snap, n_crashed))
        if not items:
            return None
        rows_list = self._intern_items(items)
        slots = np.fromiter((b.slot for b, _, _ in items), np.int32,
                            count=len(items))
        walked = self._walk_batch_native(self.R, rows_list, slots)
        if walked is None:              # no native lib: NumPy walk
            for i, (b, _, _) in enumerate(items):
                self.R = _walk_return(self.R, rows_list[i], b.slot,
                                      self.P)
                self.settled_returns += 1
                self.walked_events += 1
                if not self.R.any():
                    self.violation = self._violation_at(b, self.R)
                    return self.violation
            return None
        R_final, dead = walked
        if dead < 0:
            self.R = R_final
            self.settled_returns += len(items)
            self.walked_events += len(items)
            return None
        self.R = R_final
        self.settled_returns += dead + 1
        self.walked_events += dead + 1
        # items[dead+1:] were dequeued but never walked; they are NOT
        # re-queued because a violation is terminal for this engine
        # (every later advance() short-circuits on self.violation, and
        # there is deliberately no reset/continue path — a monitor that
        # has proven non-linearizability has nothing more to decide)
        self.violation = self._violation_at(items[dead][0], R_final)
        return self.violation

    # per-flush cap on the tail walk: the queue can grow far beyond the
    # in-flight window when ONE op stays pending for a long time (every
    # later return blocks behind it), and re-walking the whole queue
    # each flush would be the O(n²) this engine exists to avoid. The
    # oldest _TAIL_CAP events still give a sound early alarm; deeper
    # events wait for settlement (or the exact final flush).
    _TAIL_CAP = 512

    def tail_alarm(self) -> Optional[Dict[str, Any]]:
        """Check (a bounded prefix of) the unsettled tail from a copy of
        the carried set with unresolved ops treated as crashed (they may
        fire anytime or never — a sound over-approximation of any
        eventual completion, so an alarm here is a real violation).
        Early detection only; the carried state is untouched."""
        if self.violation is not None or not self._queue:
            return None
        items = list(self._queue)[:self._TAIL_CAP]
        # intern everything FIRST: interning may re-encode self.R
        rows_list = self._intern_items(items)
        slots = np.fromiter((b.slot for b, _, _ in items), np.int32,
                            count=len(items))
        walked = self._walk_batch_native(self.R, rows_list, slots)
        if walked is None:              # no native lib: NumPy walk
            R = self.R.copy()
            for i, (b, _, _) in enumerate(items):
                R = _walk_return(R, rows_list[i], b.slot, self.P)
                if not R.any():
                    self.violation = self._violation_at(b, R)
                    return self.violation
            return None
        R_final, dead = walked
        if dead >= 0:
            self.violation = self._violation_at(items[dead][0], R_final)
            return self.violation
        return None

    def _violation_at(self, b: _Binding, R) -> Dict[str, Any]:
        op = b.inv.with_(type=OK, value=b.value)
        return {"valid": False, "engine": "online-incremental",
                "op": op.to_dict(),
                "settled-returns": self.settled_returns}

    def in_flight(self) -> int:
        """Returns not yet conclusively walked + live invocations (the
        monitor's unsettled window)."""
        return len(self._queue) + len(self._proc)


def _pack_words(R: np.ndarray, M: int) -> np.ndarray:
    """Bit-pack the mask axis of a bool [S, M] set into u64 words."""
    packed8 = np.packbits(R, axis=1, bitorder="little")
    n_words = max(1, -(-M // 64))
    buf = np.zeros((R.shape[0], n_words * 8), np.uint8)
    buf[:, :packed8.shape[1]] = packed8
    return np.ascontiguousarray(buf).view(np.uint64)


def _unpack_words(words: np.ndarray, M: int) -> np.ndarray:
    return np.unpackbits(words.view(np.uint8), axis=1,
                         bitorder="little")[:, :M].astype(bool)


_TCODE = {INVOKE: 0, OK: 1, FAIL: 2, INFO: 3}
_SCALAR_T = (int, str, bool, float)


class NativeStreamEngine:
    """The incremental monitor with its per-op bookkeeping in C++
    (``native/preproc.cpp jt_mon_*`` via
    :class:`~jepsen_tpu.checkers.preproc_native.Monitor`): profiling
    the Python :class:`IncrementalEngine` on a 100k-op stream showed
    ~95% of its ~1.9 s was host object churn — per-return snapshot
    lists, per-member interning (428k ``hashable`` calls), per-op dict
    traffic — and only ~0.1 s the actual bit-packed walk. Here
    ``feed`` just buffers; ``advance`` drains the buffer into three
    int arrays, makes ONE native feed call (slot assignment, settle
    queue, snapshots) and ONE native advance call (settled-returns
    walk), leaving Python only value interning (model-dependent) and
    the carried set ``R`` (re-encoded on the rare memo/W growth).
    Same soundness story and same verdicts as IncrementalEngine
    (differentially tested in ``tests/test_online.py`` and the
    cross-engine fuzzer); measured ~6-8x faster end-to-end. The
    accelerator is deliberately NOT involved: one tunnel round trip
    costs more than walking an entire flush, and per-flush XLA
    dispatch lost on every axis measured in round 3 (BASELINE.md)."""

    _TAIL_CAP = 512

    def __init__(self, model: Model, *, max_states: int = 100_000,
                 max_slots: int = 20, max_dense: int = 1 << 22):
        from jepsen_tpu.checkers import preproc_native
        self.model = model
        self.max_states = max_states
        self.max_slots = max_slots
        self.max_dense = max_dense
        self._mon = preproc_native.Monitor(max_slots)
        self.alphabet: Dict[Tuple[Any, Any], int] = {}
        self.alpha_ops: List[Op] = []
        self.memo = None
        self.W = 1
        self.R: Optional[np.ndarray] = None      # bool [S, 2^W]
        self._buf: List[Op] = []
        self._live_inv: Dict[Any, Tuple[int, Op]] = {}
        self._bind_ops: List[Op] = []            # bind id -> invoke op
        self._bind_val: Dict[int, Any] = {}      # bind id -> final value
        self._procmap: Dict[Any, int] = {}       # non-int process ids
        self._memo_dirty = False
        self.settled_returns = 0
        self.walked_events = 0
        self.violation: Optional[Dict[str, Any]] = None

    # -- interning ------------------------------------------------------------

    def _pkey(self, p) -> int:
        # disjoint encodings: genuine int processes land on evens,
        # interned non-int processes on odds — a history mixing
        # process "a" with process -1 can never collide in the native
        # live map
        if isinstance(p, int):
            return p * 2
        v = self._procmap.get(p)
        if v is None:
            v = len(self._procmap) * 2 + 1
            self._procmap[p] = v
        return v

    def _oid(self, f: str, v: Any) -> int:
        # fast path: scalar values (and tuples of scalars — cas pairs)
        # ARE their hashable form, skipping the recursive converter
        # that dominated the Python engine
        tv = type(v)
        if v is None or tv in _SCALAR_T:
            k = (f, v)
        elif tv is tuple and all(
                x is None or type(x) in _SCALAR_T for x in v):
            k = (f, v)
        else:
            k = (f, hashable(v))
        o = self.alphabet.get(k)
        if o is None:
            from jepsen_tpu.op import invoke as mk_invoke
            o = len(self.alpha_ops)
            self.alphabet[k] = o
            self.alpha_ops.append(mk_invoke(0, f, v))
            self._memo_dirty = True
        return o

    # -- memo / geometry growth ----------------------------------------------

    def _rebuild_memo(self) -> None:
        from jepsen_tpu.models.memo import StateExplosion, memo_ops
        old_memo, old_R = self.memo, self.R
        try:
            self.memo = memo_ops(self.model, tuple(self.alpha_ops),
                                 max_states=self.max_states)
        except StateExplosion as e:
            raise _Overflow(str(e)) from e
        S = self.memo.n_states
        if S * (1 << self.W) > self.max_dense:
            raise _Overflow(f"dense config space {S}x{1 << self.W}")
        R = np.zeros((S, 1 << self.W), bool)
        if old_R is None:
            R[0, 0] = True
        else:
            new_id = {st: i for i, st in enumerate(self.memo.states)}
            for sid in np.nonzero(old_R.any(axis=1))[0]:
                R[new_id[old_memo.states[sid]]] |= old_R[sid]
        self.R = R
        self._memo_dirty = False

    def _grow_W(self, W2: int) -> None:
        S = self.R.shape[0] if self.R is not None else 2
        if S * (1 << W2) > self.max_dense:
            raise _Overflow(f"dense config space {S}x{1 << W2}")
        if self.R is not None:
            R2 = np.zeros((self.R.shape[0], 1 << W2), bool)
            R2[:, :self.R.shape[1]] = self.R
            self.R = R2
        self.W = W2

    def _feed_native(self, types, procs, oids) -> None:
        W_new = self._mon.feed(types, procs, oids)
        if W_new == -1:
            raise _Overflow("double invoke")
        if W_new == -2:
            raise _Overflow(f"history needs > {self.max_slots} slots")
        if self.memo is None or self._memo_dirty:
            self._rebuild_memo()
        if W_new > self.W:
            self._grow_W(int(W_new))

    # -- ingestion ------------------------------------------------------------

    def feed(self, op: Op) -> None:
        self._buf.append(op)

    def feed_many(self, ops: List[Op]) -> None:
        self._buf.extend(ops)

    def _drain(self) -> None:
        if not self._buf:
            return
        ops, self._buf = self._buf, []
        n = len(ops)
        types = np.empty(n, np.int32)
        procs = np.empty(n, np.int64)
        oids = np.full(n, -1, np.int32)
        # locals for the per-op loop: this runs once per appended op
        # on the session hot path, where bound-method and attribute
        # re-lookup is a measurable fraction of the stage cost
        tcode_get = _TCODE.get
        oid = self._oid
        pkey = self._pkey
        live_inv = self._live_inv
        live_pop = live_inv.pop
        bind_ops = self._bind_ops
        bind_val = self._bind_val
        m = 0
        for op in ops:
            p = op.process
            if p == "nemesis":
                continue
            t = tcode_get(op.type)
            if t is None:
                continue
            if t == 0:
                # wildcard id: this op's crashed-at-invoke identity,
                # used only by the unsettled-tail alarm
                oids[m] = oid(op.f, op.value)
                live_inv[p] = (len(bind_ops), op)
                bind_ops.append(op)
            else:
                entry = live_pop(p, None)
                if entry is None:
                    continue            # completion without invoke
                bid, inv = entry
                if t == 1:              # ok: completion value wins
                    val = op.value if op.value is not None else inv.value
                    oids[m] = oid(inv.f, val)
                    bind_val[bid] = val
                elif t == 3:            # crashed: invoke value stands
                    oids[m] = oid(inv.f, inv.value)
                    bind_val[bid] = inv.value
            types[m] = t
            procs[m] = pkey(p)
            m += 1
        if m:
            self._feed_native(types[:m], procs[:m], oids[:m])

    # -- the walk -------------------------------------------------------------

    def _resolve_stragglers(self) -> None:
        """The run is over: every still-pending invocation resolves
        as crashed, making the final incremental verdict the exact
        full-history one. Shared with the device session engine
        (``serve.session.DeviceFrontierEngine``) so the two advance
        paths cannot drift."""
        if not self._live_inv:
            return
        items = list(self._live_inv.items())
        self._live_inv.clear()
        k = len(items)
        types = np.full(k, 3, np.int32)
        procs = np.empty(k, np.int64)
        oids = np.empty(k, np.int32)
        for i, (p, (bid, inv)) in enumerate(items):
            procs[i] = self._pkey(p)
            oids[i] = self._oid(inv.f, inv.value)
            self._bind_val[bid] = inv.value
        self._feed_native(types, procs, oids)

    def advance(self, run_over: bool = False) -> Optional[Dict[str, Any]]:
        if self.violation is not None:
            return self.violation
        self._drain()
        if run_over:
            self._resolve_stragglers()
        if self.memo is None:
            return None
        # one long-pending op blocks the whole settle queue; skip the
        # R pack/unpack round trip when advance would walk nothing
        _s, queued, _l, _w, front_ok = self._mon.stats()
        if queued == 0 or not front_ok:
            return None
        M = 1 << self.W
        words = _pack_words(self.R, M)
        walked, dead_bind = self._mon.advance(self.memo.table, words)
        self.R = _unpack_words(words, M)
        self.settled_returns += walked
        self.walked_events += walked
        if dead_bind >= 0:
            self.violation = self._violation_at(dead_bind)
        return self.violation

    def tail_alarm(self) -> Optional[Dict[str, Any]]:
        """Bounded unsettled-tail check from a COPY of the carried set,
        unresolved ops as crashed-at-invoke wildcards (sound
        over-approximation — an alarm is a real violation)."""
        if self.violation is not None or self.memo is None:
            return None
        self._drain()
        rows, slots, binds = self._mon.tail(self._TAIL_CAP, self.W)
        if len(slots) == 0:
            return None
        from jepsen_tpu.checkers import preproc_native
        words = _pack_words(self.R, 1 << self.W)   # a copy by packing
        dead = preproc_native.walk_dense(self.memo.table, words, self.W,
                                         slots, rows)
        if dead is not None and dead >= 0:
            self.violation = self._violation_at(int(binds[dead]))
        return self.violation

    def _violation_at(self, bid: int) -> Dict[str, Any]:
        inv = self._bind_ops[bid]
        op = inv.with_(type=OK, value=self._bind_val.get(bid, inv.value))
        return {"valid": False, "engine": "online-native",
                "op": op.to_dict(),
                "settled-returns": self.settled_returns}

    def in_flight(self) -> int:
        _settled, queued, live, _w, _f = self._mon.stats()
        return queued + live + len(self._buf)


class OnlineLinearizable:
    """Background prefix re-checker. Wire :meth:`observe` as the history
    observer (``core.History(observer=...)``), :meth:`start` /
    :meth:`stop` around the run, and pass ``on_violation`` to abort the
    test early (the runner sets its stop flag there)."""

    def __init__(self, model: Model, *,
                 interval_s: float = 1.0,
                 min_new_ops: int = 128,
                 mode: str = "incremental",
                 on_violation: Optional[Callable[[Dict[str, Any]], None]]
                 = None,
                 **checker_kw: Any):
        self.model = model
        self.interval_s = interval_s
        self.min_new_ops = min_new_ops
        self.mode = mode
        self.on_violation = on_violation
        self.checker_kw = checker_kw
        self._ops: List[Op] = []
        self._lock = threading.Lock()
        self._flush_lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._checked_upto = 0          # longest CONCLUSIVELY checked prefix
        self._inconclusive_tail = 0
        self._flushes = 0
        self._run_over = False
        self.violation: Optional[Dict[str, Any]] = None
        self._engine = None
        self._engine_cursor = 0
        if mode == "incremental":
            eng_kw = {k: checker_kw[k] for k in
                      ("max_states", "max_slots", "max_dense")
                      if k in checker_kw}
            # prefer the C++ streaming core (~6-8x the Python engine);
            # same semantics, differentially tested
            from jepsen_tpu.checkers import preproc_native
            if preproc_native.available():
                self._engine = NativeStreamEngine(model, **eng_kw)
            else:
                self._engine = IncrementalEngine(model, **eng_kw)

    # -- producer side (worker threads, via History observer) ---------------

    def observe(self, op: Op) -> None:
        with self._lock:
            self._ops.append(op)
        if len(self._ops) - self._checked_upto >= self.min_new_ops:
            self._wake.set()

    # -- checking ------------------------------------------------------------

    def flush(self) -> Optional[Dict[str, Any]]:
        """Check the current prefix; returns the violation dict once one
        is found (then sticky — no further work happens). Serialized: the
        monitor thread and a caller's stop() may both land here."""
        with self._flush_lock:
            return self._flush_locked()

    def _flush_locked(self) -> Optional[Dict[str, Any]]:
        if self.violation is not None:
            return self.violation
        if self._engine is not None:
            try:
                return self._flush_incremental()
            except _Overflow as e:
                # capacity decline, not a death: recorded as a route
                # decision (the engine-ladder discipline)
                from jepsen_tpu import obs
                obs.decision("online-incremental", "route",
                             cause=f"overflow:{type(e).__name__}")
                log.info("online check: dense state overflowed (%s); "
                         "falling back to prefix re-checking", e)
            except Exception as e:                      # noqa: BLE001
                from jepsen_tpu import obs
                obs.engine_fallback("online-incremental",
                                    type(e).__name__)
                log.warning("online incremental engine failed (%s); "
                            "falling back to prefix re-checking", e)
            # permanent fallback: the recheck path below re-verifies
            # everything from scratch, so nothing is lost
            self._engine = None
            self._checked_upto = 0
            self._inconclusive_tail = 0
        with self._lock:
            prefix = list(self._ops)
        if (len(prefix) <= self._checked_upto
                and not self._inconclusive_tail):
            return None
        from jepsen_tpu.checkers.facade import check_safe, linearizable

        kw = dict(self.checker_kw)
        if "algorithm" not in kw:
            # low-latency default: the C++ WGL engine has no per-shape
            # compile cost, so flushes keep up with fast op streams; a
            # time limit bounds its exponential worst case ("unknown"
            # flushes are retried at the next cadence tick). The device
            # engine remains the post-hoc source of truth.
            from jepsen_tpu.checkers import wgl_native
            if wgl_native.available():
                kw["algorithm"] = "wgl-native"
                kw.setdefault("time_limit", max(5.0, 5 * self.interval_s))
            else:
                kw["algorithm"] = "auto"
        checker = linearizable(self.model, **kw)
        res = check_safe(checker, None, prefix)
        self._flushes += 1
        if res.get("valid") is True:
            self._checked_upto = len(prefix)
            self._inconclusive_tail = 0
        elif res.get("valid") is False:
            self._checked_upto = len(prefix)
            self._inconclusive_tail = 0
            res["prefix-ops"] = len(prefix)
            res["detected-at-flush"] = self._flushes
            self.violation = res
            log.warning("online check: violation after %d ops (%s)",
                        len(prefix), res.get("op"))
            if self.on_violation is not None:
                try:
                    self.on_violation(res)
                # jtlint: ok fallback — on_violation notify garnish; the violation itself is recorded
                except Exception:                       # noqa: BLE001
                    pass
        else:
            # inconclusive (engine timeout / overflow): do NOT advance —
            # these ops are re-checked next flush, and result() must not
            # claim them verified
            self._inconclusive_tail = len(prefix) - self._checked_upto
        return self.violation

    def _flush_incremental(self) -> Optional[Dict[str, Any]]:
        eng = self._engine
        with self._lock:
            new = self._ops[self._engine_cursor:]
            self._engine_cursor = len(self._ops)
        if hasattr(eng, "feed_many"):
            eng.feed_many(new)
        else:
            for op in new:
                eng.feed(op)
        self._flushes += 1
        v = eng.advance(run_over=self._run_over)
        if v is None and not self._run_over:
            v = eng.tail_alarm()
        unsettled = eng.in_flight()
        self._checked_upto = max(0, self._engine_cursor - 2 * unsettled)
        if v is not None:
            v = dict(v)
            v["prefix-ops"] = self._engine_cursor
            v["detected-at-flush"] = self._flushes
            self.violation = v
            log.warning("online check: violation after %d ops (%s)",
                        self._engine_cursor, v.get("op"))
            if self.on_violation is not None:
                try:
                    self.on_violation(v)
                # jtlint: ok fallback — on_violation notify garnish; the violation itself is recorded
                except Exception:                       # noqa: BLE001
                    pass
        return self.violation

    # -- thread lifecycle ----------------------------------------------------

    def start(self) -> "OnlineLinearizable":
        import contextvars

        # run under a copy of the starter's context so obs records from
        # monitor flushes reach the enclosing run's capture scope
        ctx = contextvars.copy_context()
        self._thread = threading.Thread(target=lambda: ctx.run(self._loop),
                                        daemon=True,
                                        name="jepsen-online-check")
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set() and self.violation is None:
            self._wake.wait(self.interval_s)
            self._wake.clear()
            if self._stop.is_set():
                break
            try:
                self.flush()
            except Exception as e:                      # noqa: BLE001
                # the monitor thread keeps running and retries next
                # interval, but an unchecked window existed: recorded
                from jepsen_tpu import obs
                obs.checker_swallowed("online-flush",
                                      type(e).__name__)
                log.warning("online check flush failed: %s", e)

    def stop(self) -> Dict[str, Any]:
        """Stop the thread, run one final flush (with every straggler
        resolved as crashed — the run is over, so the incremental
        verdict becomes the exact full-history one), and return
        :meth:`result`."""
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(30)
        self._run_over = True
        try:
            self.flush()
        except Exception as e:                          # noqa: BLE001
            # result() below reports only what WAS verified; the
            # failed final flush is recorded, never silent
            from jepsen_tpu import obs
            obs.checker_swallowed("online-flush", type(e).__name__)
            log.warning("online check final flush failed: %s", e)
        return self.result()

    def result(self) -> Dict[str, Any]:
        if self.violation is not None:
            out = dict(self.violation)
            out["valid"] = False
            return out
        if self._engine is not None:
            out = {"valid": True, "mode": "incremental",
                   "ops-checked": self._engine_cursor,
                   "settled-returns": self._engine.settled_returns,
                   "flushes": self._flushes}
            if not self._run_over:
                unsettled = self._engine.in_flight()
                if unsettled:
                    out["in-flight-ops"] = unsettled
            return out
        out: Dict[str, Any] = {"valid": True,
                               "ops-checked": self._checked_upto,
                               "flushes": self._flushes}
        if self._inconclusive_tail:
            # the last flush(es) were inconclusive: the tail was never
            # verified, so the monitor's verdict is only "no violation
            # SEEN", not a clean bill
            out["valid"] = "unknown"
            out["unchecked-tail-ops"] = self._inconclusive_tail
        return out
