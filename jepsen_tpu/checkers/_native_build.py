"""Shared build/load scaffolding for the on-demand C++ helpers under
``native/`` (used by :mod:`.wgl_native` and :mod:`.preproc_native`).

Each helper is one translation unit compiled with g++ into
``jepsen_tpu/_build/lib*.so`` the first time it is needed; callers fall
back to their pure-Python paths when the toolchain is unavailable.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Callable, Optional

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_BUILD_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "_build")


class NativeLib:
    """One lazily-built shared library.

    ``declare(lib)`` runs once after loading to set ctypes
    restype/argtypes. Build failures are cached; :meth:`load` then
    returns None forever (callers keep their Python fallback).
    """

    def __init__(self, src_name: str, so_name: str,
                 declare: Callable[[ctypes.CDLL], None]) -> None:
        self._src = os.path.join(_NATIVE_DIR, src_name)
        self._so = os.path.join(_BUILD_DIR, so_name)
        self._declare = declare
        self._lock = threading.Lock()
        self._lib: Optional[ctypes.CDLL] = None
        self.error: Optional[str] = None

    def _build(self) -> Optional[str]:
        try:
            if (os.path.exists(self._so) and
                    os.path.getmtime(self._so) >= os.path.getmtime(self._src)):
                return None
            os.makedirs(_BUILD_DIR, exist_ok=True)
            # per-process tmp name: concurrent builders each write their
            # own file and the os.replace install stays atomic
            tmp = f"{self._so}.{os.getpid()}.tmp"
            p = subprocess.run(
                ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
                 "-o", tmp, self._src],
                capture_output=True, text=True, timeout=120)
            if p.returncode != 0:
                return f"g++ failed: {p.stderr[:500]}"
            os.replace(tmp, self._so)
            return None
        # jtlint: ok fallback — the probe RETURNS the error string; the chain surfaces it as engine.skipped
        except FileNotFoundError:
            return "g++ not found"
        # jtlint: ok fallback — the probe RETURNS the error string; the chain surfaces it as engine.skipped
        except Exception as e:                          # noqa: BLE001
            return f"{type(e).__name__}: {e}"

    def load(self) -> Optional[ctypes.CDLL]:
        with self._lock:
            if self._lib is not None or self.error is not None:
                return self._lib
            err = self._build()
            if err is not None:
                self.error = err
                return None
            lib = ctypes.CDLL(self._so)
            self._declare(lib)
            self._lib = lib
            return self._lib

    def available(self) -> bool:
        return self.load() is not None
