"""CPU reference Wing-Gong-Lowe linearizability search.

Upstream: ``knossos/src/knossos/wgl.clj`` (SURVEY.md §2.2, §3.2) — Wing &
Gong's (1993) search over linearization orders with Lowe's (2017)
memoization of ⟨linearized-set, model-state⟩ configurations.

This implementation is breadth-first over *configurations* ``(state_id,
linearized_mask)`` rather than the upstream's recursive DFS over a mutable
doubly-linked list: each BFS level linearizes exactly one more operation, so
the structure mirrors the TPU frontier search (:mod:`.wgl_tpu`) and serves as
its bit-exact oracle, while exploring the same configuration space the
upstream memo set ``HashSet<⟨BitSet, state⟩>`` deduplicates.

Semantics (matching knossos; SURVEY.md §7 "hard parts" #4):

- ``fail`` completions are stripped in preprocessing (the op never happened).
- ``info``/crashed ops stay forever-pending: they may linearize at any point
  after invocation (explored like any candidate) or never (simply left
  unlinearized — validity only requires every ``ok`` op to linearize).
- An op may be linearized next iff no *unlinearized* op completed before its
  invocation: ``inv(x) < min(ret(y) for unlinearized y)``.
- Exceeding ``time_limit`` or ``max_configs`` yields ``valid == "unknown"``
  (upstream ``knossos.search`` timeout / memory-watchdog behaviour).

Model states are int-coded lazily (only states actually reached by legal
linearization prefixes are materialized), which keeps models with large
alphabets tractable without the full BFS table of
:mod:`jepsen_tpu.models.memo`.
"""
from __future__ import annotations

import time as _time
from typing import Any, Callable, Dict, List, Optional, Sequence, Set, Tuple

from jepsen_tpu import history as h
from jepsen_tpu.models import Model, is_inconsistent
from jepsen_tpu.op import Op

INF = 1 << 60


def check(model: Model, history: Sequence[Op], *,
          time_limit: Optional[float] = None,
          max_configs: int = 5_000_000,
          strategy: str = "dfs",
          should_abort: Optional[Callable[[], bool]] = None
          ) -> Dict[str, Any]:
    """Check ``history`` against ``model``. Returns a knossos-style map:
    ``{"valid": True|False|"unknown", "configs-explored": int, ...}``; on
    failure adds ``"op"`` (the op that could not be linearized) and
    ``"max-linearized"`` (the deepest coverage of ok ops reached).

    ``strategy="dfs"`` matches the upstream recursive search (fast first
    witness on valid histories); ``strategy="bfs"`` explores level-by-level,
    bit-exactly mirroring the TPU frontier search. Both use the same memo
    set and explore the same configuration space.
    """
    entries = h.analysis_entries(history)
    packed = h.pack_entries(entries)
    return check_packed(model, packed, time_limit=time_limit,
                        max_configs=max_configs, strategy=strategy,
                        should_abort=should_abort)


def check_packed(model: Model, packed: h.PackedHistory, *,
                 time_limit: Optional[float] = None,
                 max_configs: int = 5_000_000,
                 strategy: str = "dfs",
                 should_abort: Optional[Callable[[], bool]] = None
                 ) -> Dict[str, Any]:
    n = packed.n
    if n == 0:
        return {"valid": True, "configs-explored": 0}
    inv_ev = packed.inv_ev
    ret_ev = [int(r) if not c else INF
              for r, c in zip(packed.ret_ev, packed.crashed)]
    inv = [int(x) for x in inv_ev]
    op_id = [int(x) for x in packed.op_id]
    ok_mask = 0
    for i in range(n):
        if not packed.crashed[i]:
            ok_mask |= 1 << i
    if ok_mask == 0:
        return {"valid": True, "configs-explored": 0}

    # lazy int-coding of model states
    states: List[Model] = [model]
    state_ids: Dict[Model, int] = {model: 0}
    trans: Dict[Tuple[int, int], int] = {}
    distinct_ops = packed.distinct_ops

    def step(sid: int, oid: int) -> int:
        key = (sid, oid)
        cached = trans.get(key)
        if cached is not None:
            return cached
        s2 = states[sid].step(distinct_ops[oid])
        if is_inconsistent(s2):
            res = -1
        else:
            res = state_ids.setdefault(s2, len(states))
            if res == len(states):
                states.append(s2)
        trans[key] = res
        return res

    start = _time.monotonic()
    seen: Set[Tuple[int, int]] = {(0, 0)}
    explored = 0
    best_cover = 0
    # every configuration reaching the deepest ok-coverage (capped 16);
    # expand() always runs on (0, 0) first, so the initial config is
    # captured without a placeholder
    best_configs: List[Tuple[int, int]] = []
    full = (1 << n) - 1
    found: List[Any] = []

    def expand(sid: int, mask: int) -> List[Tuple[int, int]]:
        """Candidate successors of a configuration: unlinearized i in
        invocation order while inv[i] < min ret over unlinearized j < i
        (scan order)."""
        nonlocal explored, best_cover
        explored += 1
        cover = (mask & ok_mask).bit_count()
        if cover > best_cover or not best_configs:
            best_cover = cover
            best_configs.clear()
            best_configs.append((sid, mask))
        elif cover == best_cover and len(best_configs) < 16:
            best_configs.append((sid, mask))
        out: List[Tuple[int, int]] = []
        m = INF
        rest = full & ~mask
        i = _lowest_bit(rest)
        while 0 <= i < n:
            if inv[i] >= m:
                break
            sid2 = step(sid, op_id[i])
            if sid2 >= 0:
                mask2 = mask | (1 << i)
                if (mask2 & ok_mask) == ok_mask:
                    found.append(True)
                    return out
                cfg = (sid2, mask2)
                if cfg not in seen:
                    seen.add(cfg)
                    out.append(cfg)
            m = min(m, ret_ev[i])
            rest &= ~(1 << i)
            i = _lowest_bit(rest)
        return out

    def over_budget() -> Optional[Dict[str, Any]]:
        if should_abort is not None and should_abort():
            return {"valid": "unknown", "cause": "aborted",
                    "configs-explored": explored}
        if time_limit is not None and _time.monotonic() - start > time_limit:
            return {"valid": "unknown", "cause": "timeout",
                    "configs-explored": explored}
        if len(seen) > max_configs:
            return {"valid": "unknown", "cause": "config-set-explosion",
                    "configs-explored": explored}
        return None

    if strategy == "bfs":
        frontier: List[Tuple[int, int]] = [(0, 0)]
        while frontier and not found:
            bad = over_budget()
            if bad:
                return bad
            nxt: List[Tuple[int, int]] = []
            for k, (sid, mask) in enumerate(frontier):
                if k % 4096 == 4095:
                    bad = over_budget()
                    if bad:
                        return bad
                nxt.extend(expand(sid, mask))
                if found:
                    break
            frontier = nxt
    elif strategy == "dfs":
        stack: List[Tuple[int, int]] = [(0, 0)]
        tick = 0
        while stack and not found:
            tick += 1
            if tick % 4096 == 0:
                bad = over_budget()
                if bad:
                    return bad
            sid, mask = stack.pop()
            stack.extend(reversed(expand(sid, mask)))
    else:
        raise ValueError(f"unknown strategy {strategy!r}")

    if found:
        return {"valid": True, "configs-explored": explored,
                "states-materialized": len(states)}

    # exhausted: non-linearizable. Report the first ok op that the deepest
    # configuration could not linearize, plus the deepest configurations
    # themselves (knossos's :final-paths analogue: model state + the
    # linearized ops CONCURRENT with the stuck op — the same
    # pending-window scope the device engines decode).
    sid, mask = best_configs[0] if best_configs else (0, 0)
    stuck = _lowest_bit(ok_mask & ~mask)
    op = packed.entries[stuck].op.to_dict() if stuck >= 0 else None
    final = []
    for s2, m2 in best_configs:
        # window relative to each config's OWN stuck op (tied configs
        # may be stuck on different ops)
        stuck2 = _lowest_bit(ok_mask & ~m2)
        if stuck2 >= 0:
            lin = [str(packed.entries[i].op) for i in range(n)
                   if (m2 >> i) & 1 and i != stuck2
                   and ret_ev[i] > inv[stuck2]]
        else:
            lin = []
        if not lin:             # fully-sequential window: show the tail
            lin = [str(packed.entries[i].op)
                   for i in range(n) if (m2 >> i) & 1][-8:]
        final.append({"model": repr(states[s2]),
                      "linearized-pending": lin})
    return {"valid": False, "op": op, "max-linearized": best_cover,
            "configs-explored": explored,
            "final-configs": final,
            "final-state": repr(states[sid])}


def _lowest_bit(x: int) -> int:
    if x == 0:
        return -1
    return (x & -x).bit_length() - 1
