"""Just-in-time linearization engine — upstream
``knossos/src/knossos/linear.clj`` (G. Lowe, *Testing for Linearizability*,
2017) with the packed config-set structures of
``knossos/src/knossos/linear/config.clj`` (SURVEY.md §2.2, §3.2).

The search advances a *set of configurations* ⟨model-state,
pending-unlinearized ops⟩ through the history's real-time event stream:

- **invoke**: the op joins every configuration's pending set.
- **return**: pending ops are fired (linearized) to a fixpoint — every
  linearization order of every subset is covered, with global dedup — and
  only configurations that linearized the returning op survive. An empty
  survivor set is a linearizability violation at exactly that event.

Firing is deferred to return events (the "just-in-time" idea): between
returns, pending sets only grow, so any linearization performed earlier is
still reachable by the closure at the next return.

Where the dense device engine (:mod:`.reach`) materializes the *entire*
``states × 2**W`` config space as one boolean tensor, this engine keeps the
reachable set sparse — the upstream's trade: cheap per-event work on
well-behaved histories, death by config-set explosion on adversarial ones
(reported as ``valid == "unknown"``, which the competition checker
(:func:`jepsen_tpu.checkers.facade.linearizable` with
``algorithm="competition"``) resolves by racing the other engines).

Config-set representations, mirroring the upstream's array/set variants:

- :class:`ArrayConfigSet` — configs packed into one sorted ``uint64``
  vector (``state_id << 32 | pending_mask``); fire steps are vectorized
  NumPy gathers and the dedup is ``np.unique``. Used when the history
  needs ≤ 32 pending-op slots.
- :class:`SetConfigSet` — a plain set of ``(state_id, mask)`` tuples with
  unbounded Python-int masks; handles arbitrary concurrency.

Model states are int-coded lazily (like :mod:`.wgl_ref`), so models with
huge or unbounded alphabets work without a full
:mod:`jepsen_tpu.models.memo` state enumeration.
"""
from __future__ import annotations

import heapq
import time as _time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from jepsen_tpu import history as h
from jepsen_tpu.models import Model, is_inconsistent
from jepsen_tpu.op import Op

KIND_INVOKE = 0
KIND_RETURN = 1

_MASK32 = np.uint64(0xFFFFFFFF)


class _LazyTable:
    """Lazily int-coded model states with per-op dense transition columns
    (the vectorizable face of ``knossos.model.memo`` without the up-front
    reachable-state enumeration). ``-1`` = inconsistent, ``-2`` = not yet
    computed."""

    def __init__(self, model: Model, distinct_ops: Sequence[Op]):
        self.states: List[Model] = [model]
        self.state_ids: Dict[Model, int] = {model: 0}
        self.ops = tuple(distinct_ops)
        self._cols: Dict[int, np.ndarray] = {}

    def step(self, sid: int, oid: int) -> int:
        s2 = self.states[sid].step(self.ops[oid])
        if is_inconsistent(s2):
            return -1
        nid = self.state_ids.setdefault(s2, len(self.states))
        if nid == len(self.states):
            self.states.append(s2)
        return nid

    def column(self, oid: int, sids: np.ndarray) -> np.ndarray:
        """Dense transition column for op ``oid``, guaranteed computed at
        every state id in ``sids``."""
        col = self._cols.get(oid)
        if col is None or len(col) < len(self.states):
            new = np.full(len(self.states), -2, np.int64)
            if col is not None:
                new[:len(col)] = col
            self._cols[oid] = col = new
        for sid in np.unique(sids):
            if col[sid] == -2:
                col[sid] = self.step(int(sid), oid)
        return col


class SetConfigSet:
    """Set-backed config set (upstream ``set-config-set``): configs are
    ``(state_id, pending_mask)`` tuples, masks unbounded Python ints."""

    rep = "set"

    def __init__(self) -> None:
        self.configs: set = {(0, 0)}

    def __len__(self) -> int:
        return len(self.configs)

    def invoke(self, slot: int) -> None:
        bit = 1 << slot
        self.configs = {(sid, mask | bit) for sid, mask in self.configs}

    def closure(self, pending: Dict[int, int], table: _LazyTable,
                budget: Callable[[int], Optional[dict]]) -> Optional[dict]:
        frontier = self.configs
        while frontier:
            bad = budget(len(self.configs))
            if bad:
                return bad
            fresh = set()
            for sid, mask in frontier:
                for slot, oid in pending.items():
                    bit = 1 << slot
                    if not mask & bit:
                        continue
                    nid = table.step(sid, oid)
                    if nid < 0:
                        continue
                    cfg = (nid, mask & ~bit)
                    if cfg not in self.configs and cfg not in fresh:
                        fresh.add(cfg)
            self.configs |= fresh
            frontier = fresh
        return None

    def project_return(self, slot: int) -> None:
        bit = 1 << slot
        self.configs = {c for c in self.configs if not c[1] & bit}

    def stash(self):
        """O(1) reference to the current container (safe to keep across
        :meth:`project_return`, which rebinds rather than mutates)."""
        return self.configs

    @staticmethod
    def decode(stash, limit: int) -> List[tuple]:
        """Up to ``limit`` ``(state_id, pending_mask)`` pairs from a
        stashed container — the raw material for knossos-style
        ``final-configs`` evidence."""
        out = []
        for c in stash:
            out.append(c)
            if len(out) >= limit:
                break
        return out


class ArrayConfigSet:
    """Array-backed config set (upstream ``array-config-set``): one sorted
    unique ``uint64`` vector, ``state_id << 32 | pending_mask``. Fires are
    vectorized column gathers; dedup is sorted-merge."""

    rep = "array"

    def __init__(self) -> None:
        self.keys = np.zeros(1, np.uint64)          # initial config (0, 0)

    def __len__(self) -> int:
        return len(self.keys)

    def invoke(self, slot: int) -> None:
        # the slot was free, so the bit is clear in every config: OR is a
        # uniform addition and preserves sortedness/uniqueness
        self.keys = self.keys | np.uint64(1 << slot)

    def closure(self, pending: Dict[int, int], table: _LazyTable,
                budget: Callable[[int], Optional[dict]]) -> Optional[dict]:
        frontier = self.keys
        while frontier.size:
            bad = budget(len(self.keys))
            if bad:
                return bad
            masks = frontier & _MASK32
            sids = (frontier >> np.uint64(32)).astype(np.int64)
            parts = []
            for slot, oid in pending.items():
                bit = np.uint64(1 << slot)
                sel = (masks & bit) != 0
                if not sel.any():
                    continue
                col = table.column(oid, sids[sel])
                tgt = col[sids[sel]]
                legal = tgt >= 0
                if not legal.any():
                    continue
                parts.append(tgt[legal].astype(np.uint64) << np.uint64(32)
                             | (masks[sel][legal] & ~bit))
            if not parts:
                break
            cand = np.unique(np.concatenate(parts))
            # keep only configs not already present (self.keys is sorted)
            pos = np.searchsorted(self.keys, cand)
            pos_c = np.minimum(pos, len(self.keys) - 1)
            fresh = cand[self.keys[pos_c] != cand]
            if not fresh.size:
                break
            self.keys = np.union1d(self.keys, fresh)
            frontier = fresh
        return None

    def project_return(self, slot: int) -> None:
        bit = np.uint64(1 << slot)
        self.keys = self.keys[(self.keys & bit) == 0]

    def stash(self):
        """O(1) reference to the current key vector (safe to keep across
        :meth:`project_return`, which rebinds rather than mutates)."""
        return self.keys

    @staticmethod
    def decode(stash, limit: int) -> List[tuple]:
        """Up to ``limit`` ``(state_id, pending_mask)`` pairs from a
        stashed key vector — the raw material for knossos-style
        ``final-configs`` evidence."""
        return [(int(k >> np.uint64(32)), int(k & _MASK32))
                for k in stash[:limit]]


def check(model: Model, history: Sequence[Op], *,
          time_limit: Optional[float] = None,
          max_configs: int = 2_000_000,
          rep: str = "auto",
          should_abort: Optional[Callable[[], bool]] = None
          ) -> Dict[str, Any]:
    """Check ``history`` against ``model`` by just-in-time linearization.
    Returns the knossos-style verdict map (``valid`` True / False /
    ``"unknown"``); on failure adds ``op`` (the operation whose return no
    configuration could satisfy)."""
    packed = h.pack(history)
    return check_packed(model, packed, time_limit=time_limit,
                        max_configs=max_configs, rep=rep,
                        should_abort=should_abort)


def check_packed(model: Model, packed: h.PackedHistory, *,
                 time_limit: Optional[float] = None,
                 max_configs: int = 2_000_000,
                 rep: str = "auto",
                 should_abort: Optional[Callable[[], bool]] = None
                 ) -> Dict[str, Any]:
    n = packed.n
    if n == 0 or packed.n_ok == 0:
        return {"valid": True, "engine": "linear", "configs-explored": 0}

    # -- event stream + slot assignment (no width cap: the set rep handles
    # any concurrency; crashed ops hold their slot forever) ------------------
    evs = []
    for i in range(n):
        evs.append((int(packed.inv_ev[i]), KIND_INVOKE, i))
        if not packed.crashed[i]:
            evs.append((int(packed.ret_ev[i]), KIND_RETURN, i))
    evs.sort()
    free: List[int] = []
    hi = 0
    slot_of: Dict[int, int] = {}
    slots = np.zeros(len(evs), np.int32)
    for e, (_, k, i) in enumerate(evs):
        if k == KIND_INVOKE:
            s = heapq.heappop(free) if free else hi
            if s == hi:
                hi += 1
            slot_of[i] = s
            slots[e] = s
        else:
            s = slot_of.pop(i)
            slots[e] = s
            heapq.heappush(free, s)         # reuse after project_return
    W = max(hi, 1)

    if rep == "auto":
        rep = "array" if W <= 32 else "set"
    if rep == "array" and W > 32:
        raise ValueError(f"array config set supports <=32 slots, need {W}")
    configs = ArrayConfigSet() if rep == "array" else SetConfigSet()

    table = _LazyTable(model, packed.distinct_ops)
    start = _time.monotonic()
    peak = 1
    explored = 0

    def budget(live: int) -> Optional[Dict[str, Any]]:
        nonlocal peak
        peak = max(peak, live)
        if live > max_configs:
            return {"valid": "unknown", "cause": "config-set-explosion",
                    "engine": "linear", "rep": configs.rep,
                    "max-config-set": peak}
        if should_abort is not None and should_abort():
            return {"valid": "unknown", "cause": "aborted",
                    "engine": "linear", "rep": configs.rep}
        if time_limit is not None and _time.monotonic() - start > time_limit:
            return {"valid": "unknown", "cause": "timeout",
                    "engine": "linear", "rep": configs.rep}
        return None

    pending: Dict[int, int] = {}            # slot -> op id (live invocations)
    last_ok: Optional[int] = None           # entry of last linearized return
    for e, (_rank, k, i) in enumerate(evs):
        s = int(slots[e])
        if k == KIND_INVOKE:
            pending[s] = int(packed.op_id[i])
            configs.invoke(s)
            explored += len(configs)
            continue
        bad = configs.closure(pending, table, budget)
        if bad:
            bad["configs-explored"] = explored
            return bad
        explored += len(configs)
        # O(1) stash of the closure set's container: project_return
        # REBINDS (never mutates) it in both reps, so on the failure
        # path this reference still holds the knossos-style final
        # configs at the failing event — no per-return copying
        stash = configs.stash()
        configs.project_return(s)
        if len(configs) == 0:
            pend_before = dict(pending)      # still includes slot s
            final = [
                {"model": str(table.states[sid]),
                 "linearized-pending": [
                     str(table.ops[pend_before[sl]])
                     for sl in sorted(pend_before)
                     if not (mask >> sl) & 1]}
                for sid, mask in configs.decode(stash, 16)]
            out = {"valid": False, "engine": "linear",
                   "rep": configs.rep,
                   "op": packed.entries[i].op.to_dict(),
                   "final-configs": final,
                   "configs-explored": explored, "max-config-set": peak,
                   "states-materialized": len(table.states)}
            if last_ok is not None:
                out["previous-ok"] = packed.entries[last_ok].op.to_dict()
            return out
        del pending[s]
        last_ok = i
    return {"valid": True, "engine": "linear", "rep": configs.rep,
            "configs-explored": explored, "max-config-set": peak,
            "final-config-count": len(configs),
            "states-materialized": len(table.states)}
