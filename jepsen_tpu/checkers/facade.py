"""The composable ``Checker`` API — upstream ``jepsen/src/jepsen/checker.clj``
(SURVEY.md §2.1): ``linearizable`` (delegating to the search engines, as the
upstream delegates to Knossos via ``knossos.competition/analysis``), the
data-invariant checkers (``set``, ``counter``, ``queue``, ``total-queue``),
``compose``, ``noop``, ``unbridled-optimism``, and ``stats``.

API shape: ``checker.check(test, history, opts) -> dict`` with at least a
``"valid"`` key (``True`` / ``False`` / ``"unknown"``), mirroring the
upstream protocol ``(check checker test model history)`` with the model
carried by the checker (or the test map) instead of a positional argument.
``check_safe`` converts a crashing checker into ``{"valid": "unknown"}``
exactly like ``jepsen.checker/check-safe``.
"""
from __future__ import annotations

import logging
import threading
import traceback as _traceback
from collections import Counter as _Counter
from dataclasses import dataclass, field
from typing import Any, Dict, Mapping, Optional, Sequence

import numpy as np

from jepsen_tpu import history as h
from jepsen_tpu import obs
from jepsen_tpu.models import Model
from jepsen_tpu.op import FAIL, INFO, INVOKE, OK, Op
from jepsen_tpu.util import hashable


class Checker:
    """Base checker (upstream ``jepsen.checker/Checker`` protocol)."""

    name = "checker"

    def check(self, test: Optional[Mapping], history: Sequence[Op],
              opts: Optional[Mapping] = None) -> Dict[str, Any]:
        raise NotImplementedError


def check_safe(checker: Checker, test: Optional[Mapping],
               history: Sequence[Op],
               opts: Optional[Mapping] = None) -> Dict[str, Any]:
    """Run a checker, turning exceptions into ``{"valid": "unknown"}``
    (upstream ``jepsen.checker/check-safe``) — but never silently: the
    full traceback is logged at warning, returned under a
    ``"traceback"`` key, and recorded in the ``obs`` ledger/counters
    (``checker.swallowed.<name>.<exception>``) so a crashing checker is
    visible to tests and the fuzzer."""
    try:
        return checker.check(test, history, opts)
    except Exception as e:                              # noqa: BLE001
        name = getattr(checker, "name", type(checker).__name__)
        tb = _traceback.format_exc()
        logging.getLogger("jepsen.checker").warning(
            "checker %s crashed (returning unknown): %s", name, e,
            exc_info=e)
        obs.checker_swallowed(name, type(e).__name__,
                              ops=len(history))
        return {"valid": "unknown",
                "error": f"{type(e).__name__}: {e}",
                "traceback": tb}


def _model_from(model: Optional[Model], test: Optional[Mapping]) -> Model:
    if model is not None:
        return model
    if test is not None and test.get("model") is not None:
        return test["model"]
    raise ValueError("no model given (checker or test['model'])")


@dataclass
class Linearizable(Checker):
    """Linearizability via the search engines (upstream
    ``jepsen.checker/linearizable`` → ``knossos.competition/analysis``).

    ``algorithm``:

    - ``"auto"`` (default): the TPU dense-reachability engine; when the
      history does not fit the dense config space (state explosion / too
      many concurrent pending ops) falls back to the C++ WGL search, then
      to the sparse-frontier device engine (whose crashed-op quotient
      survives crash-heavy histories that explode the exact searches),
      then to the Python oracle.
    - ``"reach"`` / ``"reach-chunked"`` — dense device engine, sequential
      or history-parallel (:mod:`jepsen_tpu.checkers.reach`).
    - ``"frontier"`` — sparse batched-frontier device engine for
      high-concurrency histories (:mod:`jepsen_tpu.checkers.frontier`).
    - ``"decompose"`` — P-compositional per-key split of single-key
      multi-register histories into a batched register check
      (:mod:`jepsen_tpu.checkers.decompose`); ``auto`` tries it first for
      ``MultiRegister`` models.
    - ``"wgl-native"`` — the C++ WGL search
      (:mod:`jepsen_tpu.checkers.wgl_native`).
    - ``"wgl-cpu"`` — the Python oracle (:mod:`jepsen_tpu.checkers.wgl_ref`).
    - ``"linear"`` — sparse just-in-time linearization, upstream
      ``knossos.linear`` (:mod:`jepsen_tpu.checkers.linear`).
    - ``"competition"`` — device engines raced against the CPU searches
      (WGL native/Python plus JIT-linearization) on threads, first
      definitive verdict wins and the losers are aborted (upstream
      ``knossos.competition`` racing wgl against linear).
    """
    model: Optional[Model] = None
    algorithm: str = "auto"
    opts: Dict[str, Any] = field(default_factory=dict)
    name = "linearizable"

    def check(self, test, history, opts=None):
        res = self._check_impl(test, history, opts)
        out_dir = (test or {}).get("dir") if hasattr(test, "get") else None
        if res.get("valid") is False and res.get("op") and out_dir:
            # render the upstream-style SVG of the failing window
            # (knossos.linear.report) next to the run's other artifacts
            import os

            from jepsen_tpu.checkers import linear_report
            try:
                path = os.path.join(out_dir, "linear.svg")
                linear_report.render_analysis(history, res, path)
                res["report-file"] = path
            # jtlint: ok fallback — reporting garnish; the verdict it must never mask is already built
            except Exception:                           # noqa: BLE001
                pass                    # reporting must never mask a verdict
        return res

    def _check_impl(self, test, history, opts=None):
        from jepsen_tpu.checkers import frontier, reach, wgl_native, wgl_ref
        from jepsen_tpu.checkers.events import ConcurrencyOverflow
        from jepsen_tpu.models.memo import StateExplosion

        # warm-start tier (ISSUE 3): wire the persistent compilation
        # cache before ANY engine compiles, so every algorithm route —
        # not just the reach entry points — starts warm on a recheck
        reach._ensure_persistent_caches()
        model = _model_from(self.model, test)
        kw = dict(self.opts)
        if opts:
            kw.update({k: v for k, v in opts.items() if k != "model"})
        algorithm = kw.pop("algorithm", self.algorithm)
        if algorithm == "reach":
            return reach.check(model, history, **_engine_kw(kw, _REACH_KW))
        if algorithm == "reach-chunked":
            return reach.check_chunked(model, history,
                                       **_engine_kw(kw, _CHUNKED_KW))
        if algorithm == "chunklock":
            from jepsen_tpu.checkers import reach_chunklock
            return reach_chunklock.check_packed(
                model, h.pack(history),
                **_engine_kw(kw, _CHUNKLOCK_KW))
        if algorithm == "frontier":
            return frontier.check(model, history,
                                  **_engine_kw(kw, _FRONTIER_KW))
        if algorithm == "decompose":
            from jepsen_tpu.checkers import decompose
            res = decompose.check(model, history,
                                  **_engine_kw(kw, _DECOMPOSE_KW))
            if res is None:
                return {"valid": "unknown", "cause": "not-decomposable",
                        "engine": "decompose"}
            return res
        if algorithm == "wgl-native":
            return wgl_native.check(model, history,
                                    **_engine_kw(kw, _NATIVE_KW))
        if algorithm == "wgl-cpu":
            return wgl_ref.check(model, history, **_engine_kw(kw, _WGL_KW))
        if algorithm == "linear":
            from jepsen_tpu.checkers import linear
            return linear.check(model, history,
                                **_engine_kw(kw, _LINEAR_KW))
        if algorithm == "auto":
            from jepsen_tpu import models as _models
            packed = h.pack(history)
            if isinstance(model, _models.MultiRegister):
                # P-compositionality (Herlihy & Wing locality): a history
                # of single-key ops splits into per-key register
                # histories, batched as one keyed device call — avoiding
                # the product-state blowup of the monolithic search. A
                # decomposed "unknown" is returned as-is: the monolithic
                # product space is strictly harder, so re-running the
                # chain on it could only burn the budget again.
                from jepsen_tpu.checkers import decompose
                try:
                    res = decompose.check_packed(
                        model, packed, **_engine_kw(kw, _DECOMPOSE_KW))
                    if res is not None:
                        obs.engine_selected(
                            res.get("engine", "decompose"),
                            ops=packed.n, valid=res.get("valid"))
                        return res
                except Exception as e:                  # noqa: BLE001
                    # fall through to the monolithic chain — recorded,
                    # not silent
                    obs.engine_fallback("decompose", type(e).__name__,
                                        ops=packed.n)
            return auto_check_packed(model, packed, kw)
        if algorithm == "competition":
            return _competition(model, history, kw)
        raise ValueError(f"unknown algorithm {algorithm!r}")


def auto_check_packed(model: Model, packed, kw: Mapping) -> Dict[str, Any]:
    """The ``auto`` fallback chain at the packed level: dense device
    engine → C++ WGL → sparse frontier → Python oracle, first conclusive
    verdict wins. Shared by :class:`Linearizable` and the per-key
    fallback in :mod:`jepsen_tpu.checkers.decompose`.

    A ``time_limit`` in ``kw`` budgets the chain as a whole: the deadline
    is computed once here and each wall-clock-limited fallback stage
    (C++ WGL, frontier, Python oracle) receives only the time remaining,
    so a history that times out in every stage costs ~1× the configured
    limit, not 1× per stage. (The dense first stage is bounded by
    structure — ``max_dense``/``max_states`` — not wall-clock, and runs
    before the budget is consulted.)

    Every stage transition lands in the :mod:`jepsen_tpu.obs`
    engine-decision ledger: exactly ONE ``"selected"`` record per call
    (the engine that produced the verdict) and one ``"fallback"``
    record per abandoned stage, with the exception class, the history
    geometry, and the stage's elapsed time — so ``obs.capture()`` can
    assert "no silent fallback occurred"."""
    import time as _time

    from jepsen_tpu.checkers import frontier, reach, transfer, wgl_native, \
        wgl_ref
    from jepsen_tpu.checkers.events import ConcurrencyOverflow
    from jepsen_tpu.models.memo import StateExplosion

    # name the wire format this chain's verdicts cross on (the
    # transfer-diet gates are env-consulted per call; run artifacts
    # must record which configuration was measured) — and warn once
    # on set JEPSEN_TPU_* gates the tree does not read (a typo'd
    # opt-out must not silently no-op)
    from jepsen_tpu import envcheck
    envcheck.check_once()
    transfer.record_mode()
    geom = {"ops": packed.n, "ok-ops": packed.n_ok}
    t_stage = _time.monotonic()

    def _selected(res: Dict[str, Any], default_stage: str
                  ) -> Dict[str, Any]:
        obs.engine_selected(res.get("engine", default_stage), **geom,
                            valid=res.get("valid"),
                            elapsed_s=round(_time.monotonic() - t_stage,
                                            6))
        return res

    def _fellback(stage: str, cause: str) -> None:
        nonlocal t_stage
        obs.engine_fallback(stage, cause, **geom,
                            elapsed_s=round(_time.monotonic() - t_stage,
                                            6))
        t_stage = _time.monotonic()

    tl = kw.get("time_limit")
    deadline = _time.monotonic() + tl if tl else None

    def _spent() -> bool:
        return deadline is not None and _time.monotonic() >= deadline

    def _budgeted(ekw: Dict[str, Any]) -> Dict[str, Any]:
        if deadline is not None:
            ekw["time_limit"] = max(1e-3, deadline - _time.monotonic())
        return ekw

    def _with_deadline_abort(ekw: Dict[str, Any]) -> Dict[str, Any]:
        """Compose the chain deadline into an engine's should_abort
        hook (for stages budgeted by abort polling, not time_limit)."""
        if deadline is not None:
            user_abort = ekw.get("should_abort")
            ekw["should_abort"] = (
                (lambda: user_abort() or _spent())
                if user_abort is not None else _spent)
        return ekw

    exploded = False                # product-space memo blow-ups seen
    try:
        # the dense stage also honors the chain budget: its walk
        # dispatches in bounded segments and turns "unknown" when
        # the deadline passes (round-2 advisor finding)
        ekw = _with_deadline_abort(_engine_kw(kw, _REACH_KW))
        with obs.span("facade.reach", **geom):
            res = reach.check_packed(model, packed, **ekw)
        if res.get("valid") in (True, False):
            return _selected(res, "reach")
        _fellback("reach", f"unknown:{res.get('cause', '?')}")
    except (reach.DenseOverflow, StateExplosion) as e:
        exploded = True
        _fellback("reach", type(e).__name__)
    except ConcurrencyOverflow as e:
        _fellback("reach", type(e).__name__)
    if not wgl_native.available() and not _spent():
        # a whole stage silently missing from a degraded install is
        # exactly what the ledger must catch: record the skip (event
        # "skipped", distinct from "fallback" — the chain is intact,
        # the INSTALL is degraded)
        obs.count("engine.skipped.wgl-native.unavailable")
        obs.decision("wgl-native", "skipped", cause="unavailable",
                     **geom)
    if wgl_native.available() and not _spent():
        try:
            with obs.span("facade.wgl-native", **geom):
                res = wgl_native.check_packed(
                    model, packed,
                    **_budgeted(_engine_kw(kw, _NATIVE_KW)))
            if res.get("valid") in (True, False):
                res["engine"] = "wgl-native-fallback"
                return _selected(res, "wgl-native-fallback")
            _fellback("wgl-native", f"unknown:{res.get('cause', '?')}")
        except StateExplosion as e:
            exploded = True         # un-memoizable / product blow-up
            _fellback("wgl-native", type(e).__name__)
    if not _spent():
        try:
            # the frontier engine's crashed-op quotient can survive
            # crash-heavy histories that explode the exact C++ search
            with obs.span("facade.frontier", **geom):
                res = frontier.check_packed(
                    model, packed,
                    **_budgeted(_engine_kw(kw, _FRONTIER_KW)))
            if res.get("valid") in (True, False):
                res["engine"] = "frontier-fallback"
                return _selected(res, "frontier-fallback")
            _fellback("frontier", f"unknown:{res.get('cause', '?')}")
        except Exception as e:                          # noqa: BLE001
            # overflow or device failure: Python path next
            _fellback("frontier", type(e).__name__)
    from jepsen_tpu import models as _models
    if isinstance(model, _models.MultiRegister):
        # multi-key TRANSACTIONAL histories on an exploding product
        # space: first the RESTRICTED product engine — per-key value
        # closures bound the jointly-reachable product states, so the
        # dense device walk runs over O(history) states where the
        # alphabet BFS needed values**keys — an EXACT True/False
        # (VERDICT round-4 item 2)
        from jepsen_tpu.checkers import decompose
        if not _spent():
            try:
                rp = decompose.check_restricted_product(
                    model, packed,
                    **_with_deadline_abort(_engine_kw(kw, _REACH_KW)))
                if rp is not None and rp.get("valid") in (True, False):
                    return _selected(rp, "restricted-product")
            except (StateExplosion, reach.DenseOverflow,
                    ConcurrencyOverflow) as e:
                # restricted space exploded too: screen next
                _fellback("restricted-product", type(e).__name__)
        # then the sound per-key projection screen — an invalid
        # projection proves non-linearizability outright; all-valid
        # projections yield an explicit "unknown + reason" instead of
        # an unbounded lazy search over a space the memoized engines
        # already refused (VERDICT round-3 item 9)
        try:
            tx = decompose.check_transactional(
                model, packed,
                **_budgeted(_engine_kw(kw, _DECOMPOSE_KW)))
        except Exception as e:                          # noqa: BLE001
            tx = None
            _fellback("transactional-screen", type(e).__name__)
        if tx is not None and (tx.get("valid") is False or exploded
                               or _spent()):
            return _selected(tx, "transactional-screen")
    if _spent():
        obs.decision("auto-chain", "timeout", **geom)
        return {"valid": "unknown", "cause": "timeout",
                "engine": "auto-chain"}
    with obs.span("facade.wgl-cpu", **geom):
        res = wgl_ref.check_packed(model, packed,
                                   **_budgeted(_engine_kw(kw, _WGL_KW)))
    res["engine"] = "wgl-cpu-fallback"
    return _selected(res, "wgl-cpu-fallback")


def auto_check_many_packed(model: Model, packed_list,
                           kw: Mapping) -> "list":
    """The ``auto`` chain for MANY packed histories at once (the
    ``independent`` checker's batch dimension, or a run that produced
    several complete histories): the batched device engines first —
    :func:`reach.check_many` routes bucketed lockstep groups, then the
    keyed flat-stream kernel, then the vmapped XLA walk — falling back
    to the per-history :func:`auto_check_packed` chain when the batch
    route cannot hold every history (dense/union overflow, or a
    too-concurrent key). Mirrors how :func:`auto_check_packed` is the
    one-history chain; results align with ``packed_list``."""
    from jepsen_tpu.checkers import autotune, reach, transfer
    from jepsen_tpu.checkers.events import ConcurrencyOverflow
    from jepsen_tpu.models.memo import StateExplosion

    transfer.record_mode()
    ekw = _engine_kw(kw, _REACH_MANY_KW)
    if "group" not in ekw:
        # recorded winners before heuristics: a lockstep group width
        # measured by tools/batch_width.py --record outranks the
        # built-in _BATCH_GROUP default (H=32-beats-H=64 folklore,
        # persisted instead of re-derived)
        g = autotune.winner("group", "default")
        if g and str(g).isdigit():
            ekw["group"] = int(g)
    try:
        with obs.span("facade.check-many", histories=len(packed_list)):
            out = reach.check_many(model, packed_list, **ekw)
        obs.engine_selected("reach-many", histories=len(packed_list),
                            engines=sorted({r.get("engine", "?")
                                            for r in out}))
        return out
    except (reach.DenseOverflow, ConcurrencyOverflow,
            StateExplosion) as e:
        obs.engine_fallback("reach-many", type(e).__name__,
                            histories=len(packed_list))
    except Exception as e:                              # noqa: BLE001
        # jax/XLA runtime failures keep the graceful per-history
        # fallback (traceback preserved); our own bugs must surface
        if not reach._raised_from_jax(e):
            raise
        logging.getLogger("jepsen.reach").warning(
            "batched many-history check failed (%r); falling back to "
            "per-history checking", e, exc_info=e)
        obs.engine_fallback("reach-many", type(e).__name__,
                            histories=len(packed_list), jax=True)
    out = []
    for p in packed_list:
        try:
            out.append(auto_check_packed(model, p, kw))
        except Exception as e:                          # noqa: BLE001
            # check-safe semantics: one pathological history yields an
            # "unknown", not a crashed batch
            obs.checker_swallowed("auto-chain", type(e).__name__,
                                  ops=p.n)
            out.append({"valid": "unknown",
                        "error": f"{type(e).__name__}: {e}"})
    return out


def stage_check_many_packed(model: Model, packed_list, kw: Mapping):
    """STAGE half of :func:`auto_check_many_packed` for the pipelined
    serve lanes: attempt ONLY the bucketed-lockstep batch route with
    the collect deferred (:func:`reach.stage_check_many` — host pack +
    device puts + kernel launches, nothing fetched). Returns a
    :class:`reach.StagedMany` handle (collect later, overlap now), or
    None when the batch is not stageable — the caller then runs the
    ordinary blocking chain, whose verdicts are bit-identical (same
    kernels, same assembly). Never raises: a staging failure declines,
    it does not consume the caller's recovery ladder."""
    from jepsen_tpu.checkers import autotune, reach, transfer

    transfer.record_mode()
    ekw = _engine_kw(kw, ("max_states", "max_slots", "max_dense",
                          "group"))
    if kw.get("devices") or kw.get("force_host"):
        # the mesh lane multi-queues its own window; forced-host runs
        # have no device walk to overlap
        return None
    if "group" not in ekw:
        g = autotune.winner("group", "default")
        if g and str(g).isdigit():
            ekw["group"] = int(g)
    try:
        with obs.span("facade.stage-many",
                      histories=len(packed_list)):
            staged = reach.stage_check_many(model, packed_list, **ekw)
    except Exception as e:                              # noqa: BLE001
        # jtlint: ok fallback — the stage probe must never cost the
        # caller its ladder: decline and let the blocking chain run
        obs.count("pipeline.stage_error")
        logging.getLogger("jepsen.reach").warning(
            "stage_check_many failed (%r); declining to blocking "
            "path", e)
        return None
    if staged is not None:
        engine = ("reach-lockstep"
                  if isinstance(staged, reach.StagedMany)
                  else "reach-batch")
        obs.engine_selected("reach-many", histories=len(packed_list),
                            engines=[engine], staged=True)
    return staged


def auto_check_txn(history: Sequence[Op],
                   kw: Optional[Mapping] = None) -> Dict[str, Any]:
    """The transactional (Elle-style) route: list-append dependency
    inference + cycle search on the MXU closure engine, host SCC
    behind the standard exactly-one-obs-fallback contract (stage
    ``txn-closure`` — recorded inside :mod:`jepsen_tpu.txn`). Exactly
    one ``"selected"`` ledger record per call names the engine that
    produced the verdict, mirroring :func:`auto_check_packed`."""
    import time as _time

    from jepsen_tpu import txn as txn_mod
    from jepsen_tpu.checkers import transfer

    transfer.record_mode()
    ekw = _engine_kw(kw or {}, _TXN_KW)
    t0 = _time.monotonic()
    with obs.span("facade.txn", ops=len(history)):
        res = txn_mod.check_history(history, **ekw)
    obs.engine_selected(res.get("engine", "txn"), txns=res.get("txns"),
                        edges=res.get("edges"),
                        valid=res.get("valid"),
                        elapsed_s=round(_time.monotonic() - t0, 6))
    return res


# keyword subsets understood by each engine; user opts are filtered so one
# checker config can carry opts for every algorithm it may route to.
_REACH_KW = ("max_states", "max_slots", "max_dense", "should_abort")
_TXN_KW = ("devices", "max_dense_txns", "force_host", "consistency")
# check_many additionally shards the key axis over a mesh and admits
# a dispatch-group width override (the serving layer's admission
# coalescer planned the batch at its own --group width; the engine-side
# re-plan must agree with it)
_REACH_MANY_KW = _REACH_KW + ("devices", "group")
_CHUNKED_KW = _REACH_KW + ("n_chunks", "max_matrix", "devices")
_CHUNKLOCK_KW = ("max_states", "max_slots", "max_dense", "n_chunks",
                 "e_pad", "suffix", "interpret")
_FRONTIER_KW = ("max_states", "frontier0", "max_frontier", "time_limit",
                "should_abort", "devices")
_DECOMPOSE_KW = _REACH_KW + ("devices", "time_limit", "should_abort",
                              "max_configs", "frontier0", "max_frontier")
_WGL_KW = ("time_limit", "max_configs", "strategy", "should_abort")
_NATIVE_KW = ("time_limit", "max_configs", "max_states", "abort_flag")
_LINEAR_KW = ("time_limit", "max_configs", "rep", "should_abort")


def _engine_kw(kw: Mapping, allowed: Sequence[str]) -> Dict[str, Any]:
    return {k: v for k, v in kw.items() if k in allowed}


def _competition(model: Model, history: Sequence[Op],
                 kw: Dict[str, Any]) -> Dict[str, Any]:
    """Race the device engine against the CPU searches (WGL — native C++
    when built, else the Python oracle — and JIT-linearization) on
    threads; the first definitive verdict wins and the losers are aborted
    (upstream ``knossos.competition/analysis``, which races wgl against
    linear). If an engine errors or returns unknown, another's verdict is
    used."""
    import queue

    from jepsen_tpu.checkers import (
        frontier, linear, reach, wgl_native, wgl_ref)
    from jepsen_tpu.checkers.search import SearchControl

    ctl = SearchControl(time_limit=kw.get("time_limit")).start()
    native_abort = (ctl.bind_native(wgl_native.AbortFlag())
                    if wgl_native.available() else None)
    verdicts: "queue.Queue" = queue.Queue()

    def run_cpu():
        try:
            if native_abort is not None:
                r = wgl_native.check(model, history,
                                     abort_flag=native_abort,
                                     **_engine_kw(kw, ("max_configs",
                                                       "max_states")))
                verdicts.put(("wgl-native", r))
                return
            r = wgl_ref.check(model, history,
                              should_abort=ctl.should_abort,
                              **_engine_kw(kw, ("max_configs", "strategy")))
            verdicts.put(("wgl-cpu", r))
        # jtlint: ok fallback — racer error carried in the verdict queue; the selector records
        except Exception as e:                          # noqa: BLE001
            verdicts.put(("wgl-cpu", {"valid": "unknown",
                                      "error": str(e)}))

    def run_tpu():
        try:
            # abortable: a losing device engine frees the chip within
            # one segment instead of walking the whole history
            ekw = _engine_kw(kw, _REACH_KW)
            ekw["should_abort"] = ctl.should_abort
            r = reach.check(model, history, **ekw)
            verdicts.put(("reach", r))
        # jtlint: ok fallback — racer error carried in the verdict queue; the selector records
        except Exception as e:                          # noqa: BLE001
            verdicts.put(("reach", {"valid": "unknown", "error": str(e)}))

    def run_linear():
        try:
            r = linear.check(model, history,
                             should_abort=ctl.should_abort,
                             **_engine_kw(kw, ("max_configs", "rep")))
            verdicts.put(("linear", r))
        # jtlint: ok fallback — racer error carried in the verdict queue; the selector records
        except Exception as e:                          # noqa: BLE001
            verdicts.put(("linear", {"valid": "unknown", "error": str(e)}))

    def run_frontier():
        try:
            r = frontier.check(model, history,
                               should_abort=ctl.should_abort,
                               **_engine_kw(kw, ("max_states", "frontier0",
                                                 "max_frontier")))
            verdicts.put(("frontier", r))
        # jtlint: ok fallback — racer error carried in the verdict queue; the selector records
        except Exception as e:                          # noqa: BLE001
            verdicts.put(("frontier", {"valid": "unknown",
                                       "error": str(e)}))

    import contextvars

    def _ctx_target(fn):
        # each racer runs under a copy of the caller's context so spans
        # and ledger records reach any active obs.capture()
        ctx = contextvars.copy_context()
        return lambda: ctx.run(fn)

    threads = [threading.Thread(target=_ctx_target(fn), daemon=True)
               for fn in (run_cpu, run_tpu, run_linear, run_frontier)]
    for t in threads:
        t.start()
    winner: Optional[Dict[str, Any]] = None
    for _ in threads:
        name, r = verdicts.get()
        if r.get("valid") in (True, False):
            winner = dict(r)
            winner["winner"] = name
            break
        winner = winner or r                 # keep an unknown as last resort
    ctl.abort()                              # stop the losing CPU search
    ctl.close()
    return winner or {"valid": "unknown"}


def linearizable(model: Optional[Model] = None,
                 algorithm: str = "auto", **opts: Any) -> Linearizable:
    return Linearizable(model=model, algorithm=algorithm, opts=opts)


@dataclass
class Compose(Checker):
    """Run several named checkers; valid iff all are (upstream
    ``jepsen.checker/compose``)."""
    checkers: Dict[str, Checker]
    name = "compose"

    def check(self, test, history, opts=None):
        results = {name: check_safe(c, test, history, opts)
                   for name, c in self.checkers.items()}
        valids = [r.get("valid") for r in results.values()]
        if all(v is True for v in valids):
            valid: Any = True
        elif any(v is False for v in valids):
            valid = False
        else:
            valid = "unknown"
        return {"valid": valid, "results": results}


def compose(checkers: Dict[str, Checker]) -> Compose:
    return Compose(checkers)


class NoopChecker(Checker):
    """Always valid (upstream ``jepsen.checker/noop``)."""
    name = "noop"

    def check(self, test, history, opts=None):
        return {"valid": True}


class UnbridledOptimism(Checker):
    """Everything is awesome (upstream
    ``jepsen.checker/unbridled-optimism``)."""
    name = "unbridled-optimism"

    def check(self, test, history, opts=None):
        return {"valid": True}


def noop_checker() -> NoopChecker:
    return NoopChecker()


def unbridled_optimism() -> UnbridledOptimism:
    return UnbridledOptimism()


@dataclass
class SetChecker(Checker):
    """Grow-only set workload: ``add`` ops followed by a final ``read``
    returning the set contents (upstream ``jepsen.checker/set``). Valid iff
    every acknowledged add is present and nothing never-attempted is."""
    name = "set"

    def check(self, test, history, opts=None):
        attempts = set()
        acked = set()
        final_read = None
        for op in history:
            if op.process == "nemesis":
                continue
            if op.f == "add":
                v = hashable(op.value)
                if op.type == INVOKE:
                    attempts.add(v)
                elif op.type == OK:
                    acked.add(v)
            elif op.f == "read" and op.type == OK:
                final_read = {hashable(v) for v in (op.value or [])}
        if final_read is None:
            return {"valid": "unknown", "error": "no final read"}
        lost = acked - final_read
        unexpected = final_read - attempts
        recovered = (final_read & attempts) - acked
        return {
            "valid": not lost and not unexpected,
            "attempt-count": len(attempts), "acknowledged-count": len(acked),
            "ok-count": len(final_read & acked),
            "lost-count": len(lost), "lost": sorted(lost, key=repr),
            "unexpected-count": len(unexpected),
            "unexpected": sorted(unexpected, key=repr),
            "recovered-count": len(recovered),
            "recovered": sorted(recovered, key=repr),
        }


def set_checker() -> SetChecker:
    return SetChecker()


@dataclass
class CounterChecker(Checker):
    """Counter workload: ``add`` deltas (possibly failing or crashing) and
    ``read`` observations (upstream ``jepsen.checker/counter``). Each ok
    read must lie within the interval of possible counter values given
    which adds had definitely / possibly taken effect at that moment."""
    name = "counter"

    def check(self, test, history, opts=None):
        pairs = h.pair(h.index(list(history))
                       if history and history[0].index < 0 else list(history))
        adds, reads = [], []
        INF = 1 << 60
        for p in pairs:
            if p.failed:
                continue
            op = p.invoke
            ret = p.complete.index if not p.crashed else INF
            if op.f == "add":
                adds.append((op.index, ret, op.value or 0, p.crashed))
            elif op.f == "read" and not p.crashed:
                v = p.complete.value
                if v is not None:
                    reads.append((op.index, ret, v))
        if not reads:
            return {"valid": True, "reads-checked": 0}
        a_inv = np.array([a[0] for a in adds], np.int64).reshape(-1, 1)
        a_ret = np.array([a[1] for a in adds], np.int64).reshape(-1, 1)
        a_d = np.array([a[2] for a in adds], np.float64).reshape(-1, 1)
        a_crash = np.array([a[3] for a in adds], bool).reshape(-1, 1)
        bad = []
        lo_all = hi_all = 0.0
        for chunk in range(0, len(reads), 4096):
            rs = reads[chunk:chunk + 4096]
            r_inv = np.array([r[0] for r in rs], np.int64)
            r_ret = np.array([r[1] for r in rs], np.int64)
            r_v = np.array([r[2] for r in rs], np.float64)
            if len(adds):
                # definitely applied: acked and returned before the read began
                exact = (~a_crash) & (a_ret < r_inv)
                # possibly applied: invoked before the read returned
                maybe = (a_inv < r_ret) & ~exact
                base = (a_d * exact).sum(axis=0)
                lo = base + (np.minimum(a_d, 0) * maybe).sum(axis=0)
                hi = base + (np.maximum(a_d, 0) * maybe).sum(axis=0)
            else:
                lo = hi = np.zeros(len(rs))
            out = (r_v < lo) | (r_v > hi)
            for i in np.nonzero(out)[0]:
                bad.append({"value": rs[i][2], "index": int(rs[i][0]),
                            "possible": [float(lo[i]), float(hi[i])]})
        return {"valid": not bad, "reads-checked": len(reads),
                "errors": bad[:32], "error-count": len(bad)}


def counter() -> CounterChecker:
    return CounterChecker()


@dataclass
class QueueChecker(Checker):
    """Queue dequeues must come from somewhere: no value dequeued more times
    than it was enqueue-attempted (upstream ``jepsen.checker/queue``)."""
    name = "queue"

    def check(self, test, history, opts=None):
        enq = _Counter()
        deq = _Counter()
        for op in history:
            if op.f == "enqueue" and op.type == INVOKE:
                enq[hashable(op.value)] += 1
            elif op.f == "dequeue" and op.type == OK:
                deq[hashable(op.value)] += 1
        overdrawn = {v: c - enq[v] for v, c in deq.items() if c > enq[v]}
        return {"valid": not overdrawn,
                "dequeued-count": sum(deq.values()),
                "overdrawn": dict(sorted(overdrawn.items(),
                                         key=lambda kv: repr(kv[0]))[:32])}


def queue() -> QueueChecker:
    return QueueChecker()


@dataclass
class TotalQueueChecker(Checker):
    """Every acknowledged enqueue is dequeued exactly once; nothing is
    dequeued that was never enqueued (upstream
    ``jepsen.checker/total-queue``)."""
    name = "total-queue"

    def check(self, test, history, opts=None):
        attempts = _Counter()
        acked = _Counter()
        deq = _Counter()
        for op in history:
            if op.f == "enqueue" and op.type == INVOKE:
                attempts[hashable(op.value)] += 1
            elif op.f == "enqueue" and op.type == OK:
                acked[hashable(op.value)] += 1
            elif op.f == "dequeue" and op.type == OK:
                deq[hashable(op.value)] += 1
        lost = {v: c - deq[v] for v, c in acked.items() if c > deq[v]}
        unexpected = {v: c for v, c in deq.items() if v not in attempts}
        duplicated = {v: c - attempts[v] for v, c in deq.items()
                      if v in attempts and c > attempts[v]}
        recovered = {v: c for v, c in deq.items()
                     if v in attempts and v not in acked}
        return {
            "valid": not lost and not unexpected,
            "attempt-count": sum(attempts.values()),
            "acknowledged-count": sum(acked.values()),
            "ok-count": sum((deq & acked).values()),
            "lost-count": sum(lost.values()),
            "lost": dict(list(lost.items())[:32]),
            "unexpected-count": sum(unexpected.values()),
            "unexpected": dict(list(unexpected.items())[:32]),
            "duplicated-count": sum(duplicated.values()),
            "recovered-count": sum(recovered.values()),
        }


def total_queue() -> TotalQueueChecker:
    return TotalQueueChecker()


@dataclass
class StatsChecker(Checker):
    """Op counts by function and completion type (later-era
    ``jepsen.checker/stats``); valid unless some function had zero
    successes."""
    name = "stats"

    def check(self, test, history, opts=None):
        by_f: Dict[Any, _Counter] = {}
        for op in history:
            if op.type == INVOKE or op.process == "nemesis":
                continue
            by_f.setdefault(op.f, _Counter())[op.type] += 1
        out = {}
        valid = True
        for f, c in sorted(by_f.items(), key=lambda kv: repr(kv[0])):
            n_ok, n_fail, n_info = c[OK], c[FAIL], c[INFO]
            ok_frac = n_ok / max(1, n_ok + n_fail + n_info)
            f_valid = n_ok > 0
            valid = valid and f_valid
            out[f] = {"valid": f_valid, "count": n_ok + n_fail + n_info,
                      "ok-count": n_ok, "fail-count": n_fail,
                      "info-count": n_info, "ok-fraction": round(ok_frac, 4)}
        return {"valid": valid if out else True, "by-f": out}


def stats() -> StatsChecker:
    return StatsChecker()
