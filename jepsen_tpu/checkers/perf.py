"""Latency / rate charts — upstream ``jepsen/src/jepsen/checker/perf.clj``
(SURVEY.md §2.1), which extracts per-op latency points and shells out to
gnuplot; here the extraction is NumPy and the plotting is matplotlib
(present in the image; no external binaries).
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from jepsen_tpu.checkers.facade import Checker
from jepsen_tpu.op import FAIL, INFO, INVOKE, OK, Op

NS = 1e9


def latency_points(history: Sequence[Op]
                   ) -> Dict[str, List[Tuple[float, float]]]:
    """(time-of-invoke [s], latency [ms]) points grouped by completion type
    (upstream ``perf/latencies``). Requires op ``time`` in ns."""
    pending: Dict[Any, Op] = {}
    out: Dict[str, List[Tuple[float, float]]] = {OK: [], FAIL: [], INFO: []}
    for op in history:
        if op.process == "nemesis":
            continue
        if op.type == INVOKE:
            pending[op.process] = op
        else:
            inv = pending.pop(op.process, None)
            if inv is not None and inv.time >= 0 and op.time >= 0:
                out[op.type].append(
                    (inv.time / NS, (op.time - inv.time) / 1e6))
    return out


def rate_points(history: Sequence[Op], dt: float = 1.0
                ) -> Dict[str, Tuple[np.ndarray, np.ndarray]]:
    """Completions/sec in ``dt``-second windows, by type (upstream
    ``perf/rate``)."""
    times: Dict[str, List[float]] = {OK: [], FAIL: [], INFO: []}
    for op in history:
        if op.type != INVOKE and op.process != "nemesis" and op.time >= 0:
            times[op.type].append(op.time / NS)
    out = {}
    tmax = max((max(v) for v in times.values() if v), default=0.0)
    edges = np.arange(0.0, tmax + dt, dt)
    for typ, ts in times.items():
        hist, _ = np.histogram(ts, bins=edges) if len(edges) > 1 else \
            (np.zeros(0), None)
        out[typ] = (edges[:-1] if len(edges) > 1 else np.zeros(0), hist / dt)
    return out


def _plot_latency(history, path, title):
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    pts = latency_points(history)
    fig, ax = plt.subplots(figsize=(10, 4))
    styles = {OK: ("#6db66d", "."), FAIL: ("#d66", "x"), INFO: ("#d6a76d", "+")}
    for typ, (color, marker) in styles.items():
        if pts[typ]:
            xs, ys = zip(*pts[typ])
            ax.semilogy(xs, ys, marker, color=color, label=typ, ms=3)
    ax.set_xlabel("time (s)")
    ax.set_ylabel("latency (ms)")
    ax.set_title(title)
    ax.legend(loc="upper right")
    fig.tight_layout()
    fig.savefig(path, dpi=110)
    plt.close(fig)


def _plot_rate(history, path, title):
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    pts = rate_points(history)
    fig, ax = plt.subplots(figsize=(10, 4))
    colors = {OK: "#6db66d", FAIL: "#d66", INFO: "#d6a76d"}
    for typ, (xs, ys) in pts.items():
        if len(xs):
            ax.plot(xs, ys, color=colors[typ], label=typ)
    ax.set_xlabel("time (s)")
    ax.set_ylabel("ops/s")
    ax.set_title(title)
    ax.legend(loc="upper right")
    fig.tight_layout()
    fig.savefig(path, dpi=110)
    plt.close(fig)


class LatencyGraph(Checker):
    """Writes ``latency-raw.png`` (upstream
    ``jepsen.checker/latency-graph``)."""
    name = "latency-graph"

    def check(self, test: Optional[Mapping], history: Sequence[Op],
              opts: Optional[Mapping] = None) -> Dict[str, Any]:
        out_dir = (opts or {}).get("dir") or (test or {}).get("dir") or (test or {}).get("store_dir")
        if not out_dir:
            return {"valid": True, "skipped": "no store dir"}
        path = os.path.join(out_dir, "latency-raw.png")
        _plot_latency(history, path, str((test or {}).get("name", "latency")))
        return {"valid": True, "file": path}


class RateGraph(Checker):
    """Writes ``rate.png`` (upstream ``jepsen.checker/rate-graph``)."""
    name = "rate-graph"

    def check(self, test: Optional[Mapping], history: Sequence[Op],
              opts: Optional[Mapping] = None) -> Dict[str, Any]:
        out_dir = (opts or {}).get("dir") or (test or {}).get("dir") or (test or {}).get("store_dir")
        if not out_dir:
            return {"valid": True, "skipped": "no store dir"}
        path = os.path.join(out_dir, "rate.png")
        _plot_rate(history, path, str((test or {}).get("name", "rate")))
        return {"valid": True, "file": path}


def latency_graph() -> LatencyGraph:
    return LatencyGraph()


def rate_graph() -> RateGraph:
    return RateGraph()
