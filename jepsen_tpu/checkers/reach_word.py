"""Word-packed kernel body for the dense-reachability returns walk —
the whole mask axis as machine words, fire passes as bitwise algebra.

PR 10 landed the first instance of this body inside the streaming
session's :class:`~jepsen_tpu.checkers.reach.FrontierCarry` (one
uint32/uint64 word per state, ~33x the dense einsum step on XLA:CPU,
where the gather/einsum chain is thunk-dispatch-bound). This module
lifts it out as a FIRST-CLASS kernel body the post-hoc engines select
through the ``reach`` dispatch seams, and generalizes the single word
to **word vectors**: the frontier is ``R[S, NW]`` uint32 with bit
``m & 31`` of word ``m >> 5`` = config ``(s, m)`` reachable, so
``M = 2**W > 32`` geometries (W > 5) run WITHOUT x64 mode — the
uint64 body (which jax silently downcasts outside x64) is retired in
favor of two-or-more uint32 words.

Fire algebra (semantics of ``reach._ret_step``, W passes per return):

- slot ``j < 5`` moves a config's mask bit WITHIN its word: the
  bit-j-clear half shifts up by ``2**j`` (``(R & ~cmask32[j]) <<
  2**j`` — the clear positions stay inside their 32-block, so no bit
  crosses a word boundary);
- slot ``j >= 5`` moves WHOLE WORDS: bit ``j`` of mask ``m`` is bit
  ``j - 5`` of its word index, so the fire is a word-axis
  permutation — the same reshape/stack trick the dense walk plays on
  the mask axis, one level up.
- the transition gather is unchanged: per pending slot, each state's
  shifted contribution OR-scatters through the transition column
  (row ``S`` = discard), reduced with :func:`jax.lax.reduce` over
  (source state, slot).

Projection on the returning slot is the inverse shift (within-word
``>> 2**j`` on the bit-set half, or the word-axis down-permutation),
selected per step from the dynamic slot index. Death indices are
exact per step (identity pads — ``ret_slot = -1`` — cannot kill a
live set), so the post-hoc entry needs no unroll-window refinement.

Selection: :func:`jepsen_tpu.checkers.autotune` winners first (the
persisted table), then heuristics; ``JEPSEN_TPU_NO_WORD_WALK=1``
opts every word body out. Differential tests pin this body
bit-identical to the dense ``_walk_returns`` einsum program and the
lockstep batch kernel across ragged buckets, crashes, and injected
violations (``tests/test_word_kernels.py``).
"""
from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import numpy as np

from jepsen_tpu import obs

# geometry admission: the transition-gather intermediate is
# [S, S, W, NW] words per fire pass — bound it so a state-rich or
# slot-rich geometry keeps the dense/einsum bodies
_MAX_GATHER_ELEMS = 1 << 22
_MAX_WORDS = 32                          # NW <= 32  ==>  W <= 10


def enabled() -> bool:
    """``JEPSEN_TPU_NO_WORD_WALK=1`` opts out every word-packed walk
    body (the carried-frontier one and the post-hoc ones alike);
    consulted per call."""
    return not os.environ.get("JEPSEN_TPU_NO_WORD_WALK")


def n_words(M: int) -> int:
    """uint32 words per state for a mask axis of ``M = 2**W``."""
    return max(1, int(M) >> 5)


def admits(S: int, W: int, M: int) -> bool:
    nw = n_words(M)
    return (nw <= _MAX_WORDS
            and S * S * max(W, 1) * nw <= _MAX_GATHER_ELEMS)


# -- packing helpers (host side) -------------------------------------------

def pack_words(R: np.ndarray) -> np.ndarray:
    """bool [S, M] -> uint32 [S, NW]; bit ``m & 31`` of word
    ``m >> 5`` = R[s, m]. For M < 32 the high bits are simply never
    set."""
    S, M = R.shape
    if M < 32:
        R = np.concatenate(
            [R, np.zeros((S, 32 - M), bool)], axis=1)
    packed = np.packbits(np.ascontiguousarray(R, np.uint8),
                         axis=1, bitorder="little")
    return packed.view(np.uint32).reshape(S, -1)


def unpack_words(words: np.ndarray, M: int) -> np.ndarray:
    """uint32 [S, NW] -> bool [S, M] (inverse of :func:`pack_words`)."""
    S = words.shape[0]
    b = np.unpackbits(words.view(np.uint8).reshape(S, -1),
                      axis=1, bitorder="little")
    return b[:, :M].astype(bool)


def pack_rows(R: np.ndarray) -> np.ndarray:
    """bool [rows, N] -> uint32 [rows, ceil(N/32)], any N: the general
    row packing behind the multi-host DCN payload (per-chunk summary
    bits cross hosts 32x denser than dense f32). Same little-endian
    bit layout as :func:`pack_words`, which it generalizes past
    power-of-two mask widths."""
    rows, N = R.shape
    pad = (-N) % 32
    if pad:
        R = np.concatenate([R, np.zeros((rows, pad), bool)], axis=1)
    packed = np.packbits(np.ascontiguousarray(R, np.uint8),
                         axis=1, bitorder="little")
    return packed.view(np.uint32).reshape(rows, (N + pad) // 32)


def unpack_rows(words: np.ndarray, N: int) -> np.ndarray:
    """uint32 [rows, NW] -> bool [rows, N] (inverse of
    :func:`pack_rows`)."""
    rows, NW = words.shape
    b = np.unpackbits(
        np.ascontiguousarray(words).view(np.uint8)
        .reshape(rows, NW * 4),
        axis=1, bitorder="little")
    return b[:, :N].astype(bool)


def table_from_P(P: np.ndarray) -> np.ndarray:
    """Recover the flat transition table the word body gathers from a
    per-op transition-matrix tensor ``P[o, s, t]`` (one-hot rows,
    all-zero = no transition): ``T[s, o]`` = target state or -1. The
    lockstep seams carry only P, so the word body derives T instead
    of threading the memo through every scheduler."""
    O1, S, _ = P.shape
    tgt = P.argmax(axis=2).astype(np.int32)          # [O1, S]
    has = P.max(axis=2) > 0.5
    T = np.where(has, tgt, -1).astype(np.int32)      # [O1, S]
    return np.ascontiguousarray(T.T)                 # [S, O1]


def pad_table(table: np.ndarray) -> np.ndarray:
    """Append the -1 sentinel column (pad slots gather it and
    discard)."""
    S = table.shape[0]
    return np.concatenate(
        [table, -np.ones((S, 1), table.dtype)], axis=1) \
        .astype(np.int32)


# -- the kernel body --------------------------------------------------------

def _cmask32(W: int) -> np.ndarray:
    """32-bit within-word masks: bit m of ``cmask32[j]`` set iff mask
    position ``m`` has bit j set (j < 5; the pattern repeats every 32
    mask positions, so one word serves every word of the vector)."""
    m = np.arange(32)
    return np.array(
        [sum(1 << int(x) for x in m[(m >> j) & 1 == 1])
         for j in range(min(W, 5))] or [0], np.uint32)


def _walk_words(Tpad, R0, ret_slot, slot_ops):
    """Multi-word returns walk: ``Tpad`` i32[S, O+1] (col O = -1
    sentinel), ``R0`` uint32[S, NW], blocks of (ret_slot, slot_ops)
    as in :func:`reach._walk_returns`. Returns ``(R, any_dead,
    first_dead)`` with the EXACT step index of the first death."""
    import jax.numpy as jnp
    from jax import lax

    S = Tpad.shape[0]
    O1 = Tpad.shape[1] - 1
    W = slot_ops.shape[1]
    NW = R0.shape[1]
    cmask = jnp.asarray(_cmask32(W))
    # firing slot j moves mask m to m | (1 << j): a shift by 2**j BIT
    # POSITIONS, i.e. multiplication by 2**(2**j) (bit-exact on the
    # bit-j-clear half; j < 5 stays within one 32-bit word)
    mult = jnp.asarray(
        np.array([np.uint32(1) << (1 << j) for j in range(min(W, 5))]
                 or [np.uint32(1)], np.uint32))
    s_idx = jnp.arange(S)
    zero = np.zeros((), np.uint32)[()]

    def _shift_up(R, jj: int):
        """Static fire shift of slot ``jj``: the bit-jj-clear half of
        every config moves to the bit-set half."""
        if jj < 5:
            lo = R & (~cmask[jj])
            return lo * mult[jj]                     # << 2**jj, exact
        jb = jj - 5
        Rr = R.reshape(S, NW >> (jb + 1), 2, 1 << jb)
        lo = Rr[:, :, 0, :]
        return jnp.stack([jnp.zeros_like(lo), lo],
                         axis=2).reshape(S, NW)

    def step(R, inp):
        j, ops_row = inp
        o = jnp.where(ops_row < 0, O1, ops_row)
        tcols = Tpad[:, o]                           # [S, W]
        tgt = jnp.where(tcols < 0, S, tcols)         # row S = discard
        for _ in range(W):
            shifted = jnp.stack([_shift_up(R, jj) for jj in range(W)],
                                axis=1)              # [S, W, NW]
            oh = s_idx[:, None, None] == tgt[None, :, :]
            contrib = jnp.where(oh[:, :, :, None],
                                shifted[None, :, :, :],
                                jnp.zeros((), jnp.uint32))
            fired = lax.reduce(contrib, zero, lax.bitwise_or, (1, 2))
            R = R | fired
        jj = jnp.maximum(j, 0)
        # projection: keep the bit-j-set half, clearing the bit — the
        # exact inverse shift, selected by the dynamic slot index
        jw = jnp.minimum(jj, mult.shape[0] - 1)
        within = (R & cmask[jw]) // mult[jw]
        jb = jnp.maximum(jj - 5, 0).astype(jnp.uint32)
        wsel = jnp.arange(NW, dtype=jnp.uint32)
        src_w = (wsel | (jnp.uint32(1) << jb)).astype(jnp.int32)
        gathered = jnp.take(R, jnp.minimum(src_w, NW - 1), axis=1)
        keep = ((wsel >> jb) & 1) == 0
        cross = jnp.where(keep[None, :], gathered,
                          jnp.zeros((), jnp.uint32))
        proj = jnp.where(jj < 5, within, cross)
        R = jnp.where(j >= 0, proj, R)
        return R, R.max() == zero

    R, deads = lax.scan(step, R0, (ret_slot, slot_ops))
    return R, deads.any(), deads.argmax()


@functools.cache
def _jitted_walk_words():
    # deliberately NOT donated: the word-packed carry is a few machine
    # words per state, and donating it was measured to corrupt the
    # aliased buffer under concurrent jax dispatch on the CPU client
    # (the PR-10 chaos finding; the regression test pins it)
    import jax
    return jax.jit(_walk_words)


@functools.cache
def _jitted_walk_words_batch():
    """vmap over the lane axis (lockstep batch seam): one shared
    transition table, per-lane streams and frontiers."""
    import jax
    return jax.jit(jax.vmap(_walk_words, in_axes=(None, 0, 0, 0)))


@functools.cache
def _jitted_walk_words_mega():
    """vmap over the SESSION lane axis (mega-batch session dispatch):
    unlike the lockstep batch seam, every lane carries its OWN
    transition table — mega-group members share a walk geometry
    (S, O, W, NW), not a model memo. Like the per-session walk jits,
    deliberately NOT donated (the PR-10 aliased-buffer corruption
    finding applies to any carried frontier)."""
    import jax
    return jax.jit(jax.vmap(_walk_words, in_axes=(0, 0, 0, 0)))


def _pad_pow2(n: int, floor: int = 64) -> int:
    return max(floor, 1 << max(0, (n - 1)).bit_length())


def walk_returns_words(table: np.ndarray, ret_slot: np.ndarray,
                       slot_ops: np.ndarray, M: int,
                       R0: Optional[np.ndarray] = None
                       ) -> Tuple[int, np.ndarray]:
    """Post-hoc single-history entry: walk the full return stream on
    the word-packed body. ``table`` i32[S, O] (memo layout, no
    sentinel column — it is appended here); ``R0`` bool[S, M]
    (default: initial state 0, mask 0). Returns ``(dead,
    final_words)``: ``dead`` the exact first dead return index (-1 =
    linearizable), ``final_words`` the final frontier uint32[S, NW].
    Blocks pad to powers of two (identity steps) so a serving daemon
    compiles log2-many walk geometries."""
    import jax.numpy as jnp

    from jepsen_tpu.checkers import transfer

    S = int(table.shape[0])
    W = int(slot_ops.shape[1])
    n = int(ret_slot.shape[0])
    Tpad = pad_table(table)
    if R0 is None:
        R0 = np.zeros((S, M), bool)
        R0[0, 0] = True
    R0w = pack_words(np.ascontiguousarray(R0, bool))
    n_pad = _pad_pow2(max(n, 1))
    rs = np.full(n_pad, -1, np.int32)
    so = np.full((n_pad, W), -1, np.int32)
    rs[:n] = ret_slot
    so[:n] = slot_ops
    transfer.count_put(
        int(Tpad.nbytes + R0w.nbytes + rs.nbytes + so.nbytes),
        int(Tpad.nbytes + R0.size * 4 + (rs.size + so.size) * 4))
    R, any_dead, first = _jitted_walk_words()(
        jnp.asarray(Tpad), jnp.asarray(R0w), jnp.asarray(rs),
        jnp.asarray(so))
    obs.count("reach.word_walk")
    if not bool(any_dead):
        return -1, np.asarray(R)
    return min(int(first), max(n - 1, 0)), np.asarray(R)


# -- mega-batch session advance ---------------------------------------------

def mega_geometry(carry) -> Optional[tuple]:
    """The walk-geometry signature a :class:`~.reach.FrontierCarry`
    contributes to a mega-group, or ``None`` when the carry cannot
    participate (dense body, or word walks opted out). Members of one
    group must agree on every compiled dimension of the batched walk:
    state count, padded table width, slot count, and words per
    state — nothing else (tables and frontiers are per-lane
    operands). Cached on the carry: a carry instance's geometry is
    fixed at seed (growth replaces the instance), and this runs
    several times per append on the mega hot path."""
    g = getattr(carry, "_mega_geom", False)
    if g is not False:
        return g
    if not getattr(carry, "words", False):
        g = None
    else:
        O1 = int(carry._T.shape[1])          # includes the -1 sentinel
        g = (int(carry.S), O1, int(carry.W), int(carry._nw))
    carry._mega_geom = g
    return g


def advance_frontiers_mega(carries, blocks) -> list:
    """ONE kernel launch advances every member of a same-geometry
    mega-group — launch + collect in one blocking call. Composition
    of :func:`launch_frontiers_mega` / :func:`collect_frontiers_mega`
    (the stage/collect split the pipelined dispatcher uses), so the
    two paths are bit-identical by construction."""
    return collect_frontiers_mega(launch_frontiers_mega(carries,
                                                        blocks))


def launch_frontiers_mega(carries, blocks) -> "MegaInflight":
    """LAUNCH half of the mega-group advance: host stacking + ONE put
    + ONE batched kernel dispatch, nothing fetched.

    Member frontiers and their per-lane transition
    tables are stacked along a lane axis ON HOST (numpy) and cross
    the wire as ONE put, walked through
    :func:`_jitted_walk_words_mega`, and scattered back to their
    owning carries from ONE bulk fetch. Host-side assembly is the
    point, not a compromise: stacking thousands of tiny per-lane
    device arrays (and lazily slicing the result back out) costs
    ~1ms of dispatch overhead PER LANE on the host-bound path —
    drowning the walk itself — while a numpy gather is ~1us per
    lane and the whole group's operands are a few hundred KB.
    Between mega waves a member's frontier lives as host word
    vectors (its next solo advance re-puts ``[S, NW]`` words — a
    few dozen bytes). Ragged member block lengths are handled the
    way every walk body handles padding: each lane pads to the
    common power-of-two length with identity steps
    (``ret_slot = -1``), which cannot kill a live set, so each lane
    is effectively masked dead-proof past its own length and death
    indices stay exact per lane.

    ``blocks`` is a list of ``(ret_slot, slot_ops)`` pairs aligned
    with ``carries``. Returns the per-member list of exact first dead
    return indices (-1 = survived), with each carry's frontier and
    ``advanced_returns`` updated exactly as its own
    :meth:`~.reach.FrontierCarry.advance` would have — the
    differential suite pins the two bit-identical. Lane count pads to
    a power of two (the PR-4 idiom: log2-many compiled lane
    geometries) with all-zero lanes whose results are discarded."""
    import jax.numpy as jnp

    from jepsen_tpu.checkers import transfer

    L = len(carries)
    if L == 0:
        return []
    sig = mega_geometry(carries[0])
    assert sig is not None
    for c in carries[1:]:
        assert mega_geometry(c) == sig, "mega-group geometry mismatch"
    W = int(carries[0].W)
    nw = int(carries[0]._nw)
    min_block = getattr(carries[0], "_MIN_BLOCK", 64)
    n_pad = max(min_block,
                max(_pad_pow2(max(len(rs), 1), min_block)
                    for rs, _ in blocks))
    L_pad = 1 << max(0, (L - 1)).bit_length()
    rs = np.full((L_pad, n_pad), -1, np.int32)
    so = np.full((L_pad, n_pad, W), -1, np.int32)
    for i, (b_rs, b_so) in enumerate(blocks):
        n = len(b_rs)
        rs[i, :n] = b_rs
        so[i, :n] = b_so

    def _lane_T(c):
        T_h = getattr(c, "_T_host", None)
        return T_h if T_h is not None else np.asarray(c._T)

    def _lane_R(c):
        r = c._R
        # first mega wave after a seed/solo advance still holds a
        # device frontier; every later wave finds host words here
        if not isinstance(r, np.ndarray):
            r = np.asarray(r)
        return r if nw > 1 else r[:, None]

    # pad lanes are all-zero: their streams are pure identity steps
    # (ret_slot = -1), their outputs are never read, and calloc'd
    # rows are cheaper than stacking replicas of a real lane
    real_T = np.stack([_lane_T(c) for c in carries])
    real_R = np.stack([_lane_R(c) for c in carries])
    if L_pad > L:
        T_h = np.zeros((L_pad,) + real_T.shape[1:], real_T.dtype)
        R0_h = np.zeros((L_pad,) + real_R.shape[1:], real_R.dtype)
        T_h[:L] = real_T
        R0_h[:L] = real_R
    else:
        T_h, R0_h = real_T, real_R
    transfer.count_put(
        int(rs.nbytes + so.nbytes + T_h.nbytes + R0_h.nbytes),
        int((rs.size + so.size) * 4 + T_h.nbytes + R0_h.nbytes))
    R, any_dead, first = _jitted_walk_words_mega()(
        jnp.asarray(T_h), jnp.asarray(R0_h), jnp.asarray(rs),
        jnp.asarray(so))
    obs.count("reach.word_walk_mega")
    return MegaInflight(carries, blocks, R, any_dead, first, L,
                        L_pad, nw)


class MegaInflight:
    """A launched-but-unfetched mega-group advance: the batched walk
    is queued on device, no result has crossed the wire. Produced by
    :func:`launch_frontiers_mega`, consumed by
    :func:`collect_frontiers_mega` — the stage/collect split of the
    mega path (ISSUE 20): the dispatcher runs the next wave's host
    bookkeeping between the two, so it overlaps the device walk
    instead of serializing behind the fetch."""

    __slots__ = ("carries", "blocks", "R", "any_dead", "first", "L",
                 "L_pad", "nw")

    def __init__(self, carries, blocks, R, any_dead, first, L, L_pad,
                 nw):
        self.carries = carries
        self.blocks = blocks
        self.R = R
        self.any_dead = any_dead
        self.first = first
        self.L = L
        self.L_pad = L_pad
        self.nw = nw

    def ready(self) -> bool:
        from jepsen_tpu.checkers import dispatch_core
        return all(dispatch_core.poll_ready(x)
                   for x in (self.R, self.any_dead, self.first))


def collect_frontiers_mega(inf: MegaInflight) -> list:
    """COLLECT half of the mega advance: the ONE bulk fetch, the
    numpy scatter back into each owning carry, and the per-member
    exact dead indices — everything downstream of the kernel."""
    if not inf:                         # empty group launched to []
        return []
    carries, blocks, nw = inf.carries, inf.blocks, inf.nw
    any_np = np.asarray(inf.any_dead)
    first_np = np.asarray(inf.first)
    # ONE bulk fetch brings every real lane's frontier home; the
    # scatter below is numpy views, not per-lane device slices
    R_h = np.asarray(inf.R[:inf.L]) if inf.L_pad > inf.L \
        else np.asarray(inf.R)
    deads = []
    for i, c in enumerate(carries):
        n = len(blocks[i][0])
        c._R = R_h[i] if nw > 1 else R_h[i, :, 0]
        if n == 0 or not bool(any_np[i]):
            dead = -1
            c.advanced_returns += n
        else:
            dead = min(int(first_np[i]), n - 1)
            c.advanced_returns += dead + 1
        deads.append(dead)
    return deads
