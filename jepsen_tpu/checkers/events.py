"""Event-stream preprocessing for the device reachability engine.

Upstream analogue: ``knossos/src/knossos/linear.clj``'s per-event walk and
``knossos/src/knossos/linear/config.clj``'s packed config sets (SURVEY.md
§2.2). Where the upstream advances an explicit *set of configuration
objects* per history event, the TPU engine (:mod:`.reach`) advances a dense
boolean reachability tensor indexed by ⟨model-state, linearized-pending
bitmask⟩. This module builds the static, int-only event stream that tensor
program consumes:

- Each analysis entry contributes an ``invoke`` event and (unless crashed)
  a ``return`` event, ordered by their history ranks.
- Pending operations are assigned **slots** (lowest free slot at invoke,
  freed at return). The slot count ``W`` bounds concurrency; the device
  bitmask axis has size ``2**W``. Crashed ops hold their slot forever —
  they may linearize at any later time — except crashed ops whose
  transition is a no-op in every model state (e.g. a crashed blind read),
  which are provably irrelevant and dropped here.

Everything produced is a NumPy int array; only these (plus the memoized
transition table) cross to the device.
"""
from __future__ import annotations

import heapq
from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np

from jepsen_tpu.history import PackedHistory
from jepsen_tpu.models.memo import Memo

KIND_INVOKE = 0
KIND_RETURN = 1
KIND_PAD = 2


class ConcurrencyOverflow(RuntimeError):
    """Raised when the history needs more pending-op slots than ``max_slots``
    — the dense ``2**W`` bitmask axis would not fit on device. Callers fall
    back to the CPU search (upstream behaviour: knossos.linear dies on
    config-set explosion and the competition falls back to WGL)."""


@dataclass(frozen=True)
class EventStream:
    """Static event stream for one history.

    ``kind``/``slot``/``opid``/``entry`` are parallel ``i32[E]`` arrays;
    ``opid`` is -1 for returns. ``W`` is the slot count (bitmask width).
    ``n_events`` may be < len(kind) when padded for batching.
    """
    kind: np.ndarray
    slot: np.ndarray
    opid: np.ndarray
    entry: np.ndarray
    W: int
    n_events: int
    n_entries: int          # entries surviving preprocessing (incl. crashed)
    n_dropped_crashed: int  # crashed no-op entries dropped

    @property
    def E(self) -> int:
        return len(self.kind)


def build(packed: PackedHistory, memo: Memo, *,
          max_slots: int = 20,
          drop_noop_crashed: bool = True) -> EventStream:
    """Assign slots and linearize the (invoke, return) events of ``packed``
    into a flat stream. Raises :class:`ConcurrencyOverflow` if more than
    ``max_slots`` ops are ever pending at once.

    Event-array construction is vectorized NumPy; the inherently
    sequential lowest-free-slot assignment runs in C++
    (``native/preproc.cpp``) with a Python fallback."""
    from jepsen_tpu.checkers import preproc_native

    n = packed.n
    crashed = np.asarray(packed.crashed, bool)
    if drop_noop_crashed and n:
        tbl = memo.table
        states = np.arange(tbl.shape[0], dtype=tbl.dtype)[:, None]
        noop_op = np.all((tbl == states) | (tbl == -1), axis=0)
        drop = crashed & noop_op[packed.op_id]
    else:
        drop = np.zeros(n, bool)
    dropped = int(drop.sum())
    idx = np.nonzero(~drop)[0].astype(np.int32)
    ridx = idx[~crashed[idx]]
    # ranks are distinct history indices, so returns order unambiguously
    ranks = np.concatenate([packed.inv_ev[idx], packed.ret_ev[ridx]])
    kinds = np.concatenate([
        np.full(len(idx), KIND_INVOKE, np.int32),
        np.full(len(ridx), KIND_RETURN, np.int32)])
    entries = np.concatenate([idx, ridx]).astype(np.int32)
    order = np.argsort(ranks, kind="stable")
    kind = kinds[order]
    entry = entries[order]
    E = len(kind)
    opid = np.where(kind == KIND_INVOKE,
                    packed.op_id[entry].astype(np.int32),
                    np.int32(-1)).astype(np.int32)
    native = preproc_native.assign_slots(kind, entry, n, max_slots)
    if native is not None:
        slot, hi = native
        if hi < 0:
            raise ConcurrencyOverflow(
                f"history needs >{max_slots} pending-op slots")
    else:
        slot = np.zeros(E, np.int32)
        free: list = []         # min-heap: reuse lowest slots first
        hi = 0                  # next never-used slot
        slot_of = {}
        for e in range(E):
            i = int(entry[e])
            if kind[e] == KIND_INVOKE:
                s = heapq.heappop(free) if free else hi
                if s == hi:
                    hi += 1
                    if hi > max_slots:
                        raise ConcurrencyOverflow(
                            f"history needs >{max_slots} pending-op slots")
                slot_of[i] = s
                slot[e] = s
            else:
                s = slot_of.pop(i)
                slot[e] = s
                heapq.heappush(free, s)
    return EventStream(kind=kind, slot=slot, opid=opid, entry=entry,
                       W=int(hi), n_events=E, n_entries=n - dropped,
                       n_dropped_crashed=dropped)


def pad(stream: EventStream, E: int, W: Optional[int] = None) -> EventStream:
    """Pad a stream to ``E`` events (kind=PAD) and widen to ``W`` slots, for
    batching several keys' streams into one vmapped device call."""
    W = stream.W if W is None else W
    if W < stream.W or E < stream.n_events:
        raise ValueError("cannot shrink a stream")
    ext = E - stream.E

    def _p(a: np.ndarray, fill: int) -> np.ndarray:
        return np.concatenate([a, np.full(ext, fill, a.dtype)])

    return EventStream(
        kind=_p(stream.kind, KIND_PAD), slot=_p(stream.slot, 0),
        opid=_p(stream.opid, -1), entry=_p(stream.entry, 0),
        W=W, n_events=stream.n_events, n_entries=stream.n_entries,
        n_dropped_crashed=stream.n_dropped_crashed)


@dataclass(frozen=True)
class ReturnStream:
    """Returns-only view of an :class:`EventStream` for the fast device
    walk (:func:`jepsen_tpu.checkers.reach._walk_returns`).

    Invoke events never change the reachable set — they only update the
    slot→op map, which is statically known — so the device loop need only
    execute return events: for return ``r``, ``slot_ops[r]`` is the full
    pending map (including the returning op) and ``ret_slot[r]`` the slot
    being returned/freed. ``ret_slot = -1`` marks padding (identity).
    ``ret_event[r]`` / ``ret_entry[r]`` map back to the original event
    index / analysis entry for failure reporting.
    """
    ret_slot: np.ndarray    # i32[R]
    slot_ops: np.ndarray    # i32[R, W]
    ret_event: np.ndarray   # i32[R]
    ret_entry: np.ndarray   # i32[R]
    W: int
    n_returns: int

    @property
    def R(self) -> int:
        return len(self.ret_slot)


def returns_view(stream: EventStream) -> ReturnStream:
    """Project an event stream to its return events with per-return
    pending-op snapshots (C++ scan when available, Python fallback)."""
    from jepsen_tpu.checkers import preproc_native

    W = max(stream.W, 1)
    native = preproc_native.returns_view(
        stream.kind, stream.slot, stream.opid, stream.entry, W,
        stream.n_events)
    if native is not None:
        ret_slot, slot_ops, ret_event, ret_entry, R = native
        return ReturnStream(ret_slot=ret_slot, slot_ops=slot_ops,
                            ret_event=ret_event, ret_entry=ret_entry,
                            W=W, n_returns=R)
    n_ret = int(np.sum(stream.kind[:stream.n_events] == KIND_RETURN))
    ret_slot = np.full(n_ret, -1, np.int32)
    slot_ops = np.full((n_ret, W), -1, np.int32)
    ret_event = np.zeros(n_ret, np.int32)
    ret_entry = np.zeros(n_ret, np.int32)
    cur = np.full(W, -1, np.int32)
    r = 0
    for e in range(stream.n_events):
        k = stream.kind[e]
        if k == KIND_INVOKE:
            cur[stream.slot[e]] = stream.opid[e]
        elif k == KIND_RETURN:
            s = stream.slot[e]
            slot_ops[r] = cur
            ret_slot[r] = s
            ret_event[r] = e
            ret_entry[r] = stream.entry[e]
            cur[s] = -1
            r += 1
    return ReturnStream(ret_slot=ret_slot, slot_ops=slot_ops,
                        ret_event=ret_event, ret_entry=ret_entry,
                        W=W, n_returns=n_ret)


def pad_returns(rs: ReturnStream, R: int, W: Optional[int] = None
                ) -> ReturnStream:
    """Pad to ``R`` returns (identity rows) / widen to ``W`` slots.
    Direct allocation, not ``np.pad`` — per-key batch preps call this
    thousands of times and np.pad's Python plumbing was ~0.4 s of a
    4096-key check.

    When no padding or widening is needed the INPUT stream is returned
    as-is (aliased arrays): treat the result as read-only."""
    W = rs.W if W is None else W
    if W < rs.W or R < rs.n_returns:
        raise ValueError("cannot shrink a return stream")
    R0, W0 = rs.R, rs.slot_ops.shape[1]
    if R == R0 and W == W0:
        return rs
    slot_ops = np.full((R, W), -1, rs.slot_ops.dtype)
    slot_ops[:R0, :W0] = rs.slot_ops
    ret_slot = np.full(R, -1, rs.ret_slot.dtype)
    ret_slot[:R0] = rs.ret_slot
    ret_event = np.zeros(R, rs.ret_event.dtype)
    ret_event[:R0] = rs.ret_event
    ret_entry = np.zeros(R, rs.ret_entry.dtype)
    ret_entry[:R0] = rs.ret_entry
    return ReturnStream(
        ret_slot=ret_slot, slot_ops=slot_ops, ret_event=ret_event,
        ret_entry=ret_entry, W=W, n_returns=rs.n_returns)
