"""Sequential consistency models — upstream: ``knossos/src/knossos/model.clj``
(SURVEY.md §2.2): pure specifications ``step(model, op) -> model' |
Inconsistent``. Models are immutable, hashable values so the memo layer
(:mod:`jepsen_tpu.models.memo`) can enumerate reachable states and int-code
transitions for the TPU solver.

Provided models match the upstream set: :class:`Register`,
:class:`CASRegister`, :class:`Mutex`, :class:`MultiRegister`,
:class:`SetModel`, :class:`FIFOQueue`, :class:`UnorderedQueue`,
:class:`NoOp`.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, FrozenSet, Optional, Tuple, Union

from jepsen_tpu.op import Op


@dataclass(frozen=True, slots=True)
class Inconsistent:
    """Returned by ``step`` when the op is illegal in this state (upstream
    ``knossos.model/inconsistent``)."""
    msg: str

    def __bool__(self) -> bool:
        return False


StepResult = Union["Model", Inconsistent]


class Model:
    """Base sequential specification (upstream ``knossos.model/Model``)."""

    def step(self, op: Op) -> StepResult:
        raise NotImplementedError

    # models are frozen dataclasses in subclasses; hashable by construction.


def inconsistent(msg: str) -> Inconsistent:
    return Inconsistent(msg)


def is_inconsistent(x: Any) -> bool:
    return isinstance(x, Inconsistent)


def _as_tuple2(value: Any) -> Tuple[Any, Any]:
    if isinstance(value, (list, tuple)) and len(value) == 2:
        return value[0], value[1]
    raise ValueError(f"expected [old new] pair, got {value!r}")


@dataclass(frozen=True, slots=True)
class Register(Model):
    """A read/write register (upstream ``knossos.model/register``).

    ``read`` with value ``None`` matches any state (an unobserved read);
    otherwise the read value must equal the state. ``write v`` sets state.
    """
    value: Any = None

    def step(self, op: Op) -> StepResult:
        if op.f == "write":
            return Register(op.value)
        if op.f == "read":
            if op.value is None or op.value == self.value:
                return self
            return inconsistent(f"read {op.value!r}, expected {self.value!r}")
        return inconsistent(f"register cannot {op.f}")


@dataclass(frozen=True, slots=True)
class CASRegister(Model):
    """Compare-and-set register (upstream ``knossos.model/cas-register``):
    ``read`` / ``write v`` / ``cas [old new]``."""
    value: Any = None

    def step(self, op: Op) -> StepResult:
        if op.f == "write":
            return CASRegister(op.value)
        if op.f == "cas":
            old, new = _as_tuple2(op.value)
            if self.value == old:
                return CASRegister(new)
            return inconsistent(f"cas {old!r}->{new!r} from {self.value!r}")
        if op.f == "read":
            if op.value is None or op.value == self.value:
                return self
            return inconsistent(f"read {op.value!r}, expected {self.value!r}")
        return inconsistent(f"cas-register cannot {op.f}")


@dataclass(frozen=True, slots=True)
class Mutex(Model):
    """A lock (upstream ``knossos.model/mutex``): ``acquire`` / ``release``."""
    locked: bool = False

    def step(self, op: Op) -> StepResult:
        if op.f in ("acquire", "lock"):
            if self.locked:
                return inconsistent("cannot acquire a held lock")
            return Mutex(True)
        if op.f in ("release", "unlock"):
            if not self.locked:
                return inconsistent("cannot release a free lock")
            return Mutex(False)
        return inconsistent(f"mutex cannot {op.f}")


@dataclass(frozen=True, slots=True)
class MultiRegister(Model):
    """A map of independent registers (upstream
    ``knossos.model/multi-register``). Op values are ``{key: v}`` maps (or
    ``[[k v] ...]`` pairs): ``read`` asserts every given key's value,
    ``write`` sets every given key."""
    registers: Tuple[Tuple[Any, Any], ...] = ()

    def _as_dict(self) -> Dict[Any, Any]:
        return dict(self.registers)

    def step(self, op: Op) -> StepResult:
        kvs = op.value
        if isinstance(kvs, dict):
            items = list(kvs.items())
        elif isinstance(kvs, (list, tuple)):
            items = [tuple(p) for p in kvs]
        else:
            return inconsistent(f"bad multi-register value {kvs!r}")
        regs = self._as_dict()
        if op.f == "write":
            for k, v in items:
                regs[k] = v
            return MultiRegister(tuple(sorted(regs.items(), key=repr)))
        if op.f == "read":
            for k, v in items:
                if v is not None and regs.get(k) != v:
                    return inconsistent(
                        f"read {v!r} at {k!r}, expected {regs.get(k)!r}")
            return self
        return inconsistent(f"multi-register cannot {op.f}")


@dataclass(frozen=True, slots=True)
class SetModel(Model):
    """A grow-only set (upstream ``knossos.model/set``): ``add v`` /
    ``read`` (value = full set contents)."""
    elements: FrozenSet[Any] = frozenset()

    def step(self, op: Op) -> StepResult:
        if op.f == "add":
            return SetModel(self.elements | {op.value})
        if op.f == "read":
            if op.value is None:
                return self
            got = frozenset(op.value)
            if got == self.elements:
                return self
            return inconsistent(f"read {sorted(map(repr, got))}, expected "
                                f"{sorted(map(repr, self.elements))}")
        return inconsistent(f"set cannot {op.f}")


@dataclass(frozen=True, slots=True)
class FIFOQueue(Model):
    """FIFO queue (upstream ``knossos.model/fifo-queue``): ``enqueue v`` /
    ``dequeue`` (value = dequeued element)."""
    items: Tuple[Any, ...] = ()

    def step(self, op: Op) -> StepResult:
        if op.f == "enqueue":
            return FIFOQueue(self.items + (op.value,))
        if op.f == "dequeue":
            if not self.items:
                return inconsistent("dequeue from empty queue")
            if op.value is not None and self.items[0] != op.value:
                return inconsistent(
                    f"dequeued {op.value!r}, expected {self.items[0]!r}")
            return FIFOQueue(self.items[1:])
        return inconsistent(f"fifo-queue cannot {op.f}")


@dataclass(frozen=True, slots=True)
class UnorderedQueue(Model):
    """Bag/unordered queue (upstream ``knossos.model/unordered-queue``)."""
    items: FrozenSet[Tuple[Any, int]] = frozenset()

    def step(self, op: Op) -> StepResult:
        counts = dict(self.items)
        if op.f == "enqueue":
            counts[op.value] = counts.get(op.value, 0) + 1
            return UnorderedQueue(frozenset(counts.items()))
        if op.f == "dequeue":
            if op.value not in counts or counts[op.value] <= 0:
                return inconsistent(f"dequeued absent {op.value!r}")
            counts[op.value] -= 1
            if counts[op.value] == 0:
                del counts[op.value]
            return UnorderedQueue(frozenset(counts.items()))
        return inconsistent(f"unordered-queue cannot {op.f}")


@dataclass(frozen=True, slots=True)
class NoOp(Model):
    """Accepts every op (upstream ``knossos.model/noop``)."""

    def step(self, op: Op) -> StepResult:
        return self


# canonical constructors, knossos-style lowercase names
def register(value: Any = None) -> Register:
    return Register(value)


def cas_register(value: Any = None) -> CASRegister:
    return CASRegister(value)


def mutex() -> Mutex:
    return Mutex(False)


def multi_register(values: Optional[Dict[Any, Any]] = None) -> MultiRegister:
    return MultiRegister(tuple(sorted((values or {}).items(), key=repr)))


def set_model() -> SetModel:
    return SetModel()


def fifo_queue() -> FIFOQueue:
    return FIFOQueue()


def unordered_queue() -> UnorderedQueue:
    return UnorderedQueue()


def noop_model() -> NoOp:
    return NoOp()


def bounded_set(universe: int = 12) -> "Model":
    """Int-coded bounded set (state = one bitmask int, <= 2**universe
    reachable states) — the memo-friendly set model that lets set
    workloads reach the dense-walk device engines. Lazy import: the
    class lives in :mod:`jepsen_tpu.models.memo` beside the memoizer
    it exists for."""
    from jepsen_tpu.models.memo import BoundedSetModel
    return BoundedSetModel(0, universe)


def bounded_queue(universe: int = 6) -> "Model":
    """Int-coded bounded FIFO queue (state = one base-(universe+1)
    int; the arrangements of distinct pending values — 1957 states at
    the default) — the memo-friendly :class:`FIFOQueue` that lets
    queue workloads reach the dense-walk device engines."""
    from jepsen_tpu.models.memo import BoundedQueueModel
    return BoundedQueueModel(0, universe)


def bounded_map(keys: int = 4, vals: int = 4) -> "Model":
    """Int-coded bounded register map (state = one base-(vals+1) int,
    <= (vals+1)**keys reachable states) — the memo-friendly
    :class:`MultiRegister`."""
    from jepsen_tpu.models.memo import BoundedMapModel
    return BoundedMapModel(0, keys, vals)
