"""Model memoization — upstream: ``knossos/src/knossos/model/memo.clj``
(SURVEY.md §2.2): for a given history, precompute the reachable
(state × distinct-op) transition table so that states become small ints and
the search becomes pure table lookups. This table *is* the TPU kernel: the
device search never steps a Python model, it gathers ``T[state, op_id]``.

``memo(model, packed)`` BFS-enumerates states reachable from ``model`` under
the history's distinct op alphabet and returns a :class:`Memo` with:

- ``table`` — int32 ``[n_states, n_ops]``; ``-1`` marks an inconsistent
  (illegal) transition.
- ``states`` — state id → model object, for reporting.
- ``entry_op`` — convenience alias of ``packed.op_id``.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Sequence, Tuple

import numpy as np

from jepsen_tpu.history import PackedHistory
from jepsen_tpu.models import Model, StepResult, inconsistent, \
    is_inconsistent
from jepsen_tpu.op import Op


class StateExplosion(RuntimeError):
    """Raised when the reachable state space exceeds ``max_states`` — the
    caller should fall back to an un-memoized (object-stepping) search."""


@dataclass(frozen=True, slots=True)
class BoundedSetModel(Model):
    """Int-coded grow-only set over a BOUNDED element universe
    ``{0..universe-1}`` (ROADMAP item 3(a) opening move): state is one
    bitmask int, so the reachable space is at most ``2**universe`` and
    the memo BFS — hence the dense-walk device engines — admits set
    workloads that :class:`~jepsen_tpu.models.SetModel` (frozenset
    state, unbounded alphabet) would push to host checking.

    ``add v`` (0 <= v < universe) sets bit ``v``; ``read`` with value
    ``None`` matches any state, otherwise the observed collection must
    equal the current contents exactly. Differentially equivalent to
    ``SetModel`` on in-universe histories (tests/test_models.py)."""
    mask: int = 0
    universe: int = 12

    def step(self, op: Op) -> StepResult:
        if op.f == "add":
            v = op.value
            if not isinstance(v, int) or not 0 <= v < self.universe:
                return inconsistent(
                    f"add {v!r} outside universe 0..{self.universe - 1}")
            return BoundedSetModel(self.mask | (1 << v), self.universe)
        if op.f == "read":
            if op.value is None:
                return self
            try:
                got = frozenset(int(x) for x in op.value)
            except (TypeError, ValueError):
                return inconsistent(f"unreadable set value {op.value!r}")
            here = frozenset(i for i in range(self.universe)
                             if self.mask >> i & 1)
            if got == here:
                return self
            return inconsistent(f"read {sorted(got)}, expected "
                                f"{sorted(here)}")
        return inconsistent(f"bounded-set cannot {op.f}")


@dataclass(frozen=True, slots=True)
class BoundedQueueModel(Model):
    """Int-coded FIFO queue over a bounded unique-value universe
    ``{0..universe-1}`` (the :class:`BoundedSetModel` trick applied to
    :class:`~jepsen_tpu.models.FIFOQueue`): the pending items are one
    base-``(universe+1)`` int (little-endian, head at the lowest
    digit, digit ``v+1`` = value ``v``), so the reachable space is
    the arrangements of distinct values — 1957 states at the default
    ``universe=6`` — and queue workloads reach the memoized dense
    ``reach`` engine instead of host-only checking.

    Enqueueing a value that is already PENDING is inconsistent (the
    unique-value workloads never produce one; this is what keeps the
    state space to arrangements). Dequeue matches
    :class:`~jepsen_tpu.models.FIFOQueue` exactly: empty-queue
    dequeue is inconsistent, a ``None`` value pops unchecked.
    Differentially equivalent to ``FIFOQueue`` on in-universe
    unique-enqueue histories (tests/test_models.py)."""
    code: int = 0
    universe: int = 6

    def _items(self) -> List[int]:
        base, c, out = self.universe + 1, self.code, []
        while c:
            out.append(c % base - 1)
            c //= base
        return out                              # head first

    def step(self, op: Op) -> StepResult:
        base = self.universe + 1
        if op.f == "enqueue":
            v = op.value
            if not isinstance(v, int) or not 0 <= v < self.universe:
                return inconsistent(
                    f"enqueue {v!r} outside universe "
                    f"0..{self.universe - 1}")
            items = self._items()
            if v in items:
                return inconsistent(f"enqueue of pending value {v!r}")
            return BoundedQueueModel(
                self.code + (v + 1) * base ** len(items),
                self.universe)
        if op.f == "dequeue":
            if not self.code:
                return inconsistent("dequeue from empty queue")
            head = self.code % base - 1
            if op.value is not None and head != op.value:
                return inconsistent(
                    f"dequeued {op.value!r}, expected {head!r}")
            return BoundedQueueModel(self.code // base, self.universe)
        return inconsistent(f"bounded-queue cannot {op.f}")


@dataclass(frozen=True, slots=True)
class BoundedMapModel(Model):
    """Int-coded register map over bounded key/value universes: keys
    ``{0..keys-1}``, values ``{0..vals-1}``, state one base-
    ``(vals+1)`` int (digit ``k`` is ``v+1``, 0 = unset) — at most
    ``(vals+1)**keys`` reachable states (625 at the defaults), the
    memo-friendly :class:`~jepsen_tpu.models.MultiRegister`. Op
    values follow multi-register: ``{key: v}`` maps or ``[[k v]...]``
    pairs; ``read`` skips ``None``-valued keys and asserts the rest
    (an unset key reads as ``None``)."""
    code: int = 0
    keys: int = 4
    vals: int = 4

    def _pairs(self, op: Op):
        kvs = op.value
        if isinstance(kvs, dict):
            return list(kvs.items())
        if isinstance(kvs, (list, tuple)):
            return [tuple(p) for p in kvs]
        return None

    def step(self, op: Op) -> StepResult:
        items = self._pairs(op)
        if items is None:
            return inconsistent(f"bad bounded-map value {op.value!r}")
        base = self.vals + 1
        if op.f == "write":
            code = self.code
            for k, v in items:
                if not isinstance(k, int) or not 0 <= k < self.keys:
                    return inconsistent(
                        f"write key {k!r} outside 0..{self.keys - 1}")
                if not isinstance(v, int) or not 0 <= v < self.vals:
                    return inconsistent(
                        f"write {v!r} outside 0..{self.vals - 1}")
                digit = code // base ** k % base
                code += (v + 1 - digit) * base ** k
            return BoundedMapModel(code, self.keys, self.vals)
        if op.f == "read":
            for k, v in items:
                if v is None:
                    continue
                if not isinstance(k, int) or not 0 <= k < self.keys:
                    return inconsistent(
                        f"read key {k!r} outside 0..{self.keys - 1}")
                digit = self.code // base ** k % base
                here = digit - 1 if digit else None
                if v != here:
                    return inconsistent(
                        f"read {v!r} at {k!r}, expected {here!r}")
            return self
        return inconsistent(f"bounded-map cannot {op.f}")


@dataclass(frozen=True)
class Memo:
    table: np.ndarray            # i32[n_states, n_ops]; -1 = inconsistent
    states: Tuple[Model, ...]    # state id -> model
    distinct_ops: Tuple[Op, ...]
    initial: int = 0

    @property
    def n_states(self) -> int:
        return len(self.states)

    @property
    def n_ops(self) -> int:
        return len(self.distinct_ops)


def memo(model: Model, packed: PackedHistory,
         max_states: int = 1_000_000) -> Memo:
    """Enumerate reachable states of ``model`` under ``packed.distinct_ops``
    and build the dense transition table."""
    return memo_ops(model, packed.distinct_ops, max_states=max_states)


def memo_ops(model: Model, distinct_ops: Sequence[Op],
             max_states: int = 1_000_000) -> Memo:
    ops = tuple(distinct_ops)
    state_ids: Dict[Model, int] = {model: 0}
    states: List[Model] = [model]
    rows: List[List[int]] = []
    frontier = [model]
    while frontier:
        next_frontier: List[Model] = []
        for s in frontier:
            row: List[int] = []
            for op in ops:
                s2 = s.step(op)
                if is_inconsistent(s2):
                    row.append(-1)
                    continue
                if s2 not in state_ids:
                    if len(states) >= max_states:
                        raise StateExplosion(
                            f"more than {max_states} reachable states for "
                            f"{type(model).__name__} over {len(ops)} ops")
                    state_ids[s2] = len(states)
                    states.append(s2)
                    next_frontier.append(s2)
                row.append(state_ids[s2])
            rows.append(row)
        frontier = next_frontier
    table = np.asarray(rows, np.int32).reshape(len(states), len(ops))
    return Memo(table=table, states=tuple(states), distinct_ops=ops)
