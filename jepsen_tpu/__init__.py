"""jepsen_tpu — a TPU-native distributed-systems safety-testing framework.

A ground-up rebuild of the capabilities of Jepsen (reference:
``daschl/jepsen``, a fork of ``jepsen-io/jepsen``; see SURVEY.md) designed
TPU-first: operation histories are structure-of-arrays int tensors, sequential
consistency models are int-coded transition tables, and the
Wing-Gong-Lowe linearizability search is a batched, vmapped, device-shardable
JAX frontier search instead of a single-threaded JVM DFS.

Layer map (mirrors SURVEY.md §1):

- :mod:`jepsen_tpu.op`, :mod:`jepsen_tpu.history` — L5 history & ops
  (upstream: ``knossos.op``, ``knossos.history``, op maps in ``jepsen.core``).
- :mod:`jepsen_tpu.models` — sequential specifications
  (upstream: ``knossos.model``, ``knossos.model.memo``).
- :mod:`jepsen_tpu.checkers` — L7 analysis, including the TPU WGL solver
  (upstream: ``jepsen.checker``, ``knossos.wgl``, ``knossos.linear``,
  ``knossos.competition``).
- :mod:`jepsen_tpu.generators` — L3 workload generation
  (upstream: ``jepsen.generator``).
- :mod:`jepsen_tpu.client`, :mod:`jepsen_tpu.nemesis`, :mod:`jepsen_tpu.net`,
  :mod:`jepsen_tpu.control`, :mod:`jepsen_tpu.db` — L0-L4
  (upstream: ``jepsen.client``, ``jepsen.nemesis``, ``jepsen.net``,
  ``jepsen.control``, ``jepsen.db``).
- :mod:`jepsen_tpu.core` — L6 test runtime (upstream: ``jepsen.core``).
- :mod:`jepsen_tpu.store`, :mod:`jepsen_tpu.web`, :mod:`jepsen_tpu.cli` —
  L9/L10 persistence, reporting, CLI (upstream: ``jepsen.store``,
  ``jepsen.web``, ``jepsen.cli``).
- :mod:`jepsen_tpu.parallel` — device-mesh sharding of the checker search
  (no upstream analogue; the reference is single-JVM).
"""

__version__ = "0.1.0"

from jepsen_tpu.op import Op, invoke, ok, fail, info  # noqa: F401
