"""Minimal EDN reader/writer for Jepsen interop.

Upstream Jepsen persists histories and results as EDN (``history.edn``,
``results.edn`` via ``jepsen.store``; knossos ships recorded test histories
as EDN under ``data/`` — SURVEY.md §2.2, §4). This is a small, dependency-free
subset parser sufficient for those files: maps, vectors, lists, sets,
keywords, symbols, strings, numbers, nil/true/false, and ``#tag`` forms
(tags are dropped, the tagged value kept).

Keywords parse to plain strings without the colon (``:invoke`` → ``"invoke"``)
— matching this framework's string-typed ops. ``dumps`` writes the keys that
Jepsen expects as keywords (``:process :type :f :value :time :index``) back
as keywords so round-trips stay Jepsen-readable.
"""
from __future__ import annotations

import re
from typing import Any, List, Tuple

from jepsen_tpu.util import hashable

_WS = set(" \t\n\r,")
_DELIM = set("()[]{}\"") | _WS
# strings that may be safely written as EDN keywords (:name tokens)
_KEYWORD_RE = re.compile(r"^[A-Za-z*+!_?<>=.-][A-Za-z0-9*+!_?<>=.#:/-]*$")
_KEYWORD_KEYS = {"process", "type", "f", "value", "time", "index", "valid?",
                 "read", "write", "cas", "invoke", "ok", "fail", "info",
                 "nemesis", "acquire", "release", "add", "lock", "unlock",
                 "enqueue", "dequeue", "start", "stop", "txn",
                 # list-append micro-op kinds (Elle's [:append k v] /
                 # [:r k vs] vectors round-trip as keywords)
                 "append", "r"}


class Keyword(str):
    """A parsed keyword; subclass of str so it compares equal to the bare
    name (``Keyword("read") == "read"``)."""
    __slots__ = ()


class Symbol(str):
    __slots__ = ()


def loads(text: str) -> Any:
    vals = loads_all(text)
    if len(vals) != 1:
        raise ValueError(f"expected one EDN form, got {len(vals)}")
    return vals[0]


def loads_all(text: str) -> List[Any]:
    vals: List[Any] = []
    i = 0
    n = len(text)
    while True:
        i = _skip_discards(text, i)
        if i >= n:
            return vals
        v, i = _read(text, i)
        vals.append(v)


def _skip_discards(s: str, i: int) -> int:
    """Skip whitespace and any ``#_form`` discard forms."""
    while True:
        i = _skip_ws(s, i)
        if s.startswith("#_", i):
            j = _skip_ws(s, i + 2)
            if j >= len(s):
                raise ValueError("#_ discard with nothing to discard")
            _, i = _read(s, j)
        else:
            return i


def _skip_ws(s: str, i: int) -> int:
    n = len(s)
    while i < n:
        c = s[i]
        if c in _WS:
            i += 1
        elif c == ";":  # comment to EOL
            while i < n and s[i] != "\n":
                i += 1
        else:
            break
    return i


def _read(s: str, i: int) -> Tuple[Any, int]:
    c = s[i]
    if c == "{":
        return _read_map(s, i + 1)
    if c == "[":
        return _read_seq(s, i + 1, "]")
    if c == "(":
        return _read_seq(s, i + 1, ")")
    if c == '"':
        return _read_string(s, i + 1)
    if c == "#":
        if i + 1 < len(s) and s[i + 1] == "{":
            vals, j = _read_seq(s, i + 2, "}")
            return set(hashable(v) for v in vals), j
        if s.startswith("#_", i):  # discard form, then read the next value
            return _read(s, _skip_discards(s, i))
        # tagged literal: read tag symbol then value; keep value
        j = i + 1
        while j < len(s) and s[j] not in _DELIM:
            j += 1
        return _read(s, _skip_ws(s, j))
    if c == ":":
        j = i + 1
        while j < len(s) and s[j] not in _DELIM:
            j += 1
        return Keyword(s[i + 1:j]), j
    if c == "\\":  # character literal
        j = i + 1
        while j < len(s) and s[j] not in _DELIM:
            j += 1
        name = s[i + 1:j]
        chars = {"newline": "\n", "space": " ", "tab": "\t", "return": "\r"}
        return chars.get(name, name[:1]), j
    # token: number, nil, true, false, symbol
    j = i
    while j < len(s) and s[j] not in _DELIM:
        j += 1
    tok = s[i:j]
    return _token(tok), j


def _token(tok: str) -> Any:
    if tok == "nil":
        return None
    if tok == "true":
        return True
    if tok == "false":
        return False
    try:
        return int(tok)
    except ValueError:
        pass
    try:
        return float(tok.rstrip("M"))
    except ValueError:
        pass
    if tok.endswith("N"):
        try:
            return int(tok[:-1])
        except ValueError:
            pass
    return Symbol(tok)


def _read_string(s: str, i: int) -> Tuple[str, int]:
    out: List[str] = []
    while i < len(s):
        c = s[i]
        if c == '"':
            return "".join(out), i + 1
        if c == "\\":
            i += 1
            esc = s[i]
            out.append({"n": "\n", "t": "\t", "r": "\r", '"': '"',
                        "\\": "\\"}.get(esc, esc))
        else:
            out.append(c)
        i += 1
    raise ValueError("unterminated string")


def _read_seq(s: str, i: int, close: str) -> Tuple[List[Any], int]:
    out: List[Any] = []
    while True:
        i = _skip_discards(s, i)
        if i >= len(s):
            raise ValueError(f"unterminated sequence, expected {close}")
        if s[i] == close:
            return out, i + 1
        v, i = _read(s, i)
        out.append(v)


def _read_map(s: str, i: int) -> Tuple[dict, int]:
    vals, i = _read_seq(s, i, "}")
    if len(vals) % 2:
        raise ValueError("map literal with odd number of forms")
    return {hashable(vals[k]): vals[k + 1] for k in range(0, len(vals), 2)}, i


def to_plain(v: Any) -> Any:
    """Deep-convert parsed EDN to plain Python: keywords/symbols → str,
    vectors → lists. Composite map keys (vectors/maps, stored hashably as
    tuples) stay tuples so the result remains a legal dict."""
    if isinstance(v, (Keyword, Symbol)):
        return str(v)
    if isinstance(v, dict):
        return {_plain_key(k): to_plain(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [to_plain(x) for x in v]
    if isinstance(v, (set, frozenset)):
        return {_plain_key(x) for x in v}
    return v


def _plain_key(k: Any) -> Any:
    """Like :func:`to_plain` but keeps the result hashable (tuples stay
    tuples) so it can serve as a dict key or set element."""
    if isinstance(k, (Keyword, Symbol)):
        return str(k)
    if isinstance(k, (tuple, frozenset)):
        return type(k)(_plain_key(x) for x in k)
    return k


def dumps(v: Any) -> str:
    out: List[str] = []
    _emit(v, out, keyword_context=False)
    return "".join(out)


def _emit(v: Any, out: List[str], keyword_context: bool) -> None:
    if v is None:
        out.append("nil")
    elif v is True:
        out.append("true")
    elif v is False:
        out.append("false")
    elif isinstance(v, Keyword):
        out.append(":" + v)
    elif isinstance(v, str):
        if keyword_context and v in _KEYWORD_KEYS and " " not in v:
            out.append(":" + v)
        else:
            out.append('"' + v.replace("\\", "\\\\").replace('"', '\\"') + '"')
    elif isinstance(v, (int, float)):
        out.append(repr(v))
    elif isinstance(v, dict):
        out.append("{")
        first = True
        for k, x in v.items():
            if not first:
                out.append(", ")
            first = False
            key = (Keyword(k) if isinstance(k, str) and not
                   isinstance(k, (Keyword, Symbol)) and _KEYWORD_RE.match(k)
                   else k)
            _emit(key, out, False)
            out.append(" ")
            _emit(x, out, keyword_context=True)
        out.append("}")
    elif isinstance(v, (list, tuple)):
        out.append("[")
        for j, x in enumerate(v):
            if j:
                out.append(" ")
            _emit(x, out, keyword_context)
        out.append("]")
    elif isinstance(v, (set, frozenset)):
        out.append("#{")
        for j, x in enumerate(sorted(v, key=repr)):
            if j:
                out.append(" ")
            _emit(x, out, keyword_context)
        out.append("}")
    else:
        _emit(str(v), out, keyword_context)
