"""jtlint — the AST-driven invariant analyzer (docs/ANALYSIS.md).

Turns the repo's hand-enforced disciplines into static CI gates:
donation aliasing (the PR-10 reuse-after-donation bug class), silent
``except`` fallbacks, the ``JEPSEN_TPU_*`` gate registry + doc
cross-check, obs counter/doc drift, and declared lock discipline.

Pure stdlib ``ast`` — importing this package never imports jax, so
``python -m jepsen_tpu.analysis --strict`` runs anywhere in seconds.

Entry points::

    python -m jepsen_tpu.analysis [--strict] [...]
    python tools/lint.py [--strict] [...]

Programmatic::

    from jepsen_tpu.analysis import run_lint
    report = run_lint("/path/to/repo")
    assert not report["live"]
"""
from jepsen_tpu.analysis.core import (Finding, Module, PASS_IDS,  # noqa: F401
                                      Tree, load_baseline, main,
                                      run_lint, run_passes,
                                      save_baseline, triage)
