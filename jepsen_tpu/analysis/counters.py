"""jtlint pass ``counter-drift``: the obs counter/gauge/histogram
namespace versus the OBSERVABILITY.md taxonomy table, both
directions.

Code side — collected with pure ``ast`` from ``jepsen_tpu/``:

- ``obs.count("…")`` / ``obs.gauge`` / ``obs.histogram`` call sites
  with a literal first argument;
- f-string names become *prefix patterns*: dynamic pieces turn into
  ``*`` segments (``f"engine.fallback.{stage}.{cause}"`` ->
  ``engine.fallback.*.*``), matching the doc rows' ``<stage>``
  placeholders;
- inside :mod:`jepsen_tpu.obs` itself, the bare ``count(…)`` helpers
  and the registry-internal ``self.counters["…"]`` stores (the
  ``obs.dropped.*`` bookkeeping) are collected too.

Doc side — every backticked name in the first column of
OBSERVABILITY.md table rows, with ``{a,b}`` alternation expanded and
``<placeholder>`` mapped to ``*``.

A code name with no matching row is an undocumented metric; a row no
code emits is doc rot. Dynamic (non-literal, non-f-string) names are
skipped — a documented limitation, not a silent pass: they are
counted and reported by ``--json`` consumers via the pass module's
:func:`collect_code_names`.
"""
from __future__ import annotations

import ast
import fnmatch
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from jepsen_tpu.analysis.core import Finding, Tree

PASS_ID = "counter-drift"

_DOC_REL = "docs/OBSERVABILITY.md"
_OBS_FNS = {"count", "gauge", "histogram", "observe"}
_NAME_OK = re.compile(r"[A-Za-z0-9_.*:<>-]+\Z")


def _pattern_of_arg(arg: ast.AST) -> Optional[str]:
    """Literal -> exact name; JoinedStr -> pattern with '*' dynamic
    segments; ``"prefix." + expr`` -> ``prefix.*``; anything else ->
    None (dynamic, skipped)."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    if isinstance(arg, ast.JoinedStr):
        parts: List[str] = []
        for v in arg.values:
            if isinstance(v, ast.Constant):
                parts.append(str(v.value))
            else:
                parts.append("*")
        return "".join(parts)
    if isinstance(arg, ast.BinOp) and isinstance(arg.op, ast.Add):
        left = _pattern_of_arg(arg.left)
        if left is not None:
            return left.rstrip("*") + "*"
    return None


def _helper_patterns(mod_tree: ast.Module) -> Dict[str, str]:
    """Module functions whose every return is a resolvable name
    expression — ``obs.count(_counter_name(x))`` then collects the
    helper's pattern (one level; the ``serve.fault.<name>`` idiom)."""
    out: Dict[str, str] = {}
    for node in ast.walk(mod_tree):
        if not isinstance(node, ast.FunctionDef):
            continue
        pats: List[str] = []
        ok = True
        for n in ast.walk(node):
            if isinstance(n, ast.Return) and n.value is not None:
                p = _pattern_of_arg(n.value)
                if p is None:
                    ok = False
                    break
                pats.append(p)
        if ok and len(set(pats)) == 1:
            out[node.name] = pats[0]
    return out


def collect_code_names(tree: Tree) -> Tuple[
        Dict[str, List[Tuple[str, int]]], List[Tuple[str, int]]]:
    """(pattern -> sites, dynamic-call sites). Scans jepsen_tpu/."""
    names: Dict[str, List[Tuple[str, int]]] = {}
    dynamic: List[Tuple[str, int]] = []
    for mod in tree.modules:
        if mod.tree is None \
                or not mod.rel.startswith("jepsen_tpu/"):
            continue
        in_obs = mod.rel.startswith("jepsen_tpu/obs/")
        helpers = _helper_patterns(mod.tree)
        for node in ast.walk(mod.tree):
            arg: Optional[ast.AST] = None
            site = None
            if isinstance(node, ast.Call):
                f = node.func
                is_obs_attr = (isinstance(f, ast.Attribute)
                               and f.attr in _OBS_FNS
                               and isinstance(f.value, ast.Name)
                               and f.value.id == "obs")
                is_bare = (in_obs and isinstance(f, ast.Name)
                           and f.id in _OBS_FNS)
                if (is_obs_attr or is_bare) and node.args:
                    arg = node.args[0]
                    site = (mod.rel, node.lineno)
            elif in_obs and isinstance(node, ast.Subscript) \
                    and isinstance(node.value, ast.Attribute) \
                    and node.value.attr in ("counters", "gauges"):
                # registry-internal bookkeeping, e.g.
                # self.counters["obs.dropped.spans"]
                arg = node.slice
                site = (mod.rel, node.lineno)
            if arg is None or site is None:
                continue
            pat = _pattern_of_arg(arg)
            if pat is None and isinstance(arg, ast.Call):
                f = arg.func
                hn = f.id if isinstance(f, ast.Name) else (
                    f.attr if isinstance(f, ast.Attribute) else None)
                pat = helpers.get(hn) if hn else None
            if pat is None:
                dynamic.append(site)
            elif "." in pat:        # namespaced metrics only
                names.setdefault(pat, []).append(site)
    for sites in names.values():
        sites.sort()
    return names, dynamic


# -- doc table parsing ---------------------------------------------------

_BACKTICK = re.compile(r"`([^`]+)`")
_BRACE = re.compile(r"\{([^{}]*)\}")


def _expand_braces(name: str) -> List[str]:
    m = _BRACE.search(name)
    if not m:
        return [name]
    out: List[str] = []
    for alt in m.group(1).split(","):
        out.extend(_expand_braces(
            name[:m.start()] + alt.strip() + name[m.end():]))
    return out


def _normalize(name: str) -> Optional[str]:
    """Doc token -> match pattern: ``<placeholder>`` becomes ``*``.
    None for tokens that are not metric names (prose code spans)."""
    n = re.sub(r"<[^<>]*>", "*", name.strip())
    if "." not in n or "=" in n or "(" in n or " " in n:
        return None
    if not _NAME_OK.match(n):
        return None
    return n


def collect_doc_rows(tree: Tree) -> Dict[str, List[Tuple[str, int]]]:
    """pattern -> [(doc file, line)] from the OBSERVABILITY.md
    taxonomy table rows (first column, backticked names)."""
    text = tree.docs.get(_DOC_REL, "")
    rows: Dict[str, List[Tuple[str, int]]] = {}
    for i, line in enumerate(text.splitlines(), 1):
        s = line.strip()
        if not s.startswith("|"):
            continue
        cells = s.split("|")
        if len(cells) < 3:
            continue
        first = cells[1]
        if set(first.strip()) <= {"-", " ", ":"}:
            continue                        # divider row
        if first.strip().lower() in ("name",):
            continue                        # header row
        for m in _BACKTICK.finditer(first):
            for ex in _expand_braces(m.group(1)):
                n = _normalize(ex)
                if n is not None:
                    rows.setdefault(n, []).append((_DOC_REL, i))
    return rows


# -- matching ------------------------------------------------------------

def _seg_match(a: str, b: str) -> bool:
    if a == "*" or b == "*":
        return True
    if "*" in a or "*" in b:
        return fnmatch.fnmatchcase(b, a) or fnmatch.fnmatchcase(a, b)
    return a == b


def patterns_match(code: str, doc: str) -> bool:
    ca, da = code.split("."), doc.split(".")
    if len(ca) != len(da):
        # a trailing wildcard absorbs extra segments (dynamic pieces
        # may themselves contain dots, e.g. tenant names)
        if da and da[-1] == "*" and len(ca) > len(da):
            ca = ca[:len(da) - 1] + ["*"]
        elif ca and ca[-1] == "*" and len(da) > len(ca):
            da = da[:len(ca) - 1] + ["*"]
        else:
            return False
    return all(_seg_match(x, y) for x, y in zip(ca, da))


def run(tree: Tree) -> List[Finding]:
    findings: List[Finding] = []
    if _DOC_REL not in tree.docs:
        return findings
    code, _dynamic = collect_code_names(tree)
    rows = collect_doc_rows(tree)

    for pat, sites in sorted(code.items()):
        if not any(patterns_match(pat, d) for d in rows):
            f, line = sites[0]
            findings.append(Finding(
                PASS_ID, f, line,
                f"obs name '{pat}' has no {_DOC_REL} table row"))

    for doc, where in sorted(rows.items()):
        if not any(patterns_match(c, doc) for c in code):
            f, line = where[0]
            findings.append(Finding(
                PASS_ID, f, line,
                f"{_DOC_REL} row '{doc}' matches no obs call site"))
    return findings
