"""jtlint core: the shared machinery of the AST-driven invariant
analyzer — source-tree loading, findings, inline suppression, the
checked-in baseline, and the CLI.

The analyzer turns the repo's hand-enforced disciplines (ENGINE.md /
OBSERVABILITY.md / SERVING.md folklore plus per-site tests) into CI
gates, the same budget-file-plus-guard shape as
``tools/transfer_guard.py``:

- **Pure stdlib ``ast``** — no jax import anywhere on the lint path,
  so the CI job needs no accelerator stack and finishes in seconds.
- **Findings carry ``file:line`` + a pass id** and are suppressible
  inline (``# jtlint: ok <pass>`` on the finding line) or via the
  checked-in ``data/lint_baseline.json`` for accepted pre-existing
  cases — baseline adds require touching the checked-in file so they
  show up in review.
- **``--strict`` exits nonzero** on anything unsuppressed.

The five passes (each its own module, registered in :data:`PASSES`):

==================  =====================================================
``donation``        host-side reads of a ``jax.jit(...,
                    donate_argnums=...)`` operand after the dispatch —
                    the PR-10 reuse-after-donation bug class
                    (:mod:`jepsen_tpu.analysis.donation`)
``fallback``        ``except`` handlers in ``checkers/``/``serve/``/
                    ``txn/`` that suppress without an obs/ledger record
                    on every path (:mod:`jepsen_tpu.analysis.fallback`)
``env-gate``        every ``JEPSEN_TPU_*`` read collected into
                    ``data/env_gates.json`` and cross-checked against
                    the docs, both directions
                    (:mod:`jepsen_tpu.analysis.envgates`)
``counter-drift``   ``obs.count/gauge/histogram`` name literals vs the
                    OBSERVABILITY.md counter tables, both directions,
                    with prefix-pattern support for dynamic names
                    (:mod:`jepsen_tpu.analysis.counters`)
``lock-discipline`` attributes a class declares guarded
                    (``_GUARDED_BY``) touched outside ``with
                    self.<lock>`` (:mod:`jepsen_tpu.analysis.locks`)
==================  =====================================================

Extending: write a module with ``run(tree) -> List[Finding]``, add it
to :data:`PASSES`, document it in docs/ANALYSIS.md, and give
``tests/test_analysis.py`` a violating fixture + a clean twin.
"""
from __future__ import annotations

import argparse
import ast
import json
import os
import re
import sys
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

PASS_IDS = ("donation", "fallback", "env-gate", "counter-drift",
            "lock-discipline")

# # jtlint: ok            -- suppress every pass on this line
# # jtlint: ok donation   -- suppress one pass (comma-separate for more)
_SUPPRESS_RE = re.compile(r"#\s*jtlint:\s*ok\b([\w ,\-]*)")

_DEFAULT_BASELINE = os.path.join("data", "lint_baseline.json")
_DEFAULT_REGISTRY = os.path.join("data", "env_gates.json")

# directories whose .py files the analyzer loads (tests are NOT
# scanned: fixtures there deliberately violate the disciplines)
_CODE_DIRS = ("jepsen_tpu", "tools")
_CODE_FILES = ("bench.py",)
_DOC_FILES = ("README.md", "ROADMAP.md")
_DOC_DIRS = ("docs",)


@dataclass(frozen=True)
class Finding:
    """One violation: pass id + repo-relative file + line + message.
    The baseline keys on ``(pass, file, msg)`` — deliberately NOT the
    line, so unrelated edits shifting lines cannot churn it."""
    pass_id: str
    file: str
    line: int
    msg: str

    def key(self) -> Tuple[str, str, str]:
        return (self.pass_id, self.file, self.msg)

    def render(self) -> str:
        return f"{self.file}:{self.line}: [{self.pass_id}] {self.msg}"

    def to_json(self) -> Dict[str, Any]:
        return {"pass": self.pass_id, "file": self.file,
                "line": self.line, "msg": self.msg}


class Module:
    """One parsed source file: AST + raw lines + the per-line inline
    suppression table."""

    def __init__(self, rel: str, source: str) -> None:
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree: Optional[ast.Module] = None
        self.parse_error: Optional[str] = None
        try:
            self.tree = ast.parse(source)
        except SyntaxError as e:        # surfaced as its own finding
            self.parse_error = f"{e.msg} (line {e.lineno})"
        # line -> set of suppressed pass ids ('*' = all)
        self.suppress: Dict[int, Set[str]] = {}
        for i, line in enumerate(self.lines, 1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            ids = {p.strip() for p in m.group(1).split(",") if p.strip()}
            self.suppress[i] = ids or {"*"}

    def suppressed(self, finding: Finding) -> bool:
        """Inline suppression: ``# jtlint: ok <pass>`` on the finding
        line, or on a standalone comment line directly above it."""
        for line in (finding.line, finding.line - 1):
            ids = self.suppress.get(line)
            if not ids:
                continue
            if line != finding.line:
                text = self.lines[line - 1].strip() \
                    if 0 < line <= len(self.lines) else ""
                if not text.startswith("#"):
                    continue
            if "*" in ids or finding.pass_id in ids:
                return True
        return False


class Tree:
    """The lint unit: every code module plus the doc texts. Built from
    a repo root, or assembled by tests from in-memory fixtures."""

    def __init__(self, root: str, modules: Sequence[Module],
                 docs: Dict[str, str]) -> None:
        self.root = root
        self.modules = list(modules)
        self.docs = dict(docs)

    @classmethod
    def load(cls, root: str) -> "Tree":
        modules: List[Module] = []
        for d in _CODE_DIRS:
            base = os.path.join(root, d)
            for dirpath, dirnames, filenames in os.walk(base):
                dirnames[:] = [x for x in dirnames
                               if x != "__pycache__"
                               and not x.startswith(".")]
                for fn in sorted(filenames):
                    if not fn.endswith(".py"):
                        continue
                    path = os.path.join(dirpath, fn)
                    rel = os.path.relpath(path, root).replace(os.sep,
                                                              "/")
                    modules.append(cls._read_module(path, rel))
        for fn in _CODE_FILES:
            path = os.path.join(root, fn)
            if os.path.exists(path):
                modules.append(cls._read_module(path, fn))
        docs: Dict[str, str] = {}
        for fn in _DOC_FILES:
            path = os.path.join(root, fn)
            if os.path.exists(path):
                with open(path, encoding="utf-8") as f:
                    docs[fn] = f.read()
        for d in _DOC_DIRS:
            base = os.path.join(root, d)
            if not os.path.isdir(base):
                continue
            for fn in sorted(os.listdir(base)):
                if fn.endswith(".md"):
                    with open(os.path.join(base, fn),
                              encoding="utf-8") as f:
                        docs[f"{d}/{fn}"] = f.read()
        return cls(root, modules, docs)

    @staticmethod
    def _read_module(path: str, rel: str) -> Module:
        with open(path, encoding="utf-8") as f:
            return Module(rel, f.read())

    def module(self, rel: str) -> Optional[Module]:
        for m in self.modules:
            if m.rel == rel:
                return m
        return None


# -- pass registry (populated lazily to keep import order trivial) -------

def _passes() -> Dict[str, Any]:
    from jepsen_tpu.analysis import (counters, donation, envgates,
                                     fallback, locks)
    return {
        "donation": donation.run,
        "fallback": fallback.run,
        "env-gate": envgates.run,
        "counter-drift": counters.run,
        "lock-discipline": locks.run,
    }


def run_passes(tree: Tree,
               passes: Optional[Sequence[str]] = None) -> List[Finding]:
    """All findings from the selected passes (default: every pass),
    plus one ``parse`` finding per unparseable module — a file the
    analyzer cannot read must not pass silently."""
    registry = _passes()
    selected = list(passes) if passes else list(PASS_IDS)
    unknown = [p for p in selected if p not in registry]
    if unknown:
        raise ValueError(f"unknown pass(es): {unknown}")
    findings: List[Finding] = []
    for m in tree.modules:
        if m.parse_error:
            findings.append(Finding("parse", m.rel, 1,
                                    f"unparseable: {m.parse_error}"))
    for p in selected:
        findings.extend(registry[p](tree))
    findings.sort(key=lambda f: (f.file, f.line, f.pass_id, f.msg))
    return findings


# -- suppression + baseline ----------------------------------------------

def load_baseline(path: str) -> Dict[Tuple[str, str, str], int]:
    """key -> accepted occurrence count (entries without a ``count``
    field accept exactly one occurrence)."""
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    out: Dict[Tuple[str, str, str], int] = {}
    for e in data.get("findings", []):
        key = (e["pass"], e["file"], e["msg"])
        out[key] = out.get(key, 0) + int(e.get("count", 1))
    return out


def save_baseline(path: str, findings: Sequence[Finding]) -> None:
    counts: Dict[Tuple[str, str, str], int] = {}
    for f in findings:
        counts[f.key()] = counts.get(f.key(), 0) + 1
    # carry hand-written extra fields (the review `why` rationales)
    # through a regeneration for keys that survive
    extras: Dict[Tuple[str, str, str], Dict[str, Any]] = {}
    if os.path.exists(path):
        try:
            with open(path, encoding="utf-8") as f:
                for e in json.load(f).get("findings", []):
                    key = (e["pass"], e["file"], e["msg"])
                    extra = {k: v for k, v in e.items()
                             if k not in ("pass", "file", "msg",
                                          "count")}
                    if extra:
                        extras[key] = extra
        except (OSError, ValueError, KeyError):
            pass
    data = {
        "_comment": ("jtlint accepted pre-existing findings; adds "
                     "require touching this checked-in file so they "
                     "show up in review. Keyed (pass, file, msg) "
                     "with an occurrence count — line-number churn "
                     "cannot invalidate entries, but a NEW identical "
                     "violation in the same file exceeds the count "
                     "and goes live."),
        "findings": [dict({"pass": p, "file": fl, "msg": m,
                           "count": counts[(p, fl, m)]},
                          **extras.get((p, fl, m), {}))
                     for (p, fl, m) in sorted(counts)],
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=True,
                  ensure_ascii=False)
        f.write("\n")


def triage(tree: Tree, findings: Sequence[Finding],
           baseline: Dict[Tuple[str, str, str], int],
           passes: Optional[Sequence[str]] = None
           ) -> Dict[str, List[Finding]]:
    """Split findings into inline-suppressed, baselined, and live
    (unsuppressed). The baseline accepts up to ``count`` occurrences
    per key — the count+1'th identical violation goes LIVE, so a new
    instance of an accepted pattern still shows up in review. Entries
    whose accepted count exceeds what fired are ``stale_baseline``
    (accepted cases cannot quietly outlive their justification);
    staleness only considers entries of the selected ``passes`` —
    a subset run must not call untested entries stale."""
    remaining = dict(baseline)
    by_rel = {m.rel: m for m in tree.modules}
    out: Dict[str, List[Finding]] = {
        "live": [], "inline": [], "baselined": []}
    for f in findings:
        mod = by_rel.get(f.file)
        if mod is not None and mod.suppressed(f):
            out["inline"].append(f)
        elif remaining.get(f.key(), 0) > 0:
            remaining[f.key()] -= 1
            out["baselined"].append(f)
        else:
            out["live"].append(f)
    ran = set(passes) if passes else set(PASS_IDS)
    out["stale_baseline"] = [Finding(p, fl, 0, m)
                             for (p, fl, m), n in remaining.items()
                             if n > 0 and p in ran]
    return out


def run_lint(root: str, passes: Optional[Sequence[str]] = None,
             baseline_path: Optional[str] = None) -> Dict[str, Any]:
    """Load the tree, run the passes, triage against the baseline.
    The programmatic entry tests and tools share with the CLI."""
    tree = Tree.load(root)
    findings = run_passes(tree, passes)
    bp = baseline_path if baseline_path is not None else \
        os.path.join(root, _DEFAULT_BASELINE)
    t = triage(tree, findings, load_baseline(bp), passes)
    t["tree"] = tree
    return t


# -- CLI -----------------------------------------------------------------

def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="jtlint",
        description="AST-driven invariant analyzer (docs/ANALYSIS.md)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: auto-detected)")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline path (default: {_DEFAULT_BASELINE})")
    ap.add_argument("--passes", default=None,
                    help="comma-separated subset of: "
                         + ",".join(PASS_IDS))
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 on any live finding or stale "
                         "baseline entry")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from the current live "
                         "findings")
    ap.add_argument("--emit-env-registry", action="store_true",
                    help=f"regenerate {_DEFAULT_REGISTRY} from the "
                         "tree and exit")
    args = ap.parse_args(argv)

    root = args.root or _find_root()
    passes = ([p.strip() for p in args.passes.split(",") if p.strip()]
              if args.passes else None)

    if args.emit_env_registry:
        from jepsen_tpu.analysis import envgates
        tree = Tree.load(root)
        path = os.path.join(root, _DEFAULT_REGISTRY)
        envgates.write_registry(tree, path)
        print(f"wrote {os.path.relpath(path, root)} "
              f"({len(envgates.collect_gates(tree))} gates)")
        return 0

    bp = args.baseline or os.path.join(root, _DEFAULT_BASELINE)

    if args.write_baseline:
        # regenerate from scratch: triage against an EMPTY baseline so
        # currently-baselined findings are re-accepted, not dropped
        tree = Tree.load(root)
        t0 = triage(tree, run_passes(tree, passes), {}, passes)
        save_baseline(bp, t0["live"])
        print(f"wrote {os.path.relpath(bp, root)} "
              f"({len(t0['live'])} findings)")
        return 0

    t = run_lint(root, passes, bp)

    if args.json:
        print(json.dumps({
            "live": [f.to_json() for f in t["live"]],
            "inline_suppressed": [f.to_json() for f in t["inline"]],
            "baselined": [f.to_json() for f in t["baselined"]],
            "stale_baseline": [f.to_json()
                               for f in t["stale_baseline"]],
        }, indent=2))
    else:
        for f in t["live"]:
            print(f.render())
        for f in t["stale_baseline"]:
            print(f"{f.file}: [{f.pass_id}] STALE baseline entry "
                  f"(no longer fires): {f.msg}")
        print(f"jtlint: {len(t['live'])} live, "
              f"{len(t['inline'])} inline-suppressed, "
              f"{len(t['baselined'])} baselined, "
              f"{len(t['stale_baseline'])} stale baseline")
    if args.strict and (t["live"] or t["stale_baseline"]):
        return 1
    return 0


def _find_root() -> str:
    """Repo root: the directory holding the ``jepsen_tpu`` package
    this module was imported from."""
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.dirname(os.path.dirname(here))


if __name__ == "__main__":          # pragma: no cover
    sys.exit(main())
