"""``python -m jepsen_tpu.analysis`` — the jtlint CLI."""
import sys

from jepsen_tpu.analysis.core import main

if __name__ == "__main__":
    sys.exit(main())
