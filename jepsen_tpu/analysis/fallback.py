"""jtlint pass ``fallback``: ``except`` handlers in ``checkers/``,
``serve/``, and ``txn/`` that *suppress* (return / continue / break /
fall through rather than re-raise) without an obs/ledger record on
every suppressing path.

This is the "no silent fallback" discipline OBSERVABILITY.md
documents and `obs.capture()` asserts dynamically — made static, so
a new ``except Exception: return None`` cannot land unrecorded even
on paths no test exercises.

What counts as a record: a call to ``obs.count`` / ``gauge`` /
``histogram`` / ``observe`` / ``decision`` / ``engine_fallback`` /
``engine_selected`` / ``engine_skipped`` / ``checker_swallowed``, a
``ledger_record`` (the serve tenant ledger), a call to any tree
function/method that itself records (computed as a name-keyed
fixpoint, so helpers like ``facade._fellback``,
``session._to_host_monitor``, or ``reach._warn_pallas_failed``
satisfy the discipline at their call sites), or — in the serve HTTP
layer — a structured ``return 4xx/5xx, {...}`` error response (the
client receives the error; the response is the record).

Path analysis is a conservative structural walk: ``if``/``else``
branches are both followed, loop bodies may run zero times (a record
inside a loop does NOT satisfy the discipline), and a handler whose
every path raises needs nothing. Best-effort cleanup handlers
(``except OSError: pass`` around ``os.unlink``) that are genuinely
fine carry an inline ``# jtlint: ok fallback`` with the
justification next to the code it excuses.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from jepsen_tpu.analysis.core import Finding, Tree

PASS_ID = "fallback"

_SCOPES = ("jepsen_tpu/checkers/", "jepsen_tpu/serve/",
           "jepsen_tpu/txn/")

_OBS_ATTRS = {
    "count", "gauge", "histogram", "observe", "decision",
    "engine_fallback", "engine_selected", "engine_skipped",
    "checker_swallowed", "ledger_record",
}


def _call_name(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def _tree_recorders(tree: Tree) -> Set[str]:
    """Names of functions/methods anywhere in the tree whose body
    contains an obs-ish call, closed under calls-a-recorder
    (fixpoint) — so a handler delegating to a helper that records
    (``facade._fellback``, ``session._to_host_monitor``,
    ``reach._warn_pallas_failed`` from another module) is compliant.
    Name-keyed across modules: deliberately permissive — a shared
    name with one recording definition credits them all, which can
    only under-report, never false-positive."""
    fns: Dict[str, ast.AST] = {}
    for mod in tree.modules:
        if mod.tree is None:
            continue
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                fns.setdefault(node.name, node)
    recorders: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for name, fn in fns.items():
            if name in recorders:
                continue
            for n in ast.walk(fn):
                if isinstance(n, ast.Call):
                    cn = _call_name(n)
                    if cn in _OBS_ATTRS or cn in recorders:
                        recorders.add(name)
                        changed = True
                        break
    return recorders


def _http_error_return(st: ast.Return) -> bool:
    """``return 4xx/5xx, {...}`` — the serve HTTP layer's structured
    error responses. The client receives the error, so the path is
    not silent: the response IS the record."""
    v = st.value
    return (isinstance(v, ast.Tuple) and len(v.elts) >= 2
            and isinstance(v.elts[0], ast.Constant)
            and isinstance(v.elts[0].value, int)
            and v.elts[0].value >= 400)


def _records(node: ast.AST, recorders: Set[str]) -> bool:
    """Does this (sub)tree contain an obs/ledger/recorder call?"""
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            cn = _call_name(n)
            if cn in _OBS_ATTRS or cn in recorders:
                return True
    return False


# terminals: (kind, recorded) with kind in raise/return/continue/break
_Terminal = Tuple[str, bool]


def _block(stmts: Sequence[ast.stmt], rec: bool,
           recorders: Set[str]) -> Tuple[List[_Terminal],
                                         Optional[bool]]:
    """Walk a statement list. Returns (terminals, fallthrough):
    ``terminals`` are the exits taken inside, each with
    recorded-by-then; ``fallthrough`` is recorded-at-end, or None
    when the block cannot fall through."""
    terms: List[_Terminal] = []
    for st in stmts:
        if isinstance(st, ast.Raise):
            terms.append(("raise", rec))
            return terms, None
        if isinstance(st, ast.Return):
            terms.append(("return",
                          rec or _records(st, recorders)
                          or _http_error_return(st)))
            return terms, None
        if isinstance(st, ast.Continue):
            terms.append(("continue", rec))
            return terms, None
        if isinstance(st, ast.Break):
            terms.append(("break", rec))
            return terms, None
        if isinstance(st, ast.If):
            if _records(st.test, recorders):
                rec = True
            t1, f1 = _block(st.body, rec, recorders)
            t2, f2 = _block(st.orelse, rec, recorders)
            terms += t1 + t2
            if f1 is None and f2 is None:
                return terms, None
            rec = all(f for f in (f1, f2) if f is not None)
            continue
        if isinstance(st, (ast.For, ast.While)):
            it = getattr(st, "iter", None) or getattr(st, "test", None)
            if it is not None and _records(it, recorders):
                rec = True
            t, _f = _block(st.body, rec, recorders)
            te, fe = _block(st.orelse, rec, recorders)
            # break/continue are loop-local; the loop may run zero
            # times, so body records do not carry past it
            terms += [x for x in t if x[0] in ("raise", "return")]
            terms += te
            if fe is None:
                return terms, None
            rec = rec and fe
            continue
        if isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                if _records(item.context_expr, recorders):
                    rec = True
            t, f = _block(st.body, rec, recorders)
            terms += t
            if f is None:
                return terms, None
            rec = f
            continue
        if isinstance(st, ast.Try):
            tb, fb = _block(st.body, rec, recorders)
            # raises in the try body may be caught by its own
            # handlers — drop them (never hides a bad exit: the
            # handlers' own exits are walked below)
            terms += [x for x in tb if x[0] != "raise"]
            falls: List[Optional[bool]] = [fb]
            for h in st.handlers:
                th, fh = _block(h.body, rec, recorders)
                terms += th
                falls.append(fh)
            if st.orelse:
                to, fo = _block(st.orelse, fb if fb is not None
                                else rec, recorders)
                terms += to
                falls[0] = fo if fb is not None else None
            if st.finalbody:
                tf, ff = _block(st.finalbody, rec, recorders)
                terms += [x for x in tf if x[0] == "raise"]
                if ff is None:
                    return terms, None
                if _records(ast.Module(body=list(st.finalbody),
                                       type_ignores=[]), recorders):
                    falls = [True if f is not None else None
                             for f in falls]
            live = [f for f in falls if f is not None]
            if not live:
                return terms, None
            rec = all(live)
            continue
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            continue                    # a nested def runs later
        if _records(st, recorders):
            rec = True
    return terms, rec


def _handler_findings(handler: ast.ExceptHandler, mod: Module,
                      recorders: Set[str],
                      finally_records: bool = False) -> List[Finding]:
    # a recording `finally` on the handler's own Try runs on every
    # exit path through the handler — credit it up front
    terms, fall = _block(handler.body, finally_records, recorders)
    silent = [t for t in terms
              if t[0] in ("return", "continue", "break") and not t[1]]
    if fall is not None and not fall:
        silent.append(("fall", False))
    if not silent:
        return []
    caught = ast.unparse(handler.type) if handler.type is not None \
        else "BaseException"
    how = sorted({k for k, _ in silent})
    return [Finding(
        PASS_ID, mod.rel, handler.lineno,
        f"except {caught}: handler suppresses "
        f"({'/'.join(how)}) without an obs/ledger record on every "
        f"path")]


def run(tree: Tree) -> List[Finding]:
    findings: List[Finding] = []
    recorders = _tree_recorders(tree)
    for mod in tree.modules:
        if mod.tree is None:
            continue
        if not any(mod.rel.startswith(s) for s in _SCOPES):
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Try):
                continue
            fin = bool(node.finalbody) and any(
                _records(st, recorders) for st in node.finalbody)
            for handler in node.handlers:
                findings.extend(
                    _handler_findings(handler, mod, recorders,
                                      finally_records=fin))
    return findings
