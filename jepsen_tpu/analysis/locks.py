"""jtlint pass ``lock-discipline``: attributes a class declares
guarded may only be touched under its lock.

The convention (seeded in ``serve/request.py``, ``serve/journal.py``,
``serve/session.py`` — any class may adopt it):

```python
class Registry:
    _GUARDED_BY = {"_lock": ("_by_id", "_done_order")}
    # or, with the default lock attribute name "_lock":
    _GUARDED_BY = ("_by_id", "_done_order")
    # helper methods CALLED with the lock already held:
    _LOCK_ASSUMED = ("_rebuild",)
```

Every ``self.<attr>`` load/store of a guarded attribute inside the
class's methods must sit lexically within ``with self.<lock>:``.
Exempt: ``__init__`` (construction precedes sharing), methods whose
name ends in ``_locked`` (the repo's existing called-under-lock
suffix), and methods listed in ``_LOCK_ASSUMED``.

This is lexical, not interprocedural — a helper that genuinely runs
under the caller's lock is *declared* so (suffix or ``_LOCK_ASSUMED``)
rather than inferred, which keeps the contract readable at the class
head and reviewable when it changes. Accesses through other
receivers (``req.session.ops``) are out of scope: the discipline is
self-access; cross-object protocols stay on the owning class.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from jepsen_tpu.analysis.core import Finding, Tree

PASS_ID = "lock-discipline"

_DEFAULT_LOCK = "_lock"


def _const_str_seq(node: ast.AST) -> Optional[Tuple[str, ...]]:
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if not (isinstance(e, ast.Constant)
                    and isinstance(e.value, str)):
                return None
            out.append(e.value)
        return tuple(out)
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    return None


def _class_decl(cls: ast.ClassDef) -> Tuple[
        Dict[str, Tuple[str, ...]], Set[str]]:
    """(lock attr -> guarded attrs, lock-assumed method names)."""
    guards: Dict[str, Tuple[str, ...]] = {}
    assumed: Set[str] = set()
    for st in cls.body:
        if not isinstance(st, (ast.Assign, ast.AnnAssign)):
            continue
        targets = st.targets if isinstance(st, ast.Assign) \
            else [st.target]
        value = st.value
        if value is None:
            continue
        for t in targets:
            if not isinstance(t, ast.Name):
                continue
            if t.id == "_GUARDED_BY":
                if isinstance(value, ast.Dict):
                    for k, v in zip(value.keys, value.values):
                        if isinstance(k, ast.Constant) \
                                and isinstance(k.value, str):
                            attrs = _const_str_seq(v)
                            if attrs:
                                guards[k.value] = attrs
                else:
                    attrs = _const_str_seq(value)
                    if attrs:
                        guards[_DEFAULT_LOCK] = attrs
            elif t.id == "_LOCK_ASSUMED":
                names = _const_str_seq(value)
                if names:
                    assumed.update(names)
    return guards, assumed


def _lock_names_held(with_stmt: ast.With) -> Set[str]:
    """Lock attribute names this ``with`` acquires via
    ``with self.<name>:`` (any item)."""
    out: Set[str] = set()
    for item in with_stmt.items:
        e = item.context_expr
        if isinstance(e, ast.Attribute) \
                and isinstance(e.value, ast.Name) \
                and e.value.id == "self":
            out.add(e.attr)
    return out


def _check_method(cls_name: str, method: ast.FunctionDef,
                  guards: Dict[str, Tuple[str, ...]],
                  rel: str) -> List[Finding]:
    attr_to_lock: Dict[str, str] = {}
    for lock, attrs in guards.items():
        for a in attrs:
            attr_to_lock[a] = lock
    findings: List[Finding] = []

    def visit(node: ast.AST, held: Set[str]) -> None:
        if isinstance(node, ast.With):
            held = held | _lock_names_held(node)
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self" \
                and node.attr in attr_to_lock:
            lock = attr_to_lock[node.attr]
            if lock not in held:
                findings.append(Finding(
                    PASS_ID, rel, node.lineno,
                    f"{cls_name}.{method.name} touches guarded "
                    f"attribute 'self.{node.attr}' outside "
                    f"`with self.{lock}`"))
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for st in method.body:
        visit(st, set())
    # one finding per line/attr
    seen: Set[Tuple[int, str]] = set()
    out = []
    for f in findings:
        k = (f.line, f.msg)
        if k not in seen:
            seen.add(k)
            out.append(f)
    return out


def run(tree: Tree) -> List[Finding]:
    findings: List[Finding] = []
    for mod in tree.modules:
        if mod.tree is None:
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            guards, assumed = _class_decl(node)
            if not guards:
                continue
            for st in node.body:
                if not isinstance(st, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    continue
                if st.name == "__init__" \
                        or st.name.endswith("_locked") \
                        or st.name in assumed:
                    continue
                findings.extend(
                    _check_method(node.name, st, guards, mod.rel))
    return findings
