"""jtlint pass ``donation``: host-side reads of a donated operand
after its dispatch — the exact PR-10 bug class (a donated word-walk
carry read by the host while XLA recycled its buffer corrupted the
session frontier in ~30% of concurrent runs, never single-threaded).

What it knows how to see, all with pure ``ast``:

1. **Donating callables.** A ``jax.jit(..., donate_argnums=<literal>)``
   call anywhere makes its enclosing function a *donating factory*
   (the repo idiom: ``_jitted_advance_frontier`` /
   ``_lane_call(..., donate=True)`` / ``_inc_call(...)`` return the
   jitted callable). When the jit sits under ``X if <param> else Y``
   or ``if <param>:`` and ``<param>`` is a factory parameter, donation
   is *gated*: a call site donates only when it passes that parameter
   a value other than its (False) default — resolved positionally or
   by keyword against the factory's signature.
2. **Donating call sites.** ``factory(...)(args)``, a local binding
   ``f = factory(...); f(args)``, or an immediate
   ``jax.jit(g, donate_argnums=...)(args)``.
3. **The dataflow.** For a donated operand that is a plain name or a
   ``self.<attr>``, statement-ordered scan of the enclosing function
   AFTER the dispatch: a load before any rebind is a finding. If the
   dispatch statement itself rebinds the operand
   (``R = step(R, ...)`` — the carried-advance idiom) the name refers
   to the fresh buffer and the site is clean. If the dispatch sits in
   a loop and the operand is never rebound inside it, reads earlier
   in the loop body execute after the dispatch on iteration 2+ and
   are flagged too (the PR-10 shape).

Over-approximations, by design: statements in exclusive ``else``
branches after the dispatch are scanned (suppress with
``# jtlint: ok donation`` when provably unreachable), and donated
operands that are expressions (``jnp.asarray(x)``) are skipped — a
fresh temporary has no host alias to protect.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from jepsen_tpu.analysis.core import Finding, Module, Tree

PASS_ID = "donation"

# a symbol is a plain local name ('name', x) or an instance attribute
# ('self', attr) — the two alias shapes worth tracking
Sym = Tuple[str, str]


@dataclass
class Factory:
    """One donating callable maker."""
    name: str
    positions: Tuple[int, ...]
    params: Tuple[str, ...] = ()
    gate_param: Optional[str] = None       # donation-enabling param
    gate_default: bool = False             # its default truthiness
    direct: bool = False                   # name IS the jitted callable


def _is_jit(func: ast.AST) -> bool:
    if isinstance(func, ast.Attribute) and func.attr == "jit":
        return True
    return isinstance(func, ast.Name) and func.id == "jit"


def _donate_positions(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """Literal donate_argnums of a jax.jit call, else None."""
    if not _is_jit(call.func):
        return None
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)):
            out = []
            for e in v.elts:
                if not (isinstance(e, ast.Constant)
                        and isinstance(e.value, int)):
                    return None
                out.append(e.value)
            return tuple(out)
        return None
    return None


def _param_names(fn: ast.FunctionDef) -> Tuple[str, ...]:
    a = fn.args
    return tuple(p.arg for p in (a.posonlyargs + a.args))


def _param_default(fn: ast.FunctionDef, name: str) -> bool:
    """Truthiness of the (constant) default of ``name``; False when
    required or non-constant."""
    a = fn.args
    pos = list(a.posonlyargs + a.args)
    defaults = list(a.defaults)
    for p, d in zip(reversed(pos), reversed(defaults)):
        if p.arg == name and isinstance(d, ast.Constant):
            return bool(d.value)
    for p, d in zip(a.kwonlyargs, a.kw_defaults):
        if p.arg == name and isinstance(d, ast.Constant):
            return bool(d.value)
    return False


def _decorator_donation(fn: ast.FunctionDef) -> Optional[Factory]:
    """``@functools.partial(jax.jit, donate_argnums=…)`` (the common
    decorator idiom): the decorated function IS a donating callable."""
    for dec in fn.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        f = dec.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None)
        if name == "partial" and dec.args and _is_jit(dec.args[0]):
            jit_proxy = ast.Call(func=dec.args[0], args=[],
                                 keywords=dec.keywords)
            pos = _donate_positions(jit_proxy)
            if pos:
                return Factory(fn.name, pos, direct=True)
        pos = _donate_positions(dec)        # @jax.jit(donate_argnums=…)
        if pos:
            return Factory(fn.name, pos, direct=True)
    return None


def collect_factories(tree: Tree) -> Dict[str, Factory]:
    """Bare-name index of donating callables across the whole tree
    (call sites routinely import them, so matching is by name — a
    collision keeps the first record, conservatively)."""
    out: Dict[str, Factory] = {}
    for mod in tree.modules:
        if mod.tree is None:
            continue
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.FunctionDef):
                fac = _decorator_donation(node) \
                    or _factory_from_def(node)
                if fac is not None:
                    out.setdefault(fac.name, fac)
            elif isinstance(node, ast.Assign):
                # module/class-level `g = jax.jit(f, donate_argnums=…)`
                if (isinstance(node.value, ast.Call)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)):
                    pos = _donate_positions(node.value)
                    if pos:
                        n = node.targets[0].id
                        out.setdefault(n, Factory(n, pos, direct=True))
    return out


def _own_statements(fn: ast.FunctionDef) -> List[ast.stmt]:
    """The function's statements in source order, recursing into
    compound statements but NOT into nested function/class defs."""
    out: List[ast.stmt] = []

    def rec(stmts: Sequence[ast.stmt]) -> None:
        for st in stmts:
            out.append(st)
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            for fname in ("body", "orelse", "finalbody"):
                sub = getattr(st, fname, None)
                if sub:
                    rec(sub)
            for h in getattr(st, "handlers", ()) or ():
                rec(h.body)
    rec(fn.body)
    return out


def _factory_from_def(fn: ast.FunctionDef) -> Optional[Factory]:
    """Does ``fn`` contain a donate-jit call in its OWN statements
    (nested defs excluded — those are the kernel bodies being
    jitted)? Resolve the optional gating parameter."""
    params = _param_names(fn)
    for st in _own_statements(fn):
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            continue
        for node in ast.walk(st):
            if isinstance(node, (ast.FunctionDef, ast.Lambda)):
                continue
            if not isinstance(node, ast.Call):
                continue
            pos = _donate_positions(node)
            if pos is None:
                continue
            gate = _gate_param(st, node, params)
            return Factory(fn.name, pos, params, gate,
                           _param_default(fn, gate) if gate else False)
    return None


def _gate_param(stmt: ast.stmt, jit_call: ast.Call,
                params: Tuple[str, ...]) -> Optional[str]:
    """Gating parameter when the jit call sits under
    ``A if <param> else B`` or ``if <param>:``."""
    for node in ast.walk(stmt):
        if isinstance(node, ast.IfExp) \
                and isinstance(node.test, ast.Name) \
                and node.test.id in params:
            if any(n is jit_call for n in ast.walk(node.body)):
                return node.test.id
    if isinstance(stmt, ast.If) and isinstance(stmt.test, ast.Name) \
            and stmt.test.id in params:
        return stmt.test.id
    return None


def _call_donates(fac: Factory, call: ast.Call) -> bool:
    """Does THIS call to a gated factory enable donation? Ungated
    factories always donate; gated ones donate when the gate argument
    resolves to anything but a constant falsy (absent -> default)."""
    if fac.direct:
        return True
    if fac.gate_param is None:
        return True
    for kw in call.keywords:
        if kw.arg == fac.gate_param:
            if isinstance(kw.value, ast.Constant):
                return bool(kw.value.value)
            return True                     # dynamic gate: assume on
        if kw.arg is None:
            return True                     # **kwargs: unresolvable
    try:
        idx = fac.params.index(fac.gate_param)
    except ValueError:
        return fac.gate_default
    if idx < len(call.args):
        a = call.args[idx]
        if any(isinstance(x, ast.Starred) for x in call.args[:idx + 1]):
            return True                     # *args: unresolvable
        if isinstance(a, ast.Constant):
            return bool(a.value)
        return True
    return fac.gate_default


def _sym_of(expr: ast.AST) -> Optional[Sym]:
    if isinstance(expr, ast.Name):
        return ("name", expr.id)
    if isinstance(expr, ast.Attribute) \
            and isinstance(expr.value, ast.Name) \
            and expr.value.id == "self":
        return ("self", expr.attr)
    return None


def _direct_nodes(stmt: ast.stmt) -> List[ast.AST]:
    """Expression nodes DIRECTLY in this statement — compound
    statements contribute only their headers (their bodies are
    separate entries in the flattened statement order), and nested
    function/lambda bodies are excluded."""
    roots: List[ast.AST] = []
    for fname, val in ast.iter_fields(stmt):
        if fname in ("body", "orelse", "finalbody", "handlers"):
            continue
        if isinstance(val, ast.AST):
            roots.append(val)
        elif isinstance(val, list):
            roots.extend(x for x in val if isinstance(x, ast.AST))
    out: List[ast.AST] = []
    for root in roots:
        for node in ast.walk(root):
            if isinstance(node, (ast.FunctionDef, ast.Lambda)):
                continue
            out.append(node)
    return out


def _sym_events(stmt: ast.stmt) -> Tuple[Set[Sym], Set[Sym]]:
    """(loads, stores) of trackable symbols DIRECTLY in this
    statement (compound statements contribute only their headers —
    their bodies are separate entries in the flattened order)."""
    loads: Set[Sym] = set()
    stores: Set[Sym] = set()
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return loads, stores
    for node in _direct_nodes(stmt):
        sym = _sym_of(node)
        if sym is None:
            continue
        ctx = getattr(node, "ctx", None)
        if isinstance(ctx, ast.Store):
            stores.add(sym)
        elif isinstance(ctx, (ast.Load, ast.Del)):
            loads.add(sym)
    if isinstance(stmt, ast.AugAssign):
        # `R |= mask` LOADS the old buffer before rebinding — on a
        # donated operand that read is itself the hazard
        sym = _sym_of(stmt.target)
        if sym is not None:
            loads.add(sym)
    return loads, stores


def _conditional_ancestors(fn: ast.FunctionDef
                           ) -> Dict[ast.stmt, Tuple[ast.stmt, ...]]:
    """Per statement, the enclosing branching/looping statements
    (if/for/while/try) within ``fn`` — a statement under one of these
    may not execute on every path through code that reaches it."""
    out: Dict[ast.stmt, Tuple[ast.stmt, ...]] = {}

    def rec(node: ast.AST, chain: Tuple[ast.stmt, ...]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)) \
                and node is not fn:
            return
        if isinstance(node, ast.stmt):
            out[node] = chain
        if isinstance(node, (ast.If, ast.For, ast.While, ast.Try)):
            chain = chain + (node,)
        for child in ast.iter_child_nodes(node):
            rec(child, chain)

    rec(fn, ())
    return out


def _enclosing_loop(fn: ast.FunctionDef,
                    stmt: ast.stmt) -> Optional[ast.stmt]:
    """Innermost for/while of ``fn`` containing ``stmt`` (nested defs
    excluded)."""
    best: Optional[ast.stmt] = None

    def rec(node: ast.AST, loops: List[ast.stmt]) -> bool:
        if node is stmt:
            nonlocal best
            best = loops[-1] if loops else None
            return True
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)) \
                and node is not fn:
            return False
        push = isinstance(node, (ast.For, ast.While))
        if push:
            loops.append(node)
        hit = any(rec(c, loops) for c in ast.iter_child_nodes(node))
        if push:
            loops.pop()
        return hit

    rec(fn, [])
    return best


@dataclass
class _Site:
    call: ast.Call
    stmt: ast.stmt
    sym: Sym
    factory: str


def _donating_sites(fn: ast.FunctionDef,
                    factories: Dict[str, Factory]) -> List[_Site]:
    """Donated (trackable) operands of every donating dispatch in
    ``fn``, with the statement each dispatch lives in."""
    stmts = _own_statements(fn)
    # local bindings: f = factory(...)  (donation resolved per call)
    bound: Dict[str, Factory] = {}
    for st in stmts:
        if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                and isinstance(st.targets[0], ast.Name) \
                and isinstance(st.value, ast.Call):
            fac = _factory_of_call(st.value, factories)
            if fac is not None and not fac.direct \
                    and _call_donates(fac, st.value):
                bound[st.targets[0].id] = fac

    sites: List[_Site] = []
    for st in stmts:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            continue
        for node in _direct_nodes(st):
            if not isinstance(node, ast.Call):
                continue
            fac, positions = _dispatch_positions(node, factories, bound)
            if fac is None:
                continue
            if any(isinstance(a, ast.Starred) for a in node.args):
                continue                    # arg mapping unresolvable
            for p in positions:
                if p < len(node.args):
                    sym = _sym_of(node.args[p])
                    if sym is not None:
                        sites.append(_Site(node, st, sym, fac))
    return sites


def _factory_of_call(call: ast.Call,
                     factories: Dict[str, Factory]
                     ) -> Optional[Factory]:
    f = call.func
    name = None
    if isinstance(f, ast.Name):
        name = f.id
    elif isinstance(f, ast.Attribute):
        name = f.attr
    fac = factories.get(name) if name else None
    return fac if fac is not None and not fac.direct else None


def _dispatch_positions(call: ast.Call, factories: Dict[str, Factory],
                        bound: Dict[str, Factory]
                        ) -> Tuple[Optional[str], Tuple[int, ...]]:
    """Is ``call`` a donating dispatch? Returns (factory name,
    donated positions) or (None, ())."""
    f = call.func
    # factory(...)(args) — including jax.jit(g, donate_argnums=…)(args)
    if isinstance(f, ast.Call):
        pos = _donate_positions(f)
        if pos is not None:
            return ("jax.jit", pos)
        fac = _factory_of_call(f, factories)
        if fac is not None and _call_donates(fac, f):
            return (fac.name, fac.positions)
        return (None, ())
    # bound(args) / direct(args)
    name = None
    if isinstance(f, ast.Name):
        name = f.id
    elif isinstance(f, ast.Attribute):
        name = f.attr
    if name in bound:
        return (bound[name].name, bound[name].positions)
    fac = factories.get(name) if name else None
    if fac is not None and fac.direct:
        return (fac.name, fac.positions)
    return (None, ())


def _sym_str(sym: Sym) -> str:
    return f"self.{sym[1]}" if sym[0] == "self" else sym[1]


def _check_site(fn: ast.FunctionDef, site: _Site,
                mod: Module) -> List[Finding]:
    stmts = _own_statements(fn)
    try:
        i = stmts.index(site.stmt)
    except ValueError:                      # pragma: no cover
        return []
    loads_i, stores_i = _sym_events(site.stmt)
    if site.sym in stores_i:
        # `R = step(R, …)`: the name now holds the fresh buffer
        return []
    findings: List[Finding] = []
    cond = _conditional_ancestors(fn)
    call_chain = set(cond.get(site.stmt, ()))

    def scan(seq: Sequence[ast.stmt]) -> Optional[str]:
        for st in seq:
            lo, sto = _sym_events(st)
            if site.sym in lo:
                findings.append(Finding(
                    PASS_ID, mod.rel, st.lineno,
                    f"host read of donated operand "
                    f"'{_sym_str(site.sym)}' after donating dispatch "
                    f"of {site.factory} (donated at line "
                    f"{site.call.lineno})"))
                return "read"
            if site.sym in sto:
                # a store ends the hazard only when it executes
                # UNCONDITIONALLY relative to the dispatch: a rebind
                # inside an if/loop/try the dispatch is not in may be
                # skipped, leaving later reads on the stale buffer
                if set(cond.get(st, ())) <= call_chain:
                    return "rebound"
        return None

    outcome = scan(stmts[i + 1:])
    if outcome == "read":
        return findings
    # loop wrap: never rebound inside the enclosing loop -> loads
    # textually before the dispatch run on the stale buffer next
    # iteration (the PR-10 shape)
    loop = _enclosing_loop(fn, site.stmt)
    if loop is not None:
        loop_stmts = [st for st in stmts
                      if st is not loop
                      and st.lineno >= loop.lineno
                      and (st.end_lineno or st.lineno)
                      <= (loop.end_lineno or loop.lineno)]
        rebound_in_loop = any(
            site.sym in _sym_events(st)[1] for st in loop_stmts)
        if not rebound_in_loop:
            j = loop_stmts.index(site.stmt)
            scan(loop_stmts[:j])
    return findings


def run(tree: Tree) -> List[Finding]:
    factories = collect_factories(tree)
    findings: List[Finding] = []
    if not factories:
        return findings
    for mod in tree.modules:
        if mod.tree is None:
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.FunctionDef):
                continue
            for site in _donating_sites(node, factories):
                findings.extend(_check_site(node, site, mod))
    # one finding per (file, line, msg)
    seen: Set[Tuple[str, int, str]] = set()
    out = []
    for f in findings:
        k = (f.file, f.line, f.msg)
        if k not in seen:
            seen.add(k)
            out.append(f)
    return out
