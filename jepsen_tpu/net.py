"""Network fault primitives — upstream ``jepsen/src/jepsen/net.clj``
(SURVEY.md §2.1, L2): the ``Net`` protocol ``drop!/heal!/slow!/flaky!/
fast!`` with an iptables/tc implementation, plus an in-process
implementation driving a :class:`~jepsen_tpu.fake.cluster.FakeCluster`
(no root, no SSH — the CI story).
"""
from __future__ import annotations

from typing import Mapping, Optional, Sequence

from jepsen_tpu import control


class Net:
    """Upstream ``jepsen.net/Net`` protocol."""

    def drop(self, test: Mapping, src: str, dst: str) -> None:
        """One-way: packets from ``src`` to ``dst`` are dropped."""
        raise NotImplementedError

    def heal(self, test: Mapping) -> None:
        """Remove all partitions."""
        raise NotImplementedError

    def slow(self, test: Mapping, mean_ms: float = 50.0,
             variance_ms: float = 10.0) -> None:
        """Add latency to all node traffic."""
        raise NotImplementedError

    def flaky(self, test: Mapping, prob: float = 0.2) -> None:
        """Drop a fraction of all packets."""
        raise NotImplementedError

    def fast(self, test: Mapping) -> None:
        """Remove slow/flaky impairments."""
        raise NotImplementedError


class IptablesNet(Net):
    """Drives ``iptables`` (partitions) and ``tc``/netem (latency, loss)
    over the control session, exactly the upstream recipe:
    ``iptables -A INPUT -s <src-ip> -j DROP -w`` on the destination node."""

    def drop(self, test, src, dst):
        s = control.session(test, dst).su()
        s.exec("iptables", "-A", "INPUT", "-s", src, "-j", "DROP", "-w")

    def heal(self, test):
        def fn(s: control.Session, node: str):
            s = s.su()
            s.exec("iptables", "-F", "-w")
            s.exec("iptables", "-X", "-w")
        control.on_nodes(test, fn)

    def slow(self, test, mean_ms=50.0, variance_ms=10.0):
        def fn(s: control.Session, node: str):
            s.su().exec("tc", "qdisc", "add", "dev", "eth0", "root",
                        "netem", "delay", f"{mean_ms}ms",
                        f"{variance_ms}ms", "distribution", "normal")
        control.on_nodes(test, fn)

    def flaky(self, test, prob=0.2):
        def fn(s: control.Session, node: str):
            s.su().exec("tc", "qdisc", "add", "dev", "eth0", "root",
                        "netem", "loss", f"{prob * 100:.1f}%",
                        "75%")
        control.on_nodes(test, fn)

    def fast(self, test):
        def fn(s: control.Session, node: str):
            s.su().exec_raw("tc qdisc del dev eth0 root")
        control.on_nodes(test, fn)


class FakeNet(Net):
    """In-process faults against a fake cluster (``test["cluster"]`` — see
    :mod:`jepsen_tpu.fake.cluster`). No upstream analogue; replaces the
    docker/SSH integration path for CI (SURVEY.md §4)."""

    def drop(self, test, src, dst):
        test["cluster"].drop_link(src, dst)

    def heal(self, test):
        test["cluster"].heal()

    def slow(self, test, mean_ms=50.0, variance_ms=10.0):
        test["cluster"].set_latency(mean_ms / 1000.0)

    def flaky(self, test, prob=0.2):
        test["cluster"].set_loss(prob)

    def fast(self, test):
        test["cluster"].set_latency(0.0)
        test["cluster"].set_loss(0.0)


def iptables() -> IptablesNet:
    return IptablesNet()


def fake() -> FakeNet:
    return FakeNet()


def net_for(test: Mapping) -> Net:
    n = test.get("net")
    if n is not None:
        return n
    if test.get("cluster") is not None:
        return FakeNet()
    return IptablesNet()
