"""History fixtures & generators for tests and benchmarks.

Upstream analogue: the recorded EDN histories shipped in ``knossos/data/``
(cas-register runs from real etcd tests, both linearizable and known-bad —
SURVEY.md §4). With no network and an empty reference mount, equivalents are
*synthesized*: :func:`gen_history` simulates concurrent clients against a
genuinely atomic object (each op commits at a random instant between its
invocation and response), so its output is linearizable by construction;
:func:`corrupt` then plants a read of a never-written value, making the
history provably non-linearizable.

These generators also drive the differential tests (TPU vs CPU oracle vs
brute force) and the benchmark ladder in ``BASELINE.md``.
"""
from __future__ import annotations

import random
from typing import Any, Dict, List, Optional, Tuple

from jepsen_tpu import models as m
from jepsen_tpu.op import Op, fail, info, invoke, ok


def gen_history(kind: str = "cas", n_ops: int = 100, processes: int = 5,
                values: int = 5, crash_p: float = 0.0,
                seed: Optional[int] = None,
                keys: int = 1) -> List[Op]:
    """Generate a linearizable-by-construction history.

    ``kind``: ``"register"`` (read/write), ``"cas"`` (read/write/cas),
    ``"mutex"`` (acquire/release), ``"multi"`` (multi-key read/write — op
    values are ``{key: value}`` maps over ``keys`` keys).

    Simulation: each process cycles IDLE → INVOKED → COMMITTED → RETURNED;
    at every tick one random process advances one stage. The commit applies
    the op to a live atomic object, so some serialization of all committed
    ops is consistent with real time. A CAS whose precondition fails at
    commit returns ``fail`` (it did not take effect), as in the etcd tests.
    With probability ``crash_p`` an op ends ``info`` instead of returning —
    whether or not it committed, exercising both crashed-op branches.
    """
    rng = random.Random(seed)
    gen_op, apply_op = _SIM_KINDS[kind]
    state: Dict[str, Any] = {"kind": kind, "values": values, "keys": keys,
                             "reg": None, "locked": None,
                             "map": {k: None for k in range(keys)}}
    # per-process: None = idle, else [op_f, op_value, committed, result]
    pending: List[Optional[list]] = [None] * processes
    history: List[Op] = []
    invoked = 0
    while invoked < n_ops or any(p is not None for p in pending):
        p = rng.randrange(processes)
        st = pending[p]
        if st is None:
            if invoked >= n_ops:
                continue
            f, v = gen_op(rng, state, p)
            if f is None:
                continue
            pending[p] = [f, v, False, None]
            history.append(invoke(p, f, v))
            invoked += 1
        elif not st[2]:
            if crash_p and rng.random() < crash_p:
                # crash before the op ever took effect
                history.append(info(p, st[0], st[1]))
                pending[p] = None
                continue
            # commit: apply atomically to the live object
            okay, result = apply_op(rng, state, p, st[0], st[1])
            st[2] = True
            st[3] = (okay, result)
        else:
            okay, result = st[3]
            if crash_p and rng.random() < crash_p:
                history.append(info(p, st[0], st[1]))
            elif okay:
                history.append(ok(p, st[0], result))
            else:
                history.append(fail(p, st[0], st[1]))
            pending[p] = None
    return [op.with_(index=i, time=i) for i, op in enumerate(history)]


def _gen_rw(rng, state, p) -> Tuple[Optional[str], Any]:
    if rng.random() < 0.5:
        return "read", None
    return "write", rng.randrange(state["values"])


def _apply_rw(rng, state, p, f, v):
    if f == "read":
        return True, state["reg"]
    state["reg"] = v
    return True, v


def _gen_cas(rng, state, p) -> Tuple[Optional[str], Any]:
    r = rng.random()
    if r < 0.34:
        return "read", None
    if r < 0.67:
        return "write", rng.randrange(state["values"])
    return "cas", [rng.randrange(state["values"]),
                   rng.randrange(state["values"])]


def _apply_cas(rng, state, p, f, v):
    if f == "cas":
        old, new = v
        if state["reg"] == old:
            state["reg"] = new
            return True, v
        return False, v
    return _apply_rw(rng, state, p, f, v)


def _gen_mutex(rng, state, p) -> Tuple[Optional[str], Any]:
    # a process alternates acquire/release attempts
    if state.get(("held", p)):
        return "release", None
    return "acquire", None


def _apply_mutex(rng, state, p, f, v):
    if f == "acquire":
        if state["locked"] is None:
            state["locked"] = p
            state[("held", p)] = True
            return True, None
        return False, None
    if state["locked"] == p:
        state["locked"] = None
        state[("held", p)] = False
        return True, None
    return False, None


def _gen_multi(rng, state, p) -> Tuple[Optional[str], Any]:
    k = rng.randrange(state["keys"])
    if rng.random() < 0.5:
        return "read", {k: None}
    return "write", {k: rng.randrange(state["values"])}


def _apply_multi(rng, state, p, f, v):
    if f == "read":
        return True, {k: state["map"][k] for k in v}
    state["map"].update(v)
    return True, v


_SIM_KINDS = {
    "register": (_gen_rw, _apply_rw),
    "cas": (_gen_cas, _apply_cas),
    "mutex": (_gen_mutex, _apply_mutex),
    "multi": (_gen_multi, _apply_multi),
}


class _LazyEntries:
    """Tuple-like view building :class:`jepsen_tpu.history.Entry`
    objects on demand — a 10M-op benchmark input must not materialize
    10M Python objects up front (entries are only touched for failure
    reporting, and benchmark histories are valid by construction)."""

    def __init__(self, inv_ev, ret_ev, op_id, proc, ops):
        self._inv, self._ret = inv_ev, ret_ev
        self._oid, self._proc, self._ops = op_id, proc, ops

    def __len__(self) -> int:
        return len(self._inv)

    def __getitem__(self, i: int):
        from jepsen_tpu.history import Entry
        tmpl = self._ops[int(self._oid[i])]
        op = tmpl.with_(process=int(self._proc[i]),
                        index=int(self._inv[i]), time=int(self._inv[i]))
        return Entry(eid=int(i), op=op, inv_ev=int(self._inv[i]),
                     ret_ev=int(self._ret[i]), crashed=False)


def gen_packed(kind: str = "cas", n_ops: int = 100, processes: int = 5,
               values: int = 5, seed: Optional[int] = None):
    """Vectorized benchmark-history generator: the same tick-loop
    simulation as :func:`gen_history` (register/cas kinds, no crashes)
    run in C++ (``native/preproc.cpp jt_gen_history``), emitting a
    :class:`~jepsen_tpu.history.PackedHistory` directly — a 10M-op
    input builds in <1 s instead of ~4 min of Python object churn.
    Linearizable by construction for the same reason (each op commits
    atomically between invocation and response; failed CAS attempts
    are stripped like the post-hoc analysis does). Falls back to
    ``pack(gen_history(...))`` when the native lib is unavailable.

    Note: for a given seed the history DIFFERS from ``gen_history``'s
    (different RNG) — same distribution, not same stream."""
    import numpy as np

    from jepsen_tpu import history as h
    from jepsen_tpu.checkers import preproc_native
    from jepsen_tpu.util import hashable

    kinds = {"register": 0, "cas": 1}
    if seed is None:
        # match gen_history(seed=None): fresh randomness per call (a
        # fixed fallback seed would silently return identical
        # histories from repeated seedless calls)
        import random as _random
        seed = _random.SystemRandom().randrange(1 << 31)
    native = (preproc_native.gen_history(
        seed, n_ops, processes, values,
        kinds[kind]) if kind in kinds else None)
    if native is None:
        return h.pack(gen_history(kind, n_ops=n_ops, processes=processes,
                                  values=values, seed=seed))
    inv_ev, ret_ev, opid_raw, proc, count = native
    order = np.argsort(inv_ev, kind="stable")  # entries by invocation
    inv_ev, ret_ev = inv_ev[order], ret_ev[order]
    opid_raw, proc = opid_raw[order], proc[order]
    # dense alphabet over the identities actually present
    V = values
    present, op_id = np.unique(opid_raw, return_inverse=True)
    ops = []
    for enc in present.tolist():
        if enc == 0:
            f, v = "read", None
        elif enc <= V:
            f, v = "read", enc - 1
        elif enc <= 2 * V:
            f, v = "write", enc - 1 - V
        else:
            a, b = divmod(enc - 1 - 2 * V, V)
            f, v = "cas", [a, b]
        ops.append(invoke(0, f, v))
    inf_ev = 2 * n_ops + 2          # > any event rank (2 per op max)
    entries = _LazyEntries(inv_ev, ret_ev, op_id.astype(np.int32), proc,
                           ops)
    return h.PackedHistory(
        n=count, inv_ev=inv_ev, ret_ev=ret_ev,
        op_id=np.ascontiguousarray(op_id, np.int32),
        crashed=np.zeros(count, bool), inf_ev=inf_ev,
        distinct_ops=tuple(ops), entries=entries,  # type: ignore[arg-type]
        op_keys=tuple((op.f, hashable(op.value)) for op in ops))


def gen_txn_history(n_txns: int = 50, keys: int = 3, processes: int = 5,
                    max_len: int = 4, read_p: float = 0.5,
                    crash_p: float = 0.0, key_rotate: int = 0,
                    seed: Optional[int] = None) -> List[Op]:
    """Generate a serializable-by-construction list-append txn history:
    the same tick simulation as :func:`gen_history`, committing each
    whole transaction atomically against live per-key lists at a random
    instant between invocation and response (so SOME serial order — the
    commit order — explains every read). Appends are per-key unique
    (Elle's traceability precondition). With ``crash_p`` a txn may end
    ``info``, committed or not — both crashed-op branches.

    ``key_rotate`` retires a key after that many appends and swaps in a
    fresh one (how real Jepsen list-append workloads bound list
    growth): without it every read copies an ever-growing list and a
    100k-txn history costs O(n^2) to build and to check. The bench
    rung uses rotation; small differential trials don't need it."""
    rng = random.Random(seed)
    key_names = [f"t{i}" for i in range(keys)]
    lists: Dict[str, list] = {k: [] for k in key_names}
    next_v: Dict[str, int] = {k: 0 for k in key_names}
    n_retired = 0

    def _maybe_rotate(k: str) -> None:
        nonlocal n_retired
        if key_rotate and len(lists[k]) >= key_rotate \
                and k in key_names:
            n_retired += 1
            fresh = f"t{keys + n_retired - 1}r"
            key_names[key_names.index(k)] = fresh
            lists[fresh] = []
            next_v[fresh] = 0
    pending: List[Optional[list]] = [None] * processes  # [micros, committed, result]
    history: List[Op] = []
    invoked = 0
    while invoked < n_txns or any(p is not None for p in pending):
        p = rng.randrange(processes)
        st = pending[p]
        if st is None:
            if invoked >= n_txns:
                continue
            micros = []
            for _ in range(rng.randint(1, max_len)):
                k = rng.choice(key_names)
                if rng.random() < read_p:
                    micros.append(["r", k, None])
                else:
                    micros.append(["append", k, next_v[k]])
                    next_v[k] += 1
            pending[p] = [micros, False, None]
            history.append(invoke(p, "txn", [list(x) for x in micros]))
            invoked += 1
        elif not st[1]:
            if crash_p and rng.random() < crash_p:
                history.append(info(p, "txn", st[0]))
                pending[p] = None
                continue
            # atomic commit: every micro-op against the live lists
            result = []
            for kind, k, v in st[0]:
                if kind == "append":
                    # a rotated-away key still commits (the txn chose
                    # it at invocation); its list just stops growing
                    # for future txns
                    lists[k].append(v)
                    result.append(["append", k, v])
                    _maybe_rotate(k)
                else:
                    result.append(["r", k, list(lists[k])])
            st[1] = True
            st[2] = result
        else:
            if crash_p and rng.random() < crash_p:
                history.append(info(p, "txn", st[0]))
            else:
                history.append(ok(p, "txn", st[2]))
            pending[p] = None
    return [op.with_(index=i, time=i) for i, op in enumerate(history)]


#: crafted list-append blocks with one known dependency cycle each
#: (fresh keys; timing-independent — the cycles come purely from the
#: read observations, which is all the inference consults)
TXN_ANOMALY_KINDS = ("G0", "G1c", "G-single", "G2")

#: lattice-level fixtures (ISSUE 17): each is invalid at a KNOWN
#: weakest level and valid at everything below it, with every txn
#: sequential (non-overlapping) so the commit-order lane is total —
#: the ground truths the lattice differential tests assert:
#:
#:   write-skew   -> weakest violated: si    (G-SIb; causal/pl-2 hold)
#:   lost-update  -> invalid at EVERY level  (G0 + G-SIa: the
#:                                            blind overwrite also
#:                                            reverses a write order)
#:   long-fork    -> weakest violated: si    (G-SIb + G2; the
#:                                            canonical SI anomaly)
#:   session-mr   -> weakest violated: pl-2  (monotonic-reads;
#:                                            causal holds)
TXN_LATTICE_KINDS = ("write-skew", "lost-update", "long-fork",
                     "session-mr")


def txn_anomaly_block(kind: str, key_prefix: str = "z",
                      process0: int = 100) -> List[Op]:
    """A self-contained txn block whose inferred graph contains
    exactly one cycle of class ``kind`` (sequential ops, fresh keys —
    append it to any history without disturbing it). The
    :data:`TXN_LATTICE_KINDS` kinds additionally pin the WEAKEST
    violated consistency level (see the table above)."""
    ka, kb = f"{key_prefix}a", f"{key_prefix}b"
    p = process0

    def seq(*txns, procs=None):
        out = []
        for i, t in enumerate(txns):
            pi = p + (i if procs is None else procs[i])
            out.append(invoke(pi, "txn",
                              [[k, kk, None if k == "r" else v]
                               for k, kk, v in t]))
            out.append(ok(pi, "txn", [list(x) for x in t]))
        return out

    if kind == "G0":
        # ww(ka): T1<T2 but ww(kb): T2<T1 — a pure write cycle
        return seq([("append", ka, 1), ("append", kb, 1)],
                   [("append", ka, 2), ("append", kb, 2)],
                   [("r", ka, [1, 2]), ("r", kb, [2, 1])])
    if kind == "G1c":
        # each txn reads the OTHER's append: wr both ways
        return seq([("append", ka, 1), ("r", kb, [1])],
                   [("r", ka, [1]), ("append", kb, 1)])
    if kind == "G-single":
        # T1 misses T2's append to ka (rw) but reads its kb append
        # (wr back): exactly one anti-dependency edge
        return seq([("r", ka, []), ("r", kb, [1])],
                   [("append", ka, 1), ("append", kb, 1)],
                   [("r", ka, [1])])
    if kind == "G2":
        # two anti-dependencies and nothing stronger
        return seq([("r", ka, []), ("append", kb, 1)],
                   [("r", kb, []), ("append", ka, 1)],
                   [("r", ka, [1]), ("r", kb, [1])])
    if kind == "write-skew":
        # the classic skew, SEQUENTIALLY: T2 starts after T1
        # committed yet still reads ka=[] — fine under causal (no
        # ww/wr cycle), a G-SIb write skew under strong-session SI
        # (rw T2->T1 closed by the commit-order edge T1->T2)
        return seq([("r", ka, []), ("r", kb, []), ("append", ka, 1)],
                   [("r", ka, []), ("r", kb, []), ("append", kb, 1)],
                   [("r", ka, [1]), ("r", kb, [1])])
    if kind == "lost-update":
        # T2 read-modify-writes over T1 without seeing T1's committed
        # append, and the recovered kb order runs BACKWARD through
        # commit order: a G0 write cycle (fails read-committed, hence
        # every level) plus the time-travel G-SIa edge ww T2->T1 with
        # T1 committed before T2 even started
        return seq([("r", ka, []), ("append", ka, 1),
                    ("append", kb, 1)],
                   [("r", ka, []), ("append", ka, 2),
                    ("append", kb, 2)],
                   [("r", ka, [1, 2]), ("r", kb, [2, 1])])
    if kind == "long-fork":
        # two readers observe the two independent writes in OPPOSITE
        # orders — the canonical SI anomaly. No ww/wr cycle (causal
        # holds); both rw edges close through commit order (G-SIb),
        # and the four-txn rw/wr cycle is a G2 under serializability.
        return seq([("append", ka, 1)],
                   [("append", kb, 1)],
                   [("r", ka, [1]), ("r", kb, [])],
                   [("r", ka, []), ("r", kb, [1])])
    if kind == "session-mr":
        # one process's reads SHRINK: txn2 sees [1,2], txn3 (same
        # process) sees [1] — a monotonic-reads session violation
        # (weakest violated: pl-2; causal still holds, there is no
        # ww/wr cycle)
        return seq([("append", ka, 1), ("append", ka, 2)],
                   [("r", ka, [1, 2])],
                   [("r", ka, [1])],
                   procs=[0, 1, 1])
    raise ValueError(f"unknown txn anomaly kind {kind!r}")


def model_for(kind: str) -> m.Model:
    return {
        "register": m.register(),
        "cas": m.cas_register(),
        "mutex": m.mutex(),
        "multi": m.multi_register(),
    }[kind]


def corrupt(history: List[Op], seed: Optional[int] = None,
            bad_value: Any = 999_999) -> List[Op]:
    """Make a history non-linearizable: rewrite one successful read's
    observed value to a value no write ever produced. For register-family
    models such a read can never be linearized, so the result is provably
    invalid."""
    rng = random.Random(seed)
    idxs = [i for i, op in enumerate(history)
            if op.type == "ok" and op.f == "read"]
    if not idxs:
        raise ValueError("history has no successful reads to corrupt")
    i = rng.choice(idxs)
    out = list(history)
    victim = out[i]
    bad = (dict.fromkeys(victim.value, bad_value)
           if isinstance(victim.value, dict) else bad_value)
    out[i] = victim.with_(value=bad)
    return out
