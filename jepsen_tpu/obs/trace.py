"""Export side of :mod:`jepsen_tpu.obs`: Chrome/Perfetto ``trace.json``
(the ``trace_event`` format — load in ``chrome://tracing`` or
https://ui.perfetto.dev), a line-oriented ``obs.jsonl`` (one record per
span/counter/gauge/decision, grep- and stream-friendly), and the
``snapshot()`` sub-object :mod:`bench` embeds in its output JSON.

``tools/trace_view.py`` parses both formats back (top spans by
self-time, the fallback table); :func:`load_any` is the shared reader.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

from jepsen_tpu.obs.core import GLOBAL, Capture, Recorder


def _recorder_of(source: Optional[Any]) -> Recorder:
    if source is None:
        return GLOBAL
    if isinstance(source, Capture):
        return source._rec
    return source


def trace_events(source: Optional[Any] = None) -> List[Dict[str, Any]]:
    """The Chrome ``traceEvents`` list: every recorded span as a ``"X"``
    (complete) event, plus one metadata event naming the process."""
    rec = _recorder_of(source)
    meta = {"name": "process_name", "ph": "M", "pid": os.getpid(),
            "tid": 0, "args": {"name": "jepsen-tpu"}}
    return [meta] + rec.span_events()


def export_trace(path: str, source: Optional[Any] = None) -> str:
    """Write a Chrome/Perfetto ``trace_event`` JSON file."""
    data = {"traceEvents": trace_events(source),
            "displayTimeUnit": "ms"}
    with open(path, "w") as f:
        json.dump(data, f, default=str)
    return path


def export_jsonl(path: str, source: Optional[Any] = None) -> str:
    """Write ``obs.jsonl``: one JSON object per line, each tagged with a
    ``"type"`` of ``span`` / ``counter`` / ``gauge`` / ``decision``."""
    rec = _recorder_of(source)
    snap = rec.snapshot()
    with open(path, "w") as f:
        for name, value in sorted(snap["counters"].items()):
            f.write(json.dumps({"type": "counter", "name": name,
                                "value": value}, default=str) + "\n")
        for name, value in sorted(snap["gauges"].items()):
            f.write(json.dumps({"type": "gauge", "name": name,
                                "value": value}, default=str) + "\n")
        for r in snap["ledger"]:
            f.write(json.dumps({"type": "decision", **r},
                               default=str) + "\n")
        for e in rec.span_events():
            f.write(json.dumps({"type": "span", **e},
                               default=str) + "\n")
    return path


def snapshot(source: Optional[Any] = None) -> Dict[str, Any]:
    """JSON-serializable counters + gauges + engine ledger + span count
    — the ``"obs"`` sub-object of ``bench.py`` output and of run
    ``results``."""
    rec = _recorder_of(source)
    out = rec.snapshot()
    out["span-count"] = len(rec.spans)
    return out


def load_any(path: str) -> Dict[str, List[Dict[str, Any]]]:
    """Read a ``trace.json`` OR an ``obs.jsonl`` back into
    ``{"spans": [...], "decisions": [...], "counters": [...],
    "gauges": [...]}`` — the shared parser behind
    ``tools/trace_view.py``."""
    out: Dict[str, List[Dict[str, Any]]] = {
        "spans": [], "decisions": [], "counters": [], "gauges": []}
    with open(path) as f:
        head = f.read(1)
        f.seek(0)
        if head == "{":
            try:
                data = json.load(f)
            except json.JSONDecodeError:
                data = None
            if isinstance(data, dict) and "traceEvents" in data:
                out["spans"] = [e for e in data["traceEvents"]
                                if e.get("ph") == "X"]
                return out
            f.seek(0)
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            kind = rec.pop("type", "span")
            if kind == "span":
                out["spans"].append(rec)
            elif kind == "decision":
                out["decisions"].append(rec)
            elif kind == "counter":
                out["counters"].append(rec)
            elif kind == "gauge":
                out["gauges"].append(rec)
    return out
