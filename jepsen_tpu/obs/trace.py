"""Export side of :mod:`jepsen_tpu.obs`: Chrome/Perfetto ``trace.json``
(the ``trace_event`` format — load in ``chrome://tracing`` or
https://ui.perfetto.dev), a line-oriented ``obs.jsonl`` (one record per
span/counter/gauge/decision, grep- and stream-friendly), and the
``snapshot()`` sub-object :mod:`bench` embeds in its output JSON.

``tools/trace_view.py`` parses both formats back (top spans by
self-time, the fallback table); :func:`load_any` is the shared reader.
"""
from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, List, Optional, Tuple

from jepsen_tpu.obs.core import GLOBAL, HIST_EDGES, Capture, Recorder


def _recorder_of(source: Optional[Any]) -> Recorder:
    if source is None:
        return GLOBAL
    if isinstance(source, Capture):
        return source._rec
    return source


def trace_events(source: Optional[Any] = None) -> List[Dict[str, Any]]:
    """The Chrome ``traceEvents`` list: every recorded span as a ``"X"``
    (complete) event, plus one metadata event naming the process."""
    rec = _recorder_of(source)
    meta = {"name": "process_name", "ph": "M", "pid": os.getpid(),
            "tid": 0, "args": {"name": "jepsen-tpu"}}
    return [meta] + rec.span_events()


def export_trace(path: str, source: Optional[Any] = None) -> str:
    """Write a Chrome/Perfetto ``trace_event`` JSON file."""
    data = {"traceEvents": trace_events(source),
            "displayTimeUnit": "ms"}
    with open(path, "w") as f:
        json.dump(data, f, default=str)
    return path


def export_jsonl(path: str, source: Optional[Any] = None) -> str:
    """Write ``obs.jsonl``: one JSON object per line, each tagged with a
    ``"type"`` of ``span`` / ``counter`` / ``gauge`` / ``histogram`` /
    ``decision``."""
    rec = _recorder_of(source)
    snap = rec.snapshot()
    with open(path, "w") as f:
        for name, value in sorted(snap["counters"].items()):
            f.write(json.dumps({"type": "counter", "name": name,
                                "value": value}, default=str) + "\n")
        for name, value in sorted(snap["gauges"].items()):
            f.write(json.dumps({"type": "gauge", "name": name,
                                "value": value}, default=str) + "\n")
        for name, h in sorted(snap.get("histograms", {}).items()):
            f.write(json.dumps({"type": "histogram", "name": name,
                                **h}, default=str) + "\n")
        for r in snap["ledger"]:
            f.write(json.dumps({"type": "decision", **r},
                               default=str) + "\n")
        for e in rec.span_events():
            f.write(json.dumps({"type": "span", **e},
                               default=str) + "\n")
    return path


def snapshot(source: Optional[Any] = None) -> Dict[str, Any]:
    """JSON-serializable counters + gauges + engine ledger + span count
    — the ``"obs"`` sub-object of ``bench.py`` output and of run
    ``results``."""
    rec = _recorder_of(source)
    out = rec.snapshot()
    out["span-count"] = len(rec.spans)
    return out


def load_any(path: str) -> Dict[str, List[Dict[str, Any]]]:
    """Read a ``trace.json`` OR an ``obs.jsonl`` back into
    ``{"spans": [...], "decisions": [...], "counters": [...],
    "gauges": [...]}`` — the shared parser behind
    ``tools/trace_view.py``."""
    out: Dict[str, List[Dict[str, Any]]] = {
        "spans": [], "decisions": [], "counters": [], "gauges": [],
        "histograms": []}
    with open(path) as f:
        head = f.read(1)
        f.seek(0)
        if head == "{":
            try:
                data = json.load(f)
            except json.JSONDecodeError:
                data = None
            if isinstance(data, dict) and "traceEvents" in data:
                out["spans"] = [e for e in data["traceEvents"]
                                if e.get("ph") == "X"]
                return out
            f.seek(0)
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            kind = rec.pop("type", "span")
            if kind == "span":
                out["spans"].append(rec)
            elif kind == "decision":
                out["decisions"].append(rec)
            elif kind == "counter":
                out["counters"].append(rec)
            elif kind == "gauge":
                out["gauges"].append(rec)
            elif kind == "histogram":
                out["histograms"].append(rec)
    return out


# -- Prometheus text exposition ------------------------------------------

_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")
_PROM_LINE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)$")
_PROM_LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="([^"]*)"')


def _prom_name(name: str) -> str:
    s = _PROM_BAD.sub("_", str(name))
    if not s or s[0].isdigit():
        s = "_" + s
    return "jepsen_" + s


def _prom_val(v: Any) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def prometheus_text(source: Optional[Any] = None) -> str:
    """Prometheus text exposition (format 0.0.4) of every counter,
    numeric gauge, and histogram in the recorder — the body of the
    daemon's ``GET /metrics``. Histograms emit the full fixed bucket
    ladder every scrape (plus ``+Inf``/``_sum``/``_count``), so two
    scrapes always difference bucket-by-bucket.

    Two classes of name are withheld: per-tenant counters
    (``serve.tenant.<t>.*`` — tenant names are client-controlled, so
    they are both unbounded cardinality and sanitization-collision
    bait; ``GET /stats`` carries the per-tenant view), and any name
    whose sanitized form collides with an already-emitted one (a
    duplicate series makes strict scrapers reject the WHOLE
    exposition; dropped names are counted in
    ``jepsen_obs_prom_collisions`` so the gap is visible)."""
    rec = _recorder_of(source)
    snap = rec.snapshot()
    lines: List[str] = []
    emitted: Dict[str, str] = {}
    collisions = 0

    def _uniq(raw: str) -> Optional[str]:
        nonlocal collisions
        s = _prom_name(raw)
        prev = emitted.get(s)
        if prev is None:
            emitted[s] = raw
            return s
        if prev == raw:
            return s
        collisions += 1
        return None

    for name, value in sorted(snap["counters"].items()):
        if name.startswith("serve.tenant."):
            continue
        n = _uniq(name)
        if n is None:
            continue
        lines.append(f"# TYPE {n} counter")
        lines.append(f"{n} {_prom_val(value)}")
    for name, value in sorted(snap["gauges"].items()):
        if isinstance(value, bool) or not isinstance(value,
                                                     (int, float)):
            continue                    # modes/dicts stay JSON-side
        n = _uniq(name)
        if n is None:
            continue
        lines.append(f"# TYPE {n} gauge")
        lines.append(f"{n} {_prom_val(value)}")
    for name, h in sorted(snap.get("histograms", {}).items()):
        n = _uniq(name)
        if n is None:
            continue
        lines.append(f"# TYPE {n} histogram")
        cum = 0
        for edge, c in zip(HIST_EDGES, h["counts"]):
            cum += c
            lines.append(f'{n}_bucket{{le="{edge:g}"}} {cum}')
        cum += h["counts"][len(HIST_EDGES)]
        lines.append(f'{n}_bucket{{le="+Inf"}} {cum}')
        lines.append(f"{n}_sum {_prom_val(h['sum'])}")
        lines.append(f"{n}_count {h['count']}")
    if collisions:
        lines.append("# TYPE jepsen_obs_prom_collisions gauge")
        lines.append(f"jepsen_obs_prom_collisions {collisions}")
    return "\n".join(lines) + "\n"


def parse_prometheus(text: str
                     ) -> Dict[str, List[Tuple[Dict[str, str],
                                               float]]]:
    """Parse a text exposition back into
    ``{metric_name: [(labels, value), ...]}``. Raises ValueError on a
    malformed sample line — the exposition-format test and loadgen's
    cross-check both parse with this."""
    out: Dict[str, List[Tuple[Dict[str, str], float]]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _PROM_LINE.match(line)
        if m is None:
            raise ValueError(f"malformed exposition line: {line!r}")
        labels = dict(_PROM_LABEL.findall(m.group(2) or ""))
        out.setdefault(m.group(1), []).append(
            (labels, float(m.group(3))))
    return out
