"""``jepsen_tpu.obs`` — observability for the whole checker pipeline
(ISSUE 2 tentpole): a thread-safe span tracer with Chrome/Perfetto
``trace_event`` export, a process-wide counters/gauges registry, and
the **engine-decision ledger** — every auto-chain stage transition and
every silent-degradation point (``check_safe`` swallows, lockstep →
per-key fallbacks, Pallas → XLA downgrades) appends a structured
record, retrievable via :func:`capture` so tests and ``tools/fuzz.py``
can assert "no silent fallback occurred".

Quick tour::

    from jepsen_tpu import obs

    with obs.span("phase", detail=1):        # nestable, thread-safe
        obs.count("cache.hits")              # process-wide counter
        obs.decision("reach", "selected")    # ledger record

    with obs.capture() as cap:               # isolated assertion scope
        run_check()
    assert cap.fallbacks() == []

    obs.export_trace("trace.json")           # chrome://tracing
    obs.export_jsonl("obs.jsonl")            # stream/grep-friendly

Set ``JEPSEN_TPU_NO_OBS=1`` to disable all recording. See
``docs/OBSERVABILITY.md`` for the full API, the counter taxonomy, and
the trace-viewer workflow.
"""
from jepsen_tpu.obs.core import (HIST_EDGES, Capture, Recorder,
                                 capture, checker_swallowed, count,
                                 counters, decision, enabled,
                                 engine_fallback, engine_selected,
                                 gauge, gauges, hist_delta, hist_merge,
                                 hist_quantile, hist_summary,
                                 histogram, histograms,
                                 quantile_from_cumulative, reset, span)
from jepsen_tpu.obs.trace import (export_jsonl, export_trace, load_any,
                                  parse_prometheus, prometheus_text,
                                  snapshot, trace_events)

__all__ = [
    "HIST_EDGES", "Capture", "Recorder", "capture",
    "checker_swallowed", "count", "counters", "decision", "enabled",
    "engine_fallback", "engine_selected", "gauge", "gauges",
    "hist_delta", "hist_merge", "hist_quantile", "hist_summary",
    "histogram", "histograms", "quantile_from_cumulative", "reset",
    "span", "export_jsonl", "export_trace", "load_any",
    "parse_prometheus", "prometheus_text", "snapshot", "trace_events",
]
