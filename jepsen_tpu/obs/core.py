"""Recorder core of the :mod:`jepsen_tpu.obs` subsystem: a thread-safe,
low-overhead span tracer, a process-wide counters/gauges registry, and
the engine-decision ledger.

Design constraints (ISSUE 2):

- **Cheap enough for hot-ish paths.** One span costs two
  ``time.perf_counter()`` reads, one small dict build, and one
  lock-guarded list append per active sink — single-digit microseconds.
  Instrumentation sits at phase/engine granularity (per check, per
  dispatch group, per run phase), never per history event, so tracer
  overhead on the 100k-op bench rung is bounded by a handful of events
  (asserted under 2% of ``check_s`` in ``tests/test_obs.py``).
  ``JEPSEN_TPU_NO_OBS=1`` disables recording entirely.
- **Thread-safe.** ``core.run`` records from worker threads and the
  competition facade races engines on threads; every recorder mutation
  is lock-guarded and span state lives on the stack (the context
  manager object), not in thread-local registries.
- **Capture isolation.** :func:`capture` registers an extra sink on a
  ``contextvars.ContextVar`` — concurrent captures on different
  threads never see each other's events, while threads *spawned inside*
  a capture can opt in by running under ``contextvars.copy_context()``
  (``core.run`` does this for its workers). Events always also reach
  the process-global recorder, which :mod:`jepsen_tpu.obs.trace`
  exports.
- **Bounded.** Span and ledger stores are capped; drops are themselves
  counted (``obs.dropped.spans`` / ``obs.dropped.ledger``) so a capped
  export is never mistaken for a complete one.
"""
from __future__ import annotations

import bisect
import contextvars
import math
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

_ENABLED = not os.environ.get("JEPSEN_TPU_NO_OBS")

# one process-wide monotonic origin so span timestamps from every
# thread land on one comparable axis (Chrome traces sort by ts)
_T0 = time.perf_counter()

_MAX_SPANS = 100_000
_MAX_LEDGER = 10_000

# Fixed log-spaced histogram buckets shared by EVERY histogram: ten
# buckets per decade (ratio 10^0.1 ~ 1.26) from 1 µs to 1000 s. One
# fixed layout means snapshots merge/difference bucket-by-bucket
# (loadgen's /metrics-delta quantile cross-check depends on that) and
# the quantile interpolation error stays well under the 15% the
# cross-check allows. Values past the last edge land in a +Inf
# overflow bucket; the recorded sum keeps the mean exact regardless.
HIST_EDGES: Tuple[float, ...] = tuple(
    round(10.0 ** (k / 10.0), 12) for k in range(-60, 31))


def _now_us() -> float:
    return (time.perf_counter() - _T0) * 1e6


class Recorder:
    """One sink of spans, counters, gauges, and ledger records. The
    process-global instance backs :func:`jepsen_tpu.obs.trace.export_*`;
    additional instances are created per :func:`capture`."""

    __slots__ = ("_lock", "spans", "counters", "gauges", "ledger",
                 "hists")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.spans: List[Dict[str, Any]] = []
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, Any] = {}
        self.ledger: List[Dict[str, Any]] = []
        self.hists: Dict[str, Dict[str, Any]] = {}

    # -- mutation (all lock-guarded) ------------------------------------
    def add_span(self, ev: Dict[str, Any]) -> None:
        with self._lock:
            if len(self.spans) >= _MAX_SPANS:
                self.counters["obs.dropped.spans"] = \
                    self.counters.get("obs.dropped.spans", 0) + 1
                return
            self.spans.append(ev)

    def count(self, name: str, n: float = 1) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + n

    def gauge(self, name: str, value: Any) -> None:
        with self._lock:
            self.gauges[name] = value

    def observe(self, name: str, value: float) -> None:
        """One histogram observation. ``le`` semantics (Prometheus):
        bucket ``i`` counts values ``<= HIST_EDGES[i]``; the trailing
        slot is the +Inf overflow bucket."""
        with self._lock:
            h = self.hists.get(name)
            if h is None:
                h = self.hists[name] = {
                    "count": 0, "sum": 0.0,
                    "counts": [0] * (len(HIST_EDGES) + 1)}
            h["counts"][bisect.bisect_left(HIST_EDGES, value)] += 1
            h["count"] += 1
            h["sum"] += value

    def decide(self, rec: Dict[str, Any]) -> None:
        with self._lock:
            if len(self.ledger) >= _MAX_LEDGER:
                self.counters["obs.dropped.ledger"] = \
                    self.counters.get("obs.dropped.ledger", 0) + 1
                return
            self.ledger.append(rec)

    # -- read side ------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """Consistent copy of counters, gauges, histograms, and the
        ledger (spans are exported separately — they can be large)."""
        with self._lock:
            return {"counters": dict(self.counters),
                    "gauges": dict(self.gauges),
                    "histograms": {k: {"count": h["count"],
                                       "sum": h["sum"],
                                       "counts": list(h["counts"])}
                                   for k, h in self.hists.items()},
                    "ledger": [dict(r) for r in self.ledger]}

    def span_events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(e) for e in self.spans]

    def clear(self) -> None:
        with self._lock:
            self.spans.clear()
            self.counters.clear()
            self.gauges.clear()
            self.ledger.clear()
            self.hists.clear()


GLOBAL = Recorder()

# extra sinks registered by capture(); a ContextVar (not a thread-local)
# so captures nest and explicit contextvars.copy_context() propagation
# into worker threads works, while unrelated threads stay isolated.
_CAPTURES: "contextvars.ContextVar[Tuple[Recorder, ...]]" = \
    contextvars.ContextVar("jepsen_tpu_obs_captures", default=())


def _sinks() -> Tuple[Recorder, ...]:
    caps = _CAPTURES.get()
    return (GLOBAL,) + caps if caps else (GLOBAL,)


def enabled() -> bool:
    return _ENABLED


# -- spans ---------------------------------------------------------------

class Span:
    """Context manager recording one Chrome-trace ``"X"`` (complete)
    event on exit. ``set(key, value)`` adds args mid-flight (e.g. the
    engine a check ultimately selected)."""

    __slots__ = ("name", "cat", "args", "_ts")

    def __init__(self, name: str, cat: str = "",
                 args: Optional[Dict[str, Any]] = None):
        self.name = name
        self.cat = cat
        self.args = args

    def set(self, key: str, value: Any) -> None:
        if self.args is None:
            self.args = {}
        self.args[key] = value

    def __enter__(self) -> "Span":
        self._ts = _now_us()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        end = _now_us()
        ev: Dict[str, Any] = {
            "name": self.name, "ph": "X", "ts": self._ts,
            "dur": end - self._ts, "pid": os.getpid(),
            "tid": threading.get_ident(),
        }
        if self.cat:
            ev["cat"] = self.cat
        if self.args:
            ev["args"] = self.args
        if exc_type is not None:
            ev.setdefault("args", {})["error"] = exc_type.__name__
        for s in _sinks():
            s.add_span(ev)


class _NullSpan:
    __slots__ = ()

    def set(self, key: str, value: Any) -> None:
        pass

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        pass


_NULL_SPAN = _NullSpan()


def span(name: str, cat: str = "", **args: Any):
    """``with obs.span("reach.walk", engine="reach-lockstep"): ...`` —
    nestable, thread-safe; exported as a Chrome/Perfetto trace event."""
    if not _ENABLED:
        return _NULL_SPAN
    return Span(name, cat, args or None)


# -- counters / gauges ---------------------------------------------------

def count(name: str, n: float = 1) -> None:
    """Bump a process-wide (and any captured) counter."""
    if not _ENABLED:
        return
    for s in _sinks():
        s.count(name, n)


def gauge(name: str, value: Any) -> None:
    """Set a last-value-wins gauge (e.g. kernel-cache hit counts)."""
    if not _ENABLED:
        return
    for s in _sinks():
        s.gauge(name, value)


def counters() -> Dict[str, float]:
    """Snapshot of the process-global counters."""
    return GLOBAL.snapshot()["counters"]


def gauges() -> Dict[str, Any]:
    """Snapshot of the process-global gauges (e.g. the streaming
    pipeline's ``prep.wall_s`` / ``prep.hidden_s`` overlap figures)."""
    return GLOBAL.snapshot()["gauges"]


# -- histograms ----------------------------------------------------------

def histogram(name: str, value: float) -> None:
    """Observe ``value`` into the fixed log-spaced histogram ``name``
    (process-wide and any captures). The serving layer feeds these
    with per-request queue-wait / service-time / end-to-end latency
    and per-dispatch-group kernel wall; ``GET /metrics`` exposes them
    as Prometheus ``_bucket``/``_sum``/``_count`` series."""
    if not _ENABLED:
        return
    for s in _sinks():
        s.observe(name, value)


def histograms() -> Dict[str, Dict[str, Any]]:
    """Snapshot of the process-global histograms:
    ``{name: {"count", "sum", "counts"}}`` with ``counts`` the raw
    per-bucket tallies aligned to :data:`HIST_EDGES` plus one +Inf
    overflow slot."""
    return GLOBAL.snapshot()["histograms"]


def hist_merge(a: Optional[Dict[str, Any]],
               b: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """Bucket-wise sum of two histogram snapshots (same fixed bucket
    layout, so merging is elementwise)."""
    if a is None or b is None:
        src = a or b or {"count": 0, "sum": 0.0,
                         "counts": [0] * (len(HIST_EDGES) + 1)}
        return {"count": src["count"], "sum": src["sum"],
                "counts": list(src["counts"])}
    return {"count": a["count"] + b["count"],
            "sum": a["sum"] + b["sum"],
            "counts": [x + y for x, y in zip(a["counts"],
                                             b["counts"])]}


def hist_delta(after: Optional[Dict[str, Any]],
               before: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """``after - before`` bucket-wise: the distribution of the
    observations that happened BETWEEN two snapshots of a cumulative
    histogram (both loadgen's /metrics cross-check and the daemon's
    time-series ring difference snapshots this way). Negative cells
    (a reset between snapshots) clamp to zero."""
    if after is None:
        return {"count": 0, "sum": 0.0,
                "counts": [0] * (len(HIST_EDGES) + 1)}
    if before is None:
        return {"count": after["count"], "sum": after["sum"],
                "counts": list(after["counts"])}
    counts = [max(0, x - y) for x, y in zip(after["counts"],
                                            before["counts"])]
    return {"count": sum(counts),
            "sum": max(0.0, after["sum"] - before["sum"]),
            "counts": counts}


def hist_quantile(h: Optional[Dict[str, Any]],
                  q: float) -> Optional[float]:
    """Estimate the ``q``-quantile (0..1) of a histogram snapshot by
    linear interpolation within the bucket holding the target rank.
    None for an empty histogram. The overflow bucket reports the last
    edge (a floor — the true value is larger)."""
    if not h or not h.get("count"):
        return None
    counts = h["counts"]
    target = q * h["count"]
    acc = 0.0
    for i, c in enumerate(counts):
        if not c:
            continue
        acc += c
        if acc >= target:
            if i >= len(HIST_EDGES):            # +Inf overflow
                return HIST_EDGES[-1]
            lo = HIST_EDGES[i - 1] if i > 0 else 0.0
            hi = HIST_EDGES[i]
            frac = (target - (acc - c)) / c
            return lo + (hi - lo) * min(1.0, max(0.0, frac))
    return HIST_EDGES[-1]


def quantile_from_cumulative(pairs: List[Tuple[float, float]],
                             q: float) -> Optional[float]:
    """Quantile from Prometheus-style CUMULATIVE buckets:
    ``pairs = [(le, cumulative_count), ...]`` (any order; +Inf
    allowed). This is the parse-side twin of :func:`hist_quantile` —
    loadgen feeds it the bucket DELTAS of two /metrics scrapes."""
    pairs = sorted((float(le), float(v)) for le, v in pairs)
    if not pairs:
        return None
    total = pairs[-1][1]
    if total <= 0:
        return None
    target = q * total
    prev_le, prev_cum = 0.0, 0.0
    for le, cum in pairs:
        if cum >= target:
            if math.isinf(le):
                return prev_le if prev_le > 0 else None
            width = cum - prev_cum
            frac = ((target - prev_cum) / width) if width > 0 else 1.0
            lo = prev_le if not math.isinf(prev_le) else 0.0
            return lo + (le - lo) * min(1.0, max(0.0, frac))
        prev_le, prev_cum = le, cum
    return pairs[-1][0] if not math.isinf(pairs[-1][0]) else None


def hist_summary(h: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """Headline digest of one histogram snapshot — the shape
    ``bench.py --serve`` and the ``/engine`` dashboard embed."""
    if not h or not h.get("count"):
        return {"count": 0}
    n = h["count"]
    out = {"count": int(n), "sum": round(h["sum"], 6),
           "mean": round(h["sum"] / n, 6)}
    for label, q in (("p50", 0.50), ("p90", 0.90), ("p99", 0.99)):
        v = hist_quantile(h, q)
        out[label] = round(v, 6) if v is not None else None
    return out


# -- engine-decision ledger ---------------------------------------------

def decision(stage: str, event: str, cause: Optional[str] = None,
             **fields: Any) -> None:
    """Append a structured record to the engine-decision ledger:
    ``stage`` (engine or pipeline stage), ``event`` (``"selected"`` /
    ``"fallback"`` / ``"swallowed"`` / ``"route"``), optional ``cause``
    (exception class or reason), plus free-form fields (history
    geometry, elapsed seconds)."""
    if not _ENABLED:
        return
    rec: Dict[str, Any] = {"ts": round(_now_us()), "stage": stage,
                           "event": event}
    if cause is not None:
        rec["cause"] = cause
    rec.update(fields)
    for s in _sinks():
        s.decide(rec)


def engine_selected(stage: str, **fields: Any) -> None:
    """An engine produced the conclusive verdict for a check. Bumps
    ``engine.selected.<stage>`` and appends a ledger record."""
    count(f"engine.selected.{stage}")
    decision(stage, "selected", **fields)


def engine_fallback(stage: str, cause: str, **fields: Any) -> None:
    """A stage was abandoned and the chain moved on. Bumps
    ``engine.fallback.<stage>.<cause>`` (fallback causes keyed by
    exception class and stage) and appends a ledger record."""
    count(f"engine.fallback.{stage}.{cause}")
    decision(stage, "fallback", cause=cause, **fields)


def checker_swallowed(stage: str, cause: str, **fields: Any) -> None:
    """``check_safe`` turned a checker crash into ``"unknown"`` — the
    crash is preserved here (and in the result's ``"traceback"``) so it
    is never silent."""
    count(f"checker.swallowed.{stage}.{cause}")
    decision(stage, "swallowed", cause=cause, **fields)


# -- capture -------------------------------------------------------------

class Capture:
    """Events recorded while a :func:`capture` context is active, plus
    assertion helpers for tests (``selections()`` / ``fallbacks()`` /
    ``swallowed()``)."""

    def __init__(self) -> None:
        self._rec = Recorder()

    # field-specific locked copies — a counters read must not copy a
    # ledger sitting at its 10k cap
    @property
    def spans(self) -> List[Dict[str, Any]]:
        return self._rec.span_events()

    @property
    def counters(self) -> Dict[str, float]:
        with self._rec._lock:
            return dict(self._rec.counters)

    @property
    def gauges(self) -> Dict[str, Any]:
        with self._rec._lock:
            return dict(self._rec.gauges)

    @property
    def histograms(self) -> Dict[str, Dict[str, Any]]:
        with self._rec._lock:
            return {k: {"count": h["count"], "sum": h["sum"],
                        "counts": list(h["counts"])}
                    for k, h in self._rec.hists.items()}

    @property
    def ledger(self) -> List[Dict[str, Any]]:
        with self._rec._lock:
            return [dict(r) for r in self._rec.ledger]

    def _by_event(self, event: str) -> List[Dict[str, Any]]:
        return [r for r in self.ledger if r.get("event") == event]

    def selections(self) -> List[Dict[str, Any]]:
        return self._by_event("selected")

    def fallbacks(self) -> List[Dict[str, Any]]:
        return self._by_event("fallback")

    def swallowed(self) -> List[Dict[str, Any]]:
        return self._by_event("swallowed")

    def summary(self) -> Dict[str, Any]:
        """JSON-serializable counters + gauges + ledger (no spans)."""
        return self._rec.snapshot()


class _CaptureCtx:
    __slots__ = ("_cap", "_token")

    def __init__(self) -> None:
        self._cap = Capture()

    def __enter__(self) -> Capture:
        self._token = _CAPTURES.set(_CAPTURES.get() + (self._cap._rec,))
        return self._cap

    def __exit__(self, exc_type, exc, tb) -> None:
        _CAPTURES.reset(self._token)


def capture() -> _CaptureCtx:
    """``with obs.capture() as cap:`` — everything recorded in this
    context (same thread, or threads run under a copied
    ``contextvars`` context) is ALSO collected into ``cap``, isolated
    from concurrent captures on other threads. Recording into the
    process-global recorder is unaffected."""
    return _CaptureCtx()


def reset() -> None:
    """Clear the process-global recorder (tests and long-lived tools)."""
    GLOBAL.clear()
