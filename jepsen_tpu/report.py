"""Report helpers — upstream ``jepsen/src/jepsen/report.clj``: spit an
analysis to a file alongside the run.
"""
from __future__ import annotations

import json
import os
from typing import Any, Mapping


def to(path: str, results: Mapping[str, Any]) -> str:
    """Write ``results`` (JSON) to ``path``, creating parents (upstream
    ``report/to``)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        json.dump(results, f, indent=2, default=str)
    return path
