"""Key-independent workloads — upstream ``jepsen/src/jepsen/independent.clj``
(SURVEY.md §2.1, §3.5): lift a single-key workload/checker over N independent
keys. Op values become ``[key, subvalue]`` tuples; the checker splits the
history per key, runs the inner checker on each sub-history, and merges.

TPU-first difference: per-key sub-histories are an *embarrassingly parallel
batch dimension* (SURVEY.md §2.4). When the inner checker is
``linearizable``, all keys that fit the dense engine are checked through
the batched device engines (:func:`jepsen_tpu.checkers.reach.check_many`
— by default the bucketed LOCKSTEP lane, where groups of keys advance
through the walk together, one return index per step, with
length-bucketed lane packing so a long key never pads the short ones) —
the upstream runs per-key Knossos analyses on a thread pool.

Generator-side combinators (``sequential_generator``,
``concurrent_generator``) live in :mod:`jepsen_tpu.generators`.
"""
from __future__ import annotations

from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

from jepsen_tpu import history as h
from jepsen_tpu.checkers.facade import Checker, Linearizable, check_safe
from jepsen_tpu.op import Op
from jepsen_tpu.util import hashable


def ktuple(key: Any, value: Any) -> List[Any]:
    """An independent op value ``[key, subvalue]`` (upstream
    ``jepsen.independent/tuple``)."""
    return [key, value]


def is_ktuple(value: Any) -> bool:
    return isinstance(value, (list, tuple)) and len(value) == 2


def split_history(history: Sequence[Op]) -> Dict[Any, List[Op]]:
    """Group ops by key, unwrapping ``[key, subvalue]`` values. Ops without
    tuple values (e.g. nemesis) are dropped, as upstream."""
    out: Dict[Any, List[Op]] = {}
    for op in history:
        if op.process == "nemesis" or not is_ktuple(op.value):
            continue
        k, v = op.value
        out.setdefault(hashable(k), []).append(op.with_(value=v))
    return {k: h.index(ops) for k, ops in out.items()}


class IndependentChecker(Checker):
    """Apply ``inner`` to each key's sub-history; valid iff every key is
    (upstream ``jepsen.independent/checker``)."""
    name = "independent"

    def __init__(self, inner: Checker):
        self.inner = inner

    def check(self, test: Optional[Mapping], history: Sequence[Op],
              opts: Optional[Mapping] = None) -> Dict[str, Any]:
        subs = split_history(history)
        keys = sorted(subs.keys(), key=repr)
        results: Dict[Any, Dict[str, Any]] = {}
        if isinstance(self.inner, Linearizable) and \
                self.inner.algorithm in ("auto", "reach"):
            results = self._check_batched(test, subs, keys, opts)
        else:
            for k in keys:
                results[k] = check_safe(self.inner, test, subs[k], opts)
        valids = [r.get("valid") for r in results.values()]
        if all(v is True for v in valids):
            valid: Any = True
        elif any(v is False for v in valids):
            valid = False
        else:
            valid = "unknown"
        failures = [k for k, r in results.items() if r.get("valid") is False]
        return {"valid": valid, "key-count": len(keys),
                "failures": failures, "results": results}

    def _check_batched(self, test, subs, keys, opts):
        """One batched device dispatch for every key that fits the
        dense engine (the bucketed lockstep lane by default); per-key
        fallback for the rest."""
        from jepsen_tpu.checkers import reach
        from jepsen_tpu.checkers.events import ConcurrencyOverflow
        from jepsen_tpu.models.memo import StateExplosion

        from jepsen_tpu.checkers.facade import (_REACH_MANY_KW,
                                                _engine_kw, _model_from,
                                                auto_check_many_packed)
        model = _model_from(self.inner.model, test)
        kw = dict(self.inner.opts)
        if opts:
            kw.update(opts)
        packs, fits, results = {}, [], {}
        for k in keys:
            try:
                packs[k] = h.pack(subs[k])
                fits.append(k)
            except Exception as e:                      # noqa: BLE001
                results[k] = {"valid": "unknown",
                              "error": f"{type(e).__name__}: {e}"}
        if self.inner.algorithm == "auto":
            # the many-histories auto chain: batched device engines
            # with the per-history fallback chain built in
            batch = auto_check_many_packed(model,
                                           [packs[k] for k in fits], kw)
            for k, r in zip(fits, batch):
                results[k] = r
            return results
        # explicit "reach": stay on the reach engines only.
        # _REACH_MANY_KW includes "devices": the key axis IS the
        # sharded axis, so a user-supplied mesh must reach check_many
        kw = _engine_kw(kw, _REACH_MANY_KW)
        try:
            batch = reach.check_many(model, [packs[k] for k in fits], **kw)
            for k, r in zip(fits, batch):
                results[k] = r
        except (reach.DenseOverflow, ConcurrencyOverflow, StateExplosion):
            # some key (or the common padding) is too big for the dense
            # engine: per-key checking, each falling back as needed
            for k in fits:
                results[k] = check_safe(self.inner, test, subs[k], opts)
        return results


def checker(inner: Checker) -> IndependentChecker:
    return IndependentChecker(inner)
