"""Fault injection — upstream ``jepsen/src/jepsen/nemesis.clj``
(SURVEY.md §2.1, L2). A nemesis is a client on the logical process
``"nemesis"``: the generator sends it ``{"f": "start"/"stop"/...}`` info
ops and its ``invoke`` breaks (or heals) the system, completing the op
with a description of what it did.

Partition topologies, process pause/kill (hammer-time), clock scrambling,
and composition mirror the upstream menu one for one.
"""
from __future__ import annotations

import random
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Tuple

from jepsen_tpu import control
from jepsen_tpu.client import Client
from jepsen_tpu.net import net_for
from jepsen_tpu.op import INFO, Op
from jepsen_tpu.util import majority


class Nemesis(Client):
    """Base nemesis: a client that harms. Default invoke echoes."""

    def invoke(self, test: Mapping, op: Op) -> Op:
        return op.with_(type=INFO)


class Noop(Nemesis):
    """Does nothing (upstream ``nemesis/noop``)."""


def noop() -> Noop:
    return Noop()


# -- partitions ---------------------------------------------------------------

Grudge = Dict[str, List[str]]     # node -> nodes it cannot hear from


def complete_grudge(components: Sequence[Sequence[str]]) -> Grudge:
    """Nodes in different components cannot talk (upstream
    ``nemesis/complete-grudge``)."""
    grudge: Grudge = {}
    for comp in components:
        others = [n for c in components if c is not comp for n in c]
        for node in comp:
            grudge[node] = list(others)
    return grudge


def bisect(nodes: Sequence[str]) -> List[List[str]]:
    """Split nodes into two halves (upstream ``nemesis/bisect``); the
    second half holds the majority when odd."""
    mid = len(nodes) // 2
    return [list(nodes[:mid]), list(nodes[mid:])]


def split_one(nodes: Sequence[str],
              rng: Optional[random.Random] = None) -> List[List[str]]:
    """Isolate one random node (upstream ``nemesis/split-one``)."""
    rng = rng or random
    lucky = rng.choice(list(nodes))
    return [[lucky], [n for n in nodes if n != lucky]]


def bridge_grudge(nodes: Sequence[str]) -> Grudge:
    """Two halves joined only by a single bridge node (upstream
    ``nemesis/bridge``): classic scenario where a quorum intersection
    argument fails."""
    ns = list(nodes)
    mid = len(ns) // 2
    bridge, a, b = ns[mid], ns[:mid], ns[mid + 1:]
    grudge: Grudge = {}
    for n in a:
        grudge[n] = list(b)
    for n in b:
        grudge[n] = list(a)
    grudge[bridge] = []
    return grudge


def majorities_ring_grudge(nodes: Sequence[str],
                           rng: Optional[random.Random] = None) -> Grudge:
    """Every node sees a majority, but no two nodes see the same one
    (upstream ``nemesis/majorities-ring``): each node hears only from its
    ⌈n/2⌉ ring neighbours."""
    ns = list(nodes)
    if rng:
        rng.shuffle(ns)
    n = len(ns)
    keep = majority(n)                      # visible-set size incl. self
    grudge: Grudge = {}
    for i, node in enumerate(ns):
        visible = {ns[(i + d) % n]
                   for d in range(-((keep - 1) // 2), keep // 2 + 1)}
        grudge[node] = [m for m in ns if m not in visible]
    return grudge


class Partitioner(Nemesis):
    """Apply a grudge on ``start``, heal on ``stop`` (upstream
    ``nemesis/partitioner``). ``grudge_fn(nodes) -> Grudge``."""

    def __init__(self, grudge_fn: Callable[[Sequence[str]], Grudge],
                 seed: Optional[int] = None):
        self._grudge_fn = grudge_fn
        self._rng = random.Random(seed)

    def invoke(self, test, op):
        net = net_for(test)
        if op.f == "start":
            grudge = self._grudge_fn(list(test["nodes"]))
            for dst, srcs in grudge.items():
                for src in srcs:
                    net.drop(test, src, dst)
            return op.with_(type=INFO, value={"isolated": {
                k: sorted(v) for k, v in grudge.items() if v}})
        if op.f == "stop":
            net.heal(test)
            return op.with_(type=INFO, value="network healed")
        return op.with_(type=INFO)


def partitioner(grudge_fn: Callable[[Sequence[str]], Grudge]) -> Partitioner:
    return Partitioner(grudge_fn)


def partition_halves() -> Partitioner:
    """Deterministic half split (upstream ``nemesis/partition-halves``)."""
    return Partitioner(lambda nodes: complete_grudge(bisect(nodes)))


def partition_random_halves(seed: Optional[int] = None) -> Partitioner:
    """Random half split (upstream ``nemesis/partition-random-halves``)."""
    nem = Partitioner(None, seed)                       # type: ignore[arg-type]

    def grudge_fn(nodes: Sequence[str]) -> Grudge:
        ns = list(nodes)
        nem._rng.shuffle(ns)
        return complete_grudge(bisect(ns))

    nem._grudge_fn = grudge_fn
    return nem


def partition_random_node(seed: Optional[int] = None) -> Partitioner:
    """Isolate one random node (upstream
    ``nemesis/partition-random-node``)."""
    nem = Partitioner(None, seed)                       # type: ignore[arg-type]
    nem._grudge_fn = lambda nodes: complete_grudge(
        split_one(nodes, nem._rng))
    return nem


def bridge() -> Partitioner:
    """Bridge partition (upstream ``nemesis/bridge``)."""
    return Partitioner(bridge_grudge)


def partition_majorities_ring(seed: Optional[int] = None) -> Partitioner:
    """Intersecting-majorities ring (upstream
    ``nemesis/partition-majorities-ring``)."""
    nem = Partitioner(None, seed)                       # type: ignore[arg-type]
    nem._grudge_fn = lambda nodes: majorities_ring_grudge(nodes, nem._rng)
    return nem


# -- process faults -----------------------------------------------------------

class HammerTime(Nemesis):
    """SIGSTOP a targeted process on ``start``, SIGCONT on ``stop``
    (upstream ``nemesis/hammer-time``). ``targeter`` picks nodes from the
    test; default one random node."""

    def __init__(self, process_pattern: str,
                 targeter: Optional[Callable[[Mapping], List[str]]] = None,
                 seed: Optional[int] = None):
        self._pattern = process_pattern
        self._rng = random.Random(seed)
        self._targeter = targeter or (
            lambda test: [self._rng.choice(list(test["nodes"]))])
        self._stopped: List[str] = []

    def _signal(self, test: Mapping, node: str, sig: str) -> None:
        cluster = test.get("cluster")
        if cluster is not None:
            cluster.pause_node(node) if sig == "STOP" else \
                cluster.resume_node(node)
            return
        s = control.session(test, node).su()
        s.exec_raw(f"pkill -{sig} -f {self._pattern} || true")

    def invoke(self, test, op):
        if op.f == "start":
            self._stopped = self._targeter(test)
            for node in self._stopped:
                self._signal(test, node, "STOP")
            return op.with_(type=INFO, value={"paused": self._stopped})
        if op.f == "stop":
            nodes = self._stopped or list(test["nodes"])
            for node in nodes:
                self._signal(test, node, "CONT")
            self._stopped = []
            return op.with_(type=INFO, value={"resumed": nodes})
        return op.with_(type=INFO)


def hammer_time(process_pattern: str = "", **kw: Any) -> HammerTime:
    return HammerTime(process_pattern, **kw)


class NodeStartStopper(Nemesis):
    """Run ``stop_fn``/``start_fn`` (session, node) on targeted nodes
    (upstream ``nemesis/node-start-stopper``) — e.g. kill -9 the DB on
    start, restart it on stop."""

    def __init__(self, targeter: Callable[[Mapping], List[str]],
                 stop_fn: Callable, start_fn: Callable):
        self._targeter = targeter
        self._stop_fn = stop_fn
        self._start_fn = start_fn
        self._affected: List[str] = []

    def invoke(self, test, op):
        if op.f == "start":
            self._affected = list(self._targeter(test))
            for node in self._affected:
                self._stop_fn(control.session(test, node), node)
            return op.with_(type=INFO, value={"stopped": self._affected})
        if op.f == "stop":
            nodes = self._affected or list(test["nodes"])
            for node in nodes:
                self._start_fn(control.session(test, node), node)
            self._affected = []
            return op.with_(type=INFO, value={"started": nodes})
        return op.with_(type=INFO)


def node_start_stopper(targeter, stop_fn, start_fn) -> NodeStartStopper:
    return NodeStartStopper(targeter, stop_fn, start_fn)


class DBNemesis(Nemesis):
    """Kill/pause the DB via its own Process protocol
    (:class:`jepsen_tpu.db.DB`) — the modern upstream ``nemesis/db-nemesis``
    shape; works against the fake cluster too."""

    def __init__(self, mode: str = "kill",
                 targeter: Optional[Callable[[Mapping], List[str]]] = None,
                 seed: Optional[int] = None):
        assert mode in ("kill", "pause")
        self._mode = mode
        self._rng = random.Random(seed)
        self._targeter = targeter or (
            lambda test: [self._rng.choice(list(test["nodes"]))])
        self._affected: List[str] = []

    def invoke(self, test, op):
        db = test.get("db")
        cluster = test.get("cluster")
        if op.f == "start":
            self._affected = self._targeter(test)
            for node in self._affected:
                if self._mode == "kill":
                    db.kill(test, node) if db else cluster.kill_node(node)
                else:
                    db.pause(test, node) if db else cluster.pause_node(node)
            return op.with_(type=INFO, value={self._mode: self._affected})
        if op.f == "stop":
            nodes = self._affected or list(test["nodes"])
            for node in nodes:
                if self._mode == "kill":
                    db.start(test, node) if db else cluster.start_node(node)
                else:
                    db.resume(test, node) if db else cluster.resume_node(node)
            self._affected = []
            return op.with_(type=INFO, value={"restarted": nodes})
        return op.with_(type=INFO)


# -- clock faults -------------------------------------------------------------

class ClockScrambler(Nemesis):
    """Jump targeted nodes' clocks by up to ±dt seconds (upstream
    ``nemesis/clock-scrambler``; the newer ``nemesis.time`` bump/strobe
    variants live in :func:`clock_nemesis`)."""

    def __init__(self, dt: float, seed: Optional[int] = None):
        self._dt = dt
        self._rng = random.Random(seed)

    def invoke(self, test, op):
        cluster = test.get("cluster")
        if op.f == "start":
            shifts = {}
            for node in test["nodes"]:
                shift = self._rng.uniform(-self._dt, self._dt)
                if cluster is not None:
                    shifts[node] = round(shift, 3)
                    cluster.bump_clock(node, shift)
                else:
                    # GNU date only accepts integral relative offsets
                    whole = int(shift) or (1 if shift > 0 else -1)
                    shifts[node] = whole
                    s = control.session(test, node).su()
                    s.exec_raw(f"date -s \"$(date -d '{whole} seconds')\"")
            return op.with_(type=INFO, value={"clock-shift-s": shifts})
        if op.f == "stop":
            for node in test["nodes"]:
                if cluster is not None:
                    cluster.bump_clock(node, None)
                else:
                    s = control.session(test, node).su()
                    s.exec_raw("ntpdate -p 1 -b pool.ntp.org || "
                               "chronyc -a makestep || true")
            return op.with_(type=INFO, value="clocks reset")
        return op.with_(type=INFO)


def clock_scrambler(dt: float = 60.0, seed: Optional[int] = None
                    ) -> ClockScrambler:
    return ClockScrambler(dt, seed=seed)


class ClockNemesis(Nemesis):
    """Precise clock faults via the compiled ``bump-time`` helper
    (upstream ``jepsen.nemesis.time`` + ``resources/bump-time.c``):
    ``{"f": "bump", "value": {node: ms}}`` jumps clocks by exact deltas;
    ``strobe`` flaps the clock; ``reset`` restores."""

    HELPER = "/opt/jepsen/bump-time"

    def install(self, test: Mapping) -> None:
        """Compile bump-time.c on every node (upstream
        ``nemesis.time/install!``)."""
        import os as _os
        src = _os.path.join(_os.path.dirname(__file__), "resources",
                            "bump_time.c")

        def fn(s: control.Session, node: str):
            s = s.su()
            s.exec("mkdir", "-p", "/opt/jepsen")
            s.upload(src, "/opt/jepsen/bump-time.c")
            s.exec("gcc", "-O2", "-o", self.HELPER,
                   "/opt/jepsen/bump-time.c")
        control.on_nodes(test, fn)

    def invoke(self, test, op):
        cluster = test.get("cluster")
        if op.f == "bump":
            for node, ms in (op.value or {}).items():
                if cluster is not None:
                    cluster.bump_clock(node, ms / 1000.0)
                else:
                    control.session(test, node).su().exec(
                        self.HELPER, "bump", str(ms))
            return op.with_(type=INFO)
        if op.f == "strobe":
            v = op.value or {}
            for node in v.get("nodes", test["nodes"]):
                if cluster is None:
                    control.session(test, node).su().exec(
                        self.HELPER, "strobe", str(v.get("delta-ms", 200)),
                        str(v.get("period-ms", 10)),
                        str(v.get("duration-ms", 1000)))
            return op.with_(type=INFO)
        if op.f == "reset":
            for node in test["nodes"]:
                if cluster is not None:
                    cluster.bump_clock(node, None)
                else:
                    control.session(test, node).su().exec(
                        self.HELPER, "reset")
            return op.with_(type=INFO)
        return op.with_(type=INFO)


def clock_nemesis() -> ClockNemesis:
    return ClockNemesis()


# -- composition --------------------------------------------------------------

class Compose(Nemesis):
    """Route ops to sub-nemeses by an ``f``-dispatch table (upstream
    ``nemesis/compose``): ``{("start", "stop"): partitioner, ...}`` or
    ``{\"partition-start\": (nem, \"start\"), ...}`` for renamed fs."""

    def __init__(self, table: Mapping[Any, Any]):
        self._routes: List[Tuple[Any, Nemesis, Optional[str]]] = []
        for key, nem in table.items():
            if isinstance(key, (tuple, frozenset, set)):
                for f in key:
                    self._routes.append((f, nem, None))
            elif isinstance(nem, tuple):
                inner, rename = nem
                self._routes.append((key, inner, rename))
            else:
                self._routes.append((key, nem, None))

    def _distinct(self) -> List[Nemesis]:
        return list({id(n): n for _, n, _ in self._routes}.values())

    def setup(self, test):
        for nem in self._distinct():
            nem.setup(test)

    def invoke(self, test, op):
        for f, nem, rename in self._routes:
            if op.f == f:
                if rename is not None:
                    res = nem.invoke(test, op.with_(f=rename))
                    return res.with_(f=op.f)
                return nem.invoke(test, op)
        return op.with_(type=INFO, value=f"no nemesis handles f={op.f!r}")

    def teardown(self, test):
        for nem in self._distinct():
            nem.teardown(test)


def compose(table: Mapping[Any, Any]) -> Compose:
    return Compose(table)
