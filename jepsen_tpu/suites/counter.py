"""Counter suite — upstream etcd/zookeeper counter workloads (SURVEY.md
§2.5): concurrent ``add`` deltas and ``read`` observations, checked with
``jepsen.checker/counter`` (every ok read must lie inside the interval of
possible counter values given which adds had definitely / possibly taken
effect).

Runs against :class:`~jepsen_tpu.fake.cluster.FakeCluster`:
``mode="linearizable"`` must pass; ``mode="sloppy"`` replicates the
post-increment VALUE last-writer-wins, so concurrent increments clobber
each other and reads drift below the definite sum — caught by the
checker.
"""
from __future__ import annotations

import random
from typing import Any, Dict, Optional

from jepsen_tpu import client as cl
from jepsen_tpu import generators as g
from jepsen_tpu import nemesis, util
from jepsen_tpu.suites import partition_cycle
from jepsen_tpu.checkers import facade, perf, timeline
from jepsen_tpu.fake import FakeCluster, Unavailable
from jepsen_tpu.fake.cluster import FakeTimeout


class CounterClient(cl.Client):
    def __init__(self, key: Any = "c"):
        self.key = key
        self.node: Any = None

    def open(self, test, node):
        c = type(self)(self.key)
        c.node = node
        return c

    def invoke(self, test, op):
        cluster: FakeCluster = test["cluster"]
        try:
            if op.f == "add":
                cluster.incr(self.node, self.key, op.value)
                return cl.ok(op)
            if op.f == "read":
                return cl.ok(op, cluster.read(self.node, self.key) or 0)
            raise ValueError(f"unknown f {op.f!r}")
        except Unavailable as e:
            return cl.fail(op, str(e))
        except FakeTimeout as e:
            return cl.info(op, str(e))


def workload(hi: int = 5, seed: Optional[int] = None) -> g.Generator:
    rng = random.Random(seed)
    return g.mix(g.Fn(lambda: {"f": "add", "value": rng.randint(1, hi)}),
                 g.Fn(lambda: {"f": "read", "value": None}), seed=seed)


def counter_test(mode: str = "linearizable", *, time_limit: float = 5.0,
                 concurrency: int = 5, seed: Optional[int] = None,
                 with_nemesis: bool = True, store: bool = False,
                 nemesis_interval: float = 1.0,
                 nodes: Any = 5) -> Dict[str, Any]:
    node_names = util.node_names(nodes)
    cluster = FakeCluster(node_names, mode=mode, seed=seed)
    main = g.TimeLimit(time_limit,
                       g.Stagger(0.001, workload(seed=seed), seed=seed))
    # final reads after a barrier (every in-flight add completed first);
    # the once-sleep is only a grace pause for the nemesis's final heal —
    # correctness does not depend on its timing: quorum reads are valid
    # pre-heal too, and minority-side reads fail cleanly (the checker
    # scores ok reads only)
    client_seq = g.Seq([main, g.synchronize(g.Seq(
        [{"sleep": 0.3},
         g.Limit(concurrency,
                 g.Fn(lambda: {"f": "read", "value": None}))]))])
    nem: Optional[nemesis.Nemesis] = None
    if with_nemesis:
        nem = nemesis.partition_random_halves(seed=seed)
        generator: g.GenLike = g.clients_gen(
            client_seq, partition_cycle(time_limit, nemesis_interval,
                                        seed=seed))
    else:
        generator = g.clients_gen(client_seq)
    return {
        "name": f"counter-{mode}",
        "nodes": node_names,
        "cluster": cluster,
        "client": CounterClient(),
        "nemesis": nem,
        "generator": generator,
        "checker": facade.compose({
            "counter": facade.counter(),
            "timeline": timeline.html(),
            "latency": perf.latency_graph(),
            "rate": perf.rate_graph(),
            "stats": facade.stats(),
        }),
        "concurrency": concurrency,
        "store": store,
        "run-time-limit": max(60.0, time_limit * 6),
        "op-timeout": 5.0,
    }
