"""Etcd suite over HTTP — upstream ``etcd/`` (SURVEY.md §2.5), which
drives etcd's v2 REST API (``GET/PUT /v2/keys/<k>``, CAS via
``prevValue``) and checks the history against the ``cas_register``
model.

Unlike :mod:`jepsen_tpu.suites.register` (direct in-proc calls), this
suite speaks the REAL wire protocol: :class:`EtcdHttpClient` is a plain
urllib HTTP client, and by default the test boots one
etcd-v2-dialect server per node (:class:`jepsen_tpu.fake.httpd
.HttpKVFrontend`, backed by the fake cluster so nemesis faults surface
as genuine 503s and socket timeouts) through the DB protocol —
the same lifecycle a real etcd would use. Point ``endpoints`` at real
etcd v2 URLs and the identical client/checker pipeline applies.

Completion mapping (the part upstream gets subtly right and tests):

- 2xx        → :ok
- 404        → :ok read of nil (key unset)
- 412        → :fail (CAS compare failed — definitely no effect)
- 503        → :fail (node refused — definitely no effect)
- timeout/5xx→ :info (indeterminate — may or may not have taken effect)
"""
from __future__ import annotations

import json
import socket
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, Optional

from jepsen_tpu import client as cl
from jepsen_tpu import db as db_mod
from jepsen_tpu import generators as g
from jepsen_tpu import models, nemesis, util
from jepsen_tpu.fake import FakeCluster
from jepsen_tpu.fake.httpd import HttpKVFrontend
from jepsen_tpu.op import Op
from jepsen_tpu.suites._common import nemesis_schedule, standard_checker


class EtcdHttpClient(cl.Client):
    """urllib client for the etcd v2 keys API. ``test["endpoints"]`` maps
    node → base URL (set up by :class:`FakeEtcdDB`, or by hand for a real
    cluster)."""

    def __init__(self, key: str = "r", timeout_s: float = 1.0):
        self.key = key
        self.timeout_s = timeout_s
        self.base: Optional[str] = None

    def open(self, test, node):
        c = type(self)(self.key, self.timeout_s)
        c.base = test["endpoints"][node]
        return c

    def _url(self) -> str:
        return f"{self.base}/v2/keys/{urllib.parse.quote(self.key)}"

    def _request(self, method: str, form: Optional[Dict[str, str]] = None):
        data = urllib.parse.urlencode(form).encode() if form else None
        req = urllib.request.Request(self._url(), data=data, method=method)
        if data:
            req.add_header("Content-Type",
                           "application/x-www-form-urlencoded")
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            return resp.status, json.loads(resp.read().decode())

    def invoke(self, test, op: Op) -> Op:
        try:
            return self._invoke(op)
        except urllib.error.HTTPError as e:
            if e.code == 404 and op.f == "read":
                return cl.ok(op, None)          # unset key reads nil
            if e.code == 404 and op.f == "cas":
                return cl.fail(op, "key not found")     # no effect
            if e.code == 412 and op.f == "cas":
                return cl.fail(op, "cas compare failed")
            if e.code == 503:
                return cl.fail(op, "node unavailable")
            return cl.info(op, f"http {e.code}")
        except (urllib.error.URLError, socket.timeout, TimeoutError,
                ConnectionError) as e:
            if isinstance(getattr(e, "reason", None), ConnectionRefusedError):
                return cl.fail(op, "connection refused")
            return cl.info(op, f"{type(e).__name__}")

    def _invoke(self, op: Op) -> Op:
        if op.f == "read":
            _, body = self._request("GET")
            raw = body["node"]["value"]
            return cl.ok(op, int(raw) if raw.lstrip("-").isdigit() else raw)
        if op.f == "write":
            self._request("PUT", {"value": str(op.value)})
            return cl.ok(op)
        if op.f == "cas":
            old, new = op.value
            self._request("PUT", {"value": str(new),
                                  "prevValue": str(old)})
            return cl.ok(op)
        raise ValueError(f"unknown f {op.f!r}")


class FakeEtcdDB(db_mod.DB):
    """DB-protocol lifecycle for the per-node HTTP front-ends: ``setup``
    on the first node boots all servers and publishes
    ``test["endpoints"]``; ``teardown`` stops them (upstream
    ``etcd/.../db.clj`` installs and starts real etcd here)."""

    def __init__(self, cluster: FakeCluster):
        import threading
        self.cluster = cluster
        self._frontend: Optional[HttpKVFrontend] = None
        self._lock = threading.Lock()

    def setup(self, test, node):
        with self._lock:                # setup_all may fan out per node
            if self._frontend is None:
                self._frontend = HttpKVFrontend(self.cluster).start()
                test["endpoints"] = self._frontend.endpoints

    def teardown(self, test, node):
        with self._lock:
            if self._frontend is not None:
                self._frontend.stop()
                self._frontend = None


def etcd_test(mode: str = "linearizable", *,
              time_limit: float = 5.0, concurrency: int = 5,
              seed: Optional[int] = None, nodes: Any = 5,
              algorithm: str = "auto", with_nemesis: bool = True,
              nemesis_interval: float = 1.0,
              store: bool = False) -> Dict[str, Any]:
    """The flagship CAS-register test over HTTP (upstream
    ``etcd/src/.../runner.clj``)."""
    node_names = util.node_names(nodes)
    cluster = FakeCluster(node_names, mode=mode, seed=seed)
    client_gen: g.GenLike = g.TimeLimit(
        time_limit, g.Stagger(0.002, g.register_workload(seed=seed),
                              seed=seed))
    nem: Optional[nemesis.Nemesis] = None
    generator: g.GenLike = client_gen
    if with_nemesis:
        nem = nemesis.partition_random_halves(seed=seed)
        generator = nemesis_schedule(client_gen, nemesis_interval)
    return {
        "name": f"etcd-{mode}",
        "nodes": node_names,
        "cluster": cluster,
        "db": FakeEtcdDB(cluster),
        "client": EtcdHttpClient("r"),
        "nemesis": nem,
        "generator": generator,
        "model": models.cas_register(),
        "checker": standard_checker(models.cas_register(),
                                    algorithm=algorithm),
        "concurrency": concurrency,
        "store": store,
        "run-time-limit": max(60.0, time_limit * 6),
        "op-timeout": 5.0,
    }
