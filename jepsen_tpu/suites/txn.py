"""Transactional list-append suite — the Elle workload (upstream
``jepsen.tests.cycle.append``) against three tiers:

- ``tier="fake"``  — multi-key transactions through
  :meth:`jepsen_tpu.fake.FakeCluster.txn`: safe mode commits the whole
  txn atomically (histories serializable by construction, the
  :class:`~jepsen_tpu.txn.TxnChecker` must agree); sloppy mode applies
  micro-ops to local replicas with last-writer-wins replication, so
  partitioned appends clobber whole lists — genuine Elle anomalies.
- ``tier="etcd"``  — single-key transactions over the etcd-v2 HTTP
  dialect (:mod:`jepsen_tpu.fake.httpd` front-ends, or real etcd v2
  endpoints): the txn commits as ONE compare-and-swap of the encoded
  list (reads observe the snapshot the CAS validated — atomic at the
  CAS point), retried on compare failure.
- ``tier="redis"`` — the same CAS-commit discipline over RESP
  (:mod:`jepsen_tpu.fake.resp`), using the canonical EVAL
  compare-and-set script.

Lists cross the CAS tiers encoded ``"L<v1>,<v2>,..."`` (the ``L``
prefix keeps the empty list a non-blank form value — etcd's
``parse_qs`` would otherwise drop an empty ``prevValue`` and turn the
CAS into a blind write).
"""
from __future__ import annotations

import urllib.error
import urllib.parse
import urllib.request
import socket
from typing import Any, Dict, List, Optional, Sequence

from jepsen_tpu import client as cl
from jepsen_tpu import generators as g
from jepsen_tpu import nemesis, txn as txn_mod, util
from jepsen_tpu.checkers import facade, perf, timeline
from jepsen_tpu.fake import FakeCluster, Unavailable
from jepsen_tpu.fake.cluster import FakeTimeout
from jepsen_tpu.op import Op
from jepsen_tpu.suites import partition_cycle
from jepsen_tpu.suites.etcd import FakeEtcdDB
from jepsen_tpu.suites.redis import FakeRedisDB, RespClient, RespError


def encode_list(vals: Sequence[Any]) -> str:
    return "L" + ",".join(str(v) for v in vals)


def decode_list(s: Optional[str]) -> List[int]:
    if not s or s == "L":
        return []
    body = s[1:] if s.startswith("L") else s
    return [int(x) for x in body.split(",")]


class FakeTxnClient(cl.Client):
    """Multi-key atomic transactions against the fake cluster."""

    def __init__(self) -> None:
        self.node: Any = None

    def open(self, test, node):
        c = type(self)()
        c.node = node
        return c

    def invoke(self, test, op: Op) -> Op:
        cluster: FakeCluster = test["cluster"]
        try:
            return cl.ok(op, cluster.txn(self.node, op.value))
        except Unavailable as e:
            return cl.fail(op, str(e))
        except FakeTimeout as e:
            return cl.info(op, str(e))


class _CasTxnClient(cl.Client):
    """Single-key list-append transactions committed as one
    compare-and-swap of the encoded list: read the current encoding,
    apply every micro-op (reads observe the snapshot plus the txn's
    own earlier appends — a prefix of the committed list), CAS
    old→new. Compare failure = definite no effect → retry; retries
    exhausted → ``fail``; indeterminate transport outcomes → ``info``
    immediately (a retry after a maybe-applied CAS could double-append
    and poison traceability)."""

    retries = 8

    # -- tier transport hooks -------------------------------------------
    def _get_enc(self, key: str) -> str:
        raise NotImplementedError

    def _cas_enc(self, key: str, old: str, new: str) -> bool:
        raise NotImplementedError

    def _invoke_txn(self, op: Op) -> Op:
        micros = op.value
        appends = any(m[0] == "append" for m in micros)
        for _attempt in range(self.retries):
            old = self._get_enc(self._storage_key(micros))
            state = decode_list(old)
            result = []
            for kind, k, v in micros:
                if kind == "append":
                    state.append(v)
                    result.append(["append", k, v])
                else:
                    result.append(["r", k, list(state)])
            if not appends:
                # a read-only single-key txn is one atomic GET
                return cl.ok(op, result)
            if self._cas_enc(self._storage_key(micros), old,
                             encode_list(state)):
                return cl.ok(op, result)
        return cl.fail(op, "cas contention")

    @staticmethod
    def _storage_key(micros) -> str:
        return str(micros[0][1])


class EtcdTxnClient(_CasTxnClient):
    """The etcd-v2 HTTP tier (``test["endpoints"]`` maps node → base
    URL — the fake front-ends by default, real etcd v2 if pointed
    there)."""

    def __init__(self, timeout_s: float = 1.0):
        self.timeout_s = timeout_s
        self.base: Optional[str] = None

    def open(self, test, node):
        c = type(self)(self.timeout_s)
        c.base = test["endpoints"][node]
        return c

    def _url(self, key: str) -> str:
        return f"{self.base}/v2/keys/{urllib.parse.quote(key)}"

    def _request(self, key: str, method: str,
                 form: Optional[Dict[str, str]] = None):
        import json
        data = urllib.parse.urlencode(form).encode() if form else None
        req = urllib.request.Request(self._url(key), data=data,
                                     method=method)
        if data:
            req.add_header("Content-Type",
                           "application/x-www-form-urlencoded")
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            return resp.status, json.loads(resp.read().decode())

    def _get_enc(self, key: str) -> str:
        try:
            _, body = self._request(key, "GET")
            return str(body["node"]["value"])
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return "L"                       # unset key = empty list
            raise

    def _cas_enc(self, key: str, old: str, new: str) -> bool:
        try:
            self._request(key, "PUT", {"value": new, "prevValue": old})
            return True
        except urllib.error.HTTPError as e:
            if e.code in (404, 412):             # definite compare miss
                return False
            raise

    def invoke(self, test, op: Op) -> Op:
        try:
            return self._invoke_txn(op)
        except urllib.error.HTTPError as e:
            if e.code == 503:
                return cl.fail(op, "node unavailable")
            return cl.info(op, f"http {e.code}")
        except (urllib.error.URLError, socket.timeout, TimeoutError,
                ConnectionError) as e:
            if isinstance(getattr(e, "reason", None),
                          ConnectionRefusedError):
                return cl.fail(op, "connection refused")
            return cl.info(op, type(e).__name__)


class RedisTxnClient(RespClient, _CasTxnClient):
    """The RESP tier: GET + the EVAL compare-and-set script commit the
    encoded list atomically (the transport/completion mapping —
    CLUSTERDOWN → fail, timeouts → info — rides
    :class:`~jepsen_tpu.suites.redis.RespClient`)."""

    retries = _CasTxnClient.retries

    def _get_enc(self, key: str) -> str:
        v = self._command("GET", key)
        return "L" if v is None else str(v)

    def _cas_enc(self, key: str, old: str, new: str) -> bool:
        from jepsen_tpu.fake.resp import CAS_SCRIPT
        return self._command("EVAL", CAS_SCRIPT, "1", key, old,
                             new) == 1

    def _invoke(self, op: Op) -> Op:
        # RespClient.invoke supplies the error mapping; the op body is
        # the CAS-commit txn instead of the register verbs
        return self._invoke_txn(op)


def txn_test(mode: str = "linearizable", *, tier: str = "fake",
             keys: int = 4, max_len: int = 4, read_p: float = 0.5,
             time_limit: float = 5.0, concurrency: int = 5,
             seed: Optional[int] = None, with_nemesis: bool = True,
             nemesis_interval: float = 1.0, store: bool = False,
             nodes: Any = 5) -> Dict[str, Any]:
    node_names = util.node_names(nodes)
    cluster = FakeCluster(node_names, mode=mode, seed=seed)
    single_key = tier != "fake"
    workload = g.TimeLimit(
        time_limit,
        g.Stagger(0.002, g.txn_workload(keys=keys, max_len=max_len,
                                        read_p=read_p, seed=seed,
                                        single_key=single_key),
                  seed=seed))
    test: Dict[str, Any] = {
        "name": f"txn-{tier}-{mode}",
        "nodes": node_names,
        "cluster": cluster,
        "checker": facade.compose({
            "txn": txn_mod.TxnChecker(),
            "timeline": timeline.html(),
            "latency": perf.latency_graph(),
            "rate": perf.rate_graph(),
            "stats": facade.stats(),
        }),
        "concurrency": concurrency,
        "store": store,
        "run-time-limit": max(60.0, time_limit * 6),
        "op-timeout": 5.0,
    }
    if tier == "fake":
        test["client"] = FakeTxnClient()
    elif tier == "etcd":
        test["client"] = EtcdTxnClient()
        test["db"] = FakeEtcdDB(cluster)
    elif tier == "redis":
        test["client"] = RedisTxnClient()
        test["db"] = FakeRedisDB(cluster)
    else:
        raise ValueError(f"unknown tier {tier!r}")
    if tier != "fake":
        # seed every workload key with the encoded empty list so the
        # first CAS has a concrete prevValue (see encode_list)
        for i in range(keys):
            cluster.write(node_names[0], f"t{i}", encode_list([]))
    nem: Optional[nemesis.Nemesis] = None
    generator: g.GenLike = g.clients_gen(workload)
    if with_nemesis:
        nem = nemesis.partition_random_halves(seed=seed)
        generator = g.clients_gen(
            workload, partition_cycle(time_limit, nemesis_interval,
                                      seed=seed))
    test["nemesis"] = nem
    test["generator"] = generator
    return test
