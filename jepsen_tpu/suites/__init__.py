"""Per-DB test suites — upstream top-level dirs (``etcd/``, ``zookeeper/``
…, SURVEY.md §2.5), each a small project wiring client + db + generator +
checker into a test map. Here: exemplar suites against the in-proc fake
cluster (and real systems when reachable)."""
from __future__ import annotations

from typing import Optional

from jepsen_tpu import generators as g


def partition_cycle(time_limit: float, interval: float,
                    seed: Optional[int] = None) -> g.Generator:
    """Shared nemesis phase: partition start/stop cycles for
    ``time_limit`` seconds, then exactly one final heal so post-fault
    client phases (drains, final reads) run against a healed system."""
    cyc = g.TimeLimit(time_limit, g.cycle(lambda: g.Seq(
        [{"f": "start"}, {"sleep": interval},
         {"f": "stop"}, {"sleep": interval}])))
    return g.Seq([cyc, g.Once({"f": "stop"})])
