"""Per-DB test suites — upstream top-level dirs (``etcd/``, ``zookeeper/``
…, SURVEY.md §2.5), each a small project wiring client + db + generator +
checker into a test map. Here: exemplar suites against the in-proc fake
cluster (and real systems when reachable)."""
