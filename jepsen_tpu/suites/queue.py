"""RabbitMQ-style queue suite — upstream ``rabbitmq/`` (SURVEY.md §2.5):
unique-value ``enqueue``/``dequeue`` ops against a replicated broker, a
partition nemesis, then a full drain phase, checked with
``jepsen.checker/queue`` (no phantom deliveries) and ``total-queue``
(every acknowledged enqueue consumed exactly once).

Runs against the in-proc :class:`~jepsen_tpu.fake.broker.FakeBroker`:
``mode="safe"`` must pass; ``mode="lossy"`` autoheals by discarding one
partition side's state and must be caught.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

from jepsen_tpu import client as cl
from jepsen_tpu import generators as g
from jepsen_tpu import nemesis, util
from jepsen_tpu.suites import partition_cycle
from jepsen_tpu.checkers import facade, perf, timeline
from jepsen_tpu.fake.broker import Empty, FakeBroker, FakeTimeout, Unavailable


class QueueClient(cl.Client):
    def __init__(self):
        self.node: Any = None

    def open(self, test, node):
        c = type(self)()
        c.node = node
        return c

    def invoke(self, test, op):
        broker: FakeBroker = test["cluster"]
        try:
            if op.f == "enqueue":
                broker.enqueue(self.node, op.value)
                return cl.ok(op)
            if op.f == "dequeue":
                return cl.ok(op, broker.dequeue(self.node))
            raise ValueError(f"unknown f {op.f!r}")
        except Empty as e:
            return cl.fail(op, str(e))
        except Unavailable as e:
            return cl.fail(op, str(e))
        except FakeTimeout as e:
            return cl.info(op, str(e))


def workload(seed: Optional[int] = None,
             enqueue_weight: int = 1,
             universe: Optional[int] = None) -> g.Generator:
    """Enqueue (unique ints) / dequeue mix; ``enqueue_weight`` > 1 biases
    toward enqueues so the queue keeps a backlog (useful for tests that
    need messages pending when a fault lands). ``universe`` caps the
    number of enqueues — unique_values counts 0,1,2,..., so capping the
    COUNT also caps every value inside the bounded-queue model's
    universe (the set suite's trick)."""
    enq: g.GenLike = g.unique_values("enqueue")
    if universe is not None:
        enq = g.Limit(universe, enq)
    deq = g.Fn(lambda: {"f": "dequeue", "value": None})
    return g.mix(*([enq] * max(1, enqueue_weight) + [deq]), seed=seed)


def _drain() -> g.Generator:
    """Dequeue until every replica is empty (the upstream ``:drain``
    phase); exhausts when nothing is left anywhere."""
    return g.Fn(lambda test, process:
                {"f": "dequeue", "value": None}
                if not test["cluster"].empty() else None)


def queue_test(mode: str = "safe", *, time_limit: float = 5.0,
               concurrency: int = 5, seed: Optional[int] = None,
               with_nemesis: bool = True, store: bool = False,
               nemesis_interval: float = 1.0,
               enqueue_weight: int = 1, nodes: Any = 5,
               universe: Optional[int] = None) -> Dict[str, Any]:
    """``universe`` bounds the enqueue workload to that many unique
    values and composes a ``linear`` checker over the int-coded
    :func:`jepsen_tpu.models.bounded_queue` model — a memo-enumerable
    state space (the arrangements of distinct pending values), so the
    queue suite's history reaches the dense-walk device engines
    instead of only the host queue invariants (ROADMAP item 3(a), the
    bounded-model remainder). Opt-in (default ``None``, the unbounded
    workload with host-only checking): capping the enqueue COUNT
    changes backlog dynamics, so faults that need a deep backlog —
    the lossy-autoheal scenario — keep the unbounded mix."""
    from jepsen_tpu import models

    node_names = util.node_names(nodes)
    broker = FakeBroker(node_names, mode=mode, seed=seed)
    wl = workload(seed=seed, enqueue_weight=enqueue_weight,
                  universe=universe)
    main = g.TimeLimit(time_limit, g.Stagger(0.001, wl, seed=seed))
    # each role runs its own phase sequence: clients mix, then drain; the
    # nemesis cycles faults for the mix window, then heals once and
    # exhausts. The barrier makes every worker finish its in-flight
    # enqueue before the drain's empty() poll can observe a transiently-
    # empty queue and stop early. (The once-sleep is a grace pause for
    # the nemesis's final heal, not a guarantee — drain correctness does
    # not depend on it: pre-heal drain ops just fail cleanly and the
    # stagger paces the retries.)
    client_seq = g.Seq([main, g.synchronize(g.Seq(
        [{"sleep": 0.3}, g.Stagger(0.001, _drain(), seed=seed)]))])
    nem: Optional[nemesis.Nemesis] = None
    if with_nemesis:
        nem = nemesis.partition_random_halves(seed=seed)
        generator: g.GenLike = g.clients_gen(
            client_seq, partition_cycle(time_limit, nemesis_interval,
                                        seed=seed))
    else:
        generator = g.clients_gen(client_seq)
    return {
        "name": f"queue-{mode}",
        "nodes": node_names,
        "cluster": broker,
        "client": QueueClient(),
        "nemesis": nem,
        "generator": generator,
        "checker": facade.compose({
            "queue": facade.queue(),
            "total-queue": facade.total_queue(),
            **({"linear": facade.linearizable(
                    models.bounded_queue(universe))}
               if universe is not None else {}),
            "timeline": timeline.html(),
            "latency": perf.latency_graph(),
            "rate": perf.rate_graph(),
            "stats": facade.stats(),
        }),
        "concurrency": concurrency,
        "store": store,
        "run-time-limit": max(60.0, time_limit * 6),
        "op-timeout": 5.0,
    }
