"""ZooKeeper-style lock suite — upstream ``zookeeper/`` (SURVEY.md §2.5):
acquire/release ops on a distributed lock, checked against the ``mutex``
model (BASELINE.md ladder config #3).

The client keeps per-process hold state and emits alternating
acquire/release attempts: a rejected try-acquire is a ``fail`` op
(stripped by the checker), so only successful transitions reach the
model — the same shape the upstream lock workload produces.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

from jepsen_tpu import client as cl
from jepsen_tpu import generators as g
from jepsen_tpu import models, nemesis, util
from jepsen_tpu.checkers import facade, timeline
from jepsen_tpu.fake.cluster import FakeTimeout, Unavailable
from jepsen_tpu.fake.lock import FakeLockService


class LockClient(cl.Client):
    def __init__(self, name: Any = "lock"):
        self.name = name
        self.node: Any = None
        self.held = False

    def open(self, test, node):
        c = type(self)(self.name)
        c.node = node
        return c

    def invoke(self, test, op):
        svc: FakeLockService = test["cluster"]
        holder = op.process
        try:
            if op.f == "acquire":
                if svc.acquire(self.node, self.name, holder):
                    self.held = True
                    return cl.ok(op)
                return cl.fail(op, "lock held")
            if op.f == "release":
                if svc.release(self.node, self.name, holder):
                    self.held = False
                    return cl.ok(op)
                return cl.fail(op, "not the holder")
            raise ValueError(f"unknown f {op.f!r}")
        except Unavailable as e:
            return cl.fail(op, str(e))
        except FakeTimeout as e:
            # an indeterminate acquire/release may have taken effect; the
            # client no longer knows its hold state — drop the belief so
            # the generator keeps making progress either way
            self.held = False
            return cl.info(op, str(e))


class LockWorkload(g.Generator):
    """Alternating acquire/release per process, driven by each worker's
    *observed* completions: after a successful acquire, try release; else
    try acquire. State is tracked via the client's ``held`` flag exposed
    in the test map (simplest faithful analogue of the upstream
    ``gen/each`` lock generator)."""

    def __init__(self):
        self._held: Dict[Any, bool] = {}

    def op(self, test, process):
        # the worker records outcomes in test["_lock_held"]; emitting
        # based on our own bookkeeping of invocations would desync on
        # fail ops, so consult the client-side state when present
        held = test.get("_lock_held", {}).get(process, False)
        return {"f": "release" if held else "acquire", "value": None}


class TrackingLockClient(LockClient):
    """LockClient that mirrors hold state into the test map so the
    workload generator can alternate correctly."""

    def invoke(self, test, op):
        res = super().invoke(test, op)
        test.setdefault("_lock_held", {})[op.process] = self.held
        return res


def mutex_test(mode: str = "linearizable", *, time_limit: float = 5.0,
               concurrency: int = 5, seed: Optional[int] = None,
               with_nemesis: bool = True, store: bool = False,
               nemesis_interval: float = 0.5, lease_ttl: float = 30.0,
               algorithm: str = "auto", nodes: Any = 5) -> Dict[str, Any]:
    """Modes: ``linearizable`` (safe), ``sloppy`` (split-brain grants,
    caught via partitions), ``leases`` (lease-based lock — safe under
    synchronized clocks, broken by clock skew: the nemesis becomes
    :func:`jepsen_tpu.nemesis.clock_nemesis` bumping one node's clock
    past the TTL each cycle, the canonical ``bump-time`` fault)."""
    import random as _random

    node_names = util.node_names(nodes)
    svc = FakeLockService(node_names, mode=mode, seed=seed,
                          lease_ttl=lease_ttl)
    client_gen = g.TimeLimit(time_limit, g.Stagger(0.001, LockWorkload(),
                                                   seed=seed))
    nem: Optional[nemesis.Nemesis] = None
    generator: g.GenLike = client_gen
    if with_nemesis and mode == "leases":
        # clock-fault nemesis: bump a random node far past the lease
        # TTL, later reset — while bumped, that node judges every lease
        # expired and double-grants
        nem = nemesis.clock_nemesis()
        rng = _random.Random(seed)
        bump_ms = int(lease_ttl * 2000)

        def _cycle():
            node = rng.choice(node_names)
            return g.Seq([{"f": "bump", "value": {node: bump_ms}},
                          {"sleep": nemesis_interval},
                          {"f": "reset"},
                          {"sleep": nemesis_interval}])

        generator = g.clients_gen(client_gen, g.cycle(_cycle))
    elif with_nemesis:
        nem = nemesis.partition_random_halves(seed=seed)
        generator = g.clients_gen(client_gen, g.cycle(lambda: g.Seq(
            [{"f": "start"}, {"sleep": nemesis_interval},
             {"f": "stop"}, {"sleep": nemesis_interval}])))
    return {
        "name": f"mutex-{mode}",
        "nodes": node_names,
        "cluster": svc,
        "client": TrackingLockClient(),
        "nemesis": nem,
        "generator": generator,
        "model": models.mutex(),
        "checker": facade.compose({
            "linear": facade.linearizable(models.mutex(),
                                          algorithm=algorithm),
            "timeline": timeline.html(),
            "stats": facade.stats(),
        }),
        "concurrency": concurrency,
        "store": store,
        "run-time-limit": max(60.0, time_limit * 6),
        "op-timeout": 5.0,
    }
