"""Shared suite scaffolding: the standard start/sleep/stop nemesis
schedule and the standard composed checker set, used by every suite
(upstream repeats these per-suite in each Leiningen project's runner;
here they live once)."""
from __future__ import annotations

from typing import Any, Dict, Optional

from jepsen_tpu import generators as g
from jepsen_tpu import models as m
from jepsen_tpu.checkers import facade, perf, timeline


def nemesis_schedule(client_gen: "g.GenLike",
                     interval: float = 1.0) -> "g.GenLike":
    """Client ops interleaved with the classic start/sleep/stop fault
    cycle (upstream's ``gen/nemesis`` + ``gen/cycle`` wiring)."""
    nem_gen = g.Seq([{"sleep": interval / 2},
                     g.cycle(lambda: g.Seq([
                         {"f": "start"},
                         {"sleep": interval},
                         {"f": "stop"},
                         {"sleep": interval}]))])
    return g.clients_gen(client_gen, nem_gen)


def standard_checker(model: "m.Model", algorithm: str = "auto",
                     **linear_opts: Any) -> "facade.Compose":
    """linearizable + timeline + latency/rate charts + stats — the
    composition every register-family suite ships."""
    return facade.compose({
        "linear": facade.linearizable(model, algorithm=algorithm,
                                      **linear_opts),
        "timeline": timeline.html(),
        "latency": perf.latency_graph(),
        "rate": perf.rate_graph(),
        "stats": facade.stats(),
    })
