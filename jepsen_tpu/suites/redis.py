"""Redis suite over RESP — the redis-family register test of the
upstream era (SURVEY.md §2.5), driving the REAL wire protocol: a raw
TCP socket speaking RESP2 (``GET``/``SET``, CAS as the canonical atomic
``EVAL`` compare-and-set script), checked against the ``cas_register``
model.

By default the test boots one RESP-dialect server per node
(:class:`jepsen_tpu.fake.resp.RespKVFrontend`, backed by the fake
cluster so nemesis faults surface as genuine ``-CLUSTERDOWN`` errors
and socket timeouts) through the DB protocol. Point ``endpoints`` at a
real Redis's ``(host, port)`` pairs and the identical client/checker
pipeline applies — the CAS script is real Lua a real server executes
atomically.

Completion mapping:

- ``+OK`` / bulk / ``:1``  → :ok
- nil bulk on read         → :ok read of nil (key unset)
- ``:0`` from the script   → :fail (CAS compare failed — no effect)
- ``-CLUSTERDOWN`` / conn refused → :fail (definitely no effect)
- parse-time rejections (``-ERR unknown command`` / arity /
  ``-WRONGTYPE`` …) → :fail (rejected before execution, no effect)
- socket timeout / conn reset mid-command / other ``-ERR`` replies
  (possible effect before the error) → :info (indeterminate)
"""
from __future__ import annotations

import socket
from typing import Any, Dict, Optional, Tuple

from jepsen_tpu import client as cl
from jepsen_tpu import db as db_mod
from jepsen_tpu import generators as g
from jepsen_tpu import models, nemesis, util
from jepsen_tpu.fake import FakeCluster
from jepsen_tpu.fake.resp import CAS_SCRIPT, RespKVFrontend
from jepsen_tpu.op import Op
from jepsen_tpu.suites._common import nemesis_schedule, standard_checker


class RespError(Exception):
    """A RESP ``-...`` error reply."""

    def __init__(self, message: str):
        super().__init__(message)
        self.message = message


# error-reply prefixes a server emits while rejecting a command BEFORE
# executing it — definitely no effect, so the op completes :fail
_DEFINITE_REJECTIONS = (
    "ERR unknown command",
    "ERR wrong number of arguments",
    "WRONGTYPE",
)


class RespClient(cl.Client):
    """Minimal RESP2 client on a raw socket (one connection per worker,
    re-dialed after errors). ``test["endpoints"]`` maps node →
    ``(host, port)``."""

    def __init__(self, key: str = "r", timeout_s: float = 1.0):
        self.key = key
        self.timeout_s = timeout_s
        self.addr: Optional[Tuple[str, int]] = None
        self._sock: Optional[socket.socket] = None
        self._rf = None

    def open(self, test, node):
        c = type(self)(self.key, self.timeout_s)
        c.addr = tuple(test["endpoints"][node])
        return c

    def close(self, test):
        self._drop()

    def _drop(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._sock, self._rf = None, None

    def _connect(self):
        if self._sock is None:
            s = socket.create_connection(self.addr, timeout=self.timeout_s)
            s.settimeout(self.timeout_s)
            self._sock = s
            self._rf = s.makefile("rb")

    def _command(self, *parts: str) -> Any:
        """Send one RESP array command, return the decoded reply
        (str bulk / int / None nil / ``+`` simple string); raises
        :class:`RespError` on ``-`` replies, OS errors on transport."""
        self._connect()
        enc = [f"*{len(parts)}\r\n".encode()]
        for p in parts:
            b = p.encode()
            enc.append(b"$%d\r\n%s\r\n" % (len(b), b))
        self._sock.sendall(b"".join(enc))
        return self._reply()

    def _reply(self) -> Any:
        line = self._rf.readline()
        if not line:
            raise ConnectionError("server closed connection")
        kind, rest = line[:1], line[1:].rstrip(b"\r\n")
        if kind == b"+":
            return rest.decode()
        if kind == b"-":
            raise RespError(rest.decode())
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            if n < 0:
                return None
            # exact reads: a short read at EOF must surface as a broken
            # connection (-> :info), never as a truncated :ok value
            data = self._read_exact(n)
            self._read_exact(2)
            return data.decode()
        raise ValueError(f"bad RESP reply {line!r}")

    def _read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self._rf.read(n - len(buf))
            if not chunk:
                raise ConnectionError("server closed mid-reply")
            buf += chunk
        return buf

    def invoke(self, test, op: Op) -> Op:
        try:
            return self._invoke(op)
        except RespError as e:
            if e.message.startswith("CLUSTERDOWN"):
                return cl.fail(op, "node unavailable")
            # a complete error reply the server produced while PARSING the
            # command (unknown command, arity, type) is a definite
            # no-effect rejection — a clean :fail that keeps checker
            # concurrency down. Anything else (script errors mid-write,
            # "-ERR timeout", LOADING, …) may have applied an effect
            # before failing, so it stays indeterminate :info.
            if e.message.startswith(_DEFINITE_REJECTIONS):
                return cl.fail(op, e.message)
            return cl.info(op, e.message)
        except ConnectionRefusedError:
            self._drop()
            return cl.fail(op, "connection refused")
        except (socket.timeout, TimeoutError, ConnectionError, OSError) as e:
            # a timed-out or broken connection may have delivered the
            # command: indeterminate, and the socket is poisoned (a late
            # reply would desynchronize framing) — re-dial next op
            self._drop()
            return cl.info(op, type(e).__name__)

    def _invoke(self, op: Op) -> Op:
        if op.f == "read":
            raw = self._command("GET", self.key)
            if raw is None:
                return cl.ok(op, None)
            return cl.ok(op, int(raw) if raw.lstrip("-").isdigit() else raw)
        if op.f == "write":
            self._command("SET", self.key, str(op.value))
            return cl.ok(op)
        if op.f == "cas":
            old, new = op.value
            r = self._command("EVAL", CAS_SCRIPT, "1", self.key,
                              str(old), str(new))
            if r == 1:
                return cl.ok(op)
            return cl.fail(op, "cas compare failed")
        raise ValueError(f"unknown f {op.f!r}")


class FakeRedisDB(db_mod.DB):
    """DB-protocol lifecycle for the per-node RESP front-ends (upstream
    redis suites install and start real redis-server here)."""

    def __init__(self, cluster: FakeCluster):
        import threading
        self.cluster = cluster
        self._frontend: Optional[RespKVFrontend] = None
        self._lock = threading.Lock()

    def setup(self, test, node):
        with self._lock:
            if self._frontend is None:
                self._frontend = RespKVFrontend(self.cluster).start()
                test["endpoints"] = self._frontend.endpoints

    def teardown(self, test, node):
        with self._lock:
            if self._frontend is not None:
                self._frontend.stop()
                self._frontend = None


def redis_test(mode: str = "linearizable", *,
               time_limit: float = 5.0, concurrency: int = 5,
               seed: Optional[int] = None, nodes: Any = 5,
               algorithm: str = "auto", with_nemesis: bool = True,
               nemesis_interval: float = 1.0,
               store: bool = False) -> Dict[str, Any]:
    """CAS-register test over RESP (redis-style upstream suite)."""
    node_names = util.node_names(nodes)
    cluster = FakeCluster(node_names, mode=mode, seed=seed)
    client_gen: g.GenLike = g.TimeLimit(
        time_limit, g.Stagger(0.002, g.register_workload(seed=seed),
                              seed=seed))
    nem: Optional[nemesis.Nemesis] = None
    generator: g.GenLike = client_gen
    if with_nemesis:
        nem = nemesis.partition_random_halves(seed=seed)
        generator = nemesis_schedule(client_gen, nemesis_interval)
    return {
        "name": f"redis-{mode}",
        "nodes": node_names,
        "cluster": cluster,
        "db": FakeRedisDB(cluster),
        "client": RespClient("r"),
        "nemesis": nem,
        "generator": generator,
        "model": models.cas_register(),
        "checker": standard_checker(models.cas_register(),
                                    algorithm=algorithm),
        "concurrency": concurrency,
        "store": store,
        "run-time-limit": max(60.0, time_limit * 6),
        "op-timeout": 5.0,
    }
