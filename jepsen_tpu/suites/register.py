"""Etcd-style CAS-register suite — upstream ``etcd/`` (SURVEY.md §2.5):
read/write/cas ops on a single register (or many independent ones),
partitions from the nemesis, linearizability checking with the
``cas_register`` model.

Runs against the in-proc :class:`~jepsen_tpu.fake.cluster.FakeCluster` by
default (``mode="linearizable"`` should pass; ``mode="sloppy"`` should
fail — both asserted by the E2E tests). Pass a real client for a real
system.
"""
from __future__ import annotations

import random
from typing import Any, Dict, Mapping, Optional

from jepsen_tpu import client as cl
from jepsen_tpu import generators as g
from jepsen_tpu import independent, models, nemesis, util
from jepsen_tpu.suites import _common
from jepsen_tpu.checkers import facade, perf, timeline
from jepsen_tpu.fake import FakeCluster, Unavailable
from jepsen_tpu.fake.cluster import FakeTimeout
from jepsen_tpu.op import Op


class KVClient(cl.Client):
    """Client for the fake cluster's KV API; the value convention matches
    the upstream etcd suite: ``read -> value``, ``write value``,
    ``cas [old, new]``. With ``key=None``, values are ``[k, v]``
    independent tuples."""

    def __init__(self, key: Any = "r"):
        self.key = key
        self.node: Any = None

    def open(self, test, node):
        c = type(self)(self.key)
        c.node = node
        return c

    def _call(self, cluster: FakeCluster, key: Any, op: Op):
        if op.f == "read":
            return cl.ok(op, cluster.read(self.node, key))
        if op.f == "write":
            cluster.write(self.node, key, op.value if self.key is not None
                          else op.value[1])
            return cl.ok(op)
        if op.f == "cas":
            old, new = op.value if self.key is not None else op.value[1]
            if cluster.cas(self.node, key, old, new):
                return cl.ok(op)
            return cl.fail(op, "cas mismatch")
        raise ValueError(f"unknown f {op.f!r}")

    def invoke(self, test, op):
        cluster: FakeCluster = test["cluster"]
        if self.key is not None:
            key, value = self.key, op.value
        else:                                   # independent [k, v] tuple
            key, value = op.value[0], op.value[1]
        try:
            res = self._call(cluster, key, op)
            if self.key is None and res.type == "ok" and op.f == "read":
                res = res.with_(value=[key, res.value])
            return res
        except Unavailable as e:
            return cl.fail(op, str(e))
        except FakeTimeout as e:
            return cl.info(op, str(e))


def workload(hi: int = 5, seed: Optional[int] = None) -> g.Generator:
    """The classic r/w/cas mix (shared stock workload)."""
    return g.register_workload(hi=hi, seed=seed)


def register_test(mode: str = "linearizable", *,
                  time_limit: float = 5.0, n_ops: Optional[int] = None,
                  concurrency: int = 5, seed: Optional[int] = None,
                  nodes: Any = 5, algorithm: str = "auto",
                  with_nemesis: bool = True, store: bool = False,
                  nemesis_interval: float = 1.0) -> Dict[str, Any]:
    """Build the test map (upstream ``etcd/src/.../runner.clj``'s
    ``tests`` fn). ``nodes``: a count or explicit node names."""
    node_names = util.node_names(nodes)
    cluster = FakeCluster(node_names, mode=mode, seed=seed)
    client_gen: g.GenLike = g.Stagger(0.001, workload(seed=seed), seed=seed)
    if n_ops is not None:
        client_gen = g.Limit(n_ops, client_gen)
    else:
        client_gen = g.TimeLimit(time_limit, client_gen)
    nem: Optional[nemesis.Nemesis] = None
    generator: g.GenLike = client_gen
    if with_nemesis:
        nem = nemesis.partition_random_halves(seed=seed)
        generator = _common.nemesis_schedule(client_gen, nemesis_interval)
    return {
        "name": f"register-{mode}",
        "nodes": node_names,
        "cluster": cluster,
        "client": KVClient("r"),
        "nemesis": nem,
        "generator": generator,
        "model": models.cas_register(),
        "checker": _common.standard_checker(models.cas_register(),
                                            algorithm=algorithm),
        "concurrency": concurrency,
        "store": store,
        "run-time-limit": max(60.0, time_limit * 6),
        "op-timeout": 5.0,
    }


def independent_test(mode: str = "linearizable", *, keys: int = 8,
                     ops_per_key: int = 50, concurrency: int = 8,
                     seed: Optional[int] = None, store: bool = False,
                     with_nemesis: bool = False) -> Dict[str, Any]:
    """Multi-key variant (upstream independent/concurrent-generator usage):
    the checker fans per-key sub-histories into one batched device call."""
    node_names = [f"n{i + 1}" for i in range(5)]
    cluster = FakeCluster(node_names, mode=mode, seed=seed)
    gen_keys = g.concurrent_generator(
        max(1, concurrency // 2), (f"k{i}" for i in range(keys)),
        lambda key: g.Limit(ops_per_key, workload(seed=seed)))
    nem = nemesis.partition_random_halves(seed=seed) if with_nemesis else None
    generator: g.GenLike = gen_keys
    if with_nemesis:
        generator = g.clients_gen(gen_keys, g.cycle(lambda: g.Seq(
            [{"f": "start"}, {"sleep": 0.5}, {"f": "stop"},
             {"sleep": 0.5}])))
    return {
        "name": f"register-independent-{mode}",
        "nodes": node_names,
        "cluster": cluster,
        "client": KVClient(None),
        "nemesis": nem,
        "generator": generator,
        "model": models.cas_register(),
        "checker": independent.checker(
            facade.linearizable(models.cas_register())),
        "concurrency": concurrency,
        "store": store,
        "run-time-limit": 120.0,
        "op-timeout": 5.0,
    }
