"""Grow-only-set suite — upstream ``elasticsearch/`` / ``mongodb/``-style
set workloads (SURVEY.md §2.5): clients ``add`` unique integers under a
partition nemesis, then a final ``read`` returns the set contents, checked
with ``jepsen.checker/set`` (no acknowledged add may be lost, nothing
never-attempted may appear).

Runs against :class:`~jepsen_tpu.fake.cluster.FakeCluster`:
``mode="linearizable"`` must pass; ``mode="sloppy"`` replicates adds only
to reachable peers and never merges, so partitioned adds vanish from the
final read — the classic lost-updates result the checker must catch.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

from jepsen_tpu import client as cl
from jepsen_tpu import generators as g
from jepsen_tpu import nemesis, util
from jepsen_tpu.suites import partition_cycle
from jepsen_tpu.checkers import facade, perf, timeline
from jepsen_tpu.fake import FakeCluster, Unavailable
from jepsen_tpu.fake.cluster import FakeTimeout


class SetClient(cl.Client):
    def __init__(self, key: Any = "s"):
        self.key = key
        self.node: Any = None

    def open(self, test, node):
        c = type(self)(self.key)
        c.node = node
        return c

    def invoke(self, test, op):
        cluster: FakeCluster = test["cluster"]
        try:
            if op.f == "add":
                cluster.sadd(self.node, self.key, op.value)
                return cl.ok(op)
            if op.f == "read":
                res = cl.ok(op, cluster.sread(self.node, self.key))
                test["_set_read_ok"] = True     # final-read phase stops here
                return res
            raise ValueError(f"unknown f {op.f!r}")
        except Unavailable as e:
            return cl.fail(op, str(e))
        except FakeTimeout as e:
            return cl.info(op, str(e))


def set_test(mode: str = "linearizable", *, time_limit: float = 5.0,
             concurrency: int = 5, seed: Optional[int] = None,
             with_nemesis: bool = True, store: bool = False,
             nemesis_interval: float = 1.0, nodes: Any = 5,
             universe: Optional[int] = 12) -> Dict[str, Any]:
    """``universe`` bounds the add workload to that many unique
    elements and composes a ``linear`` checker over the int-coded
    :func:`jepsen_tpu.models.bounded_set` model — a memo-enumerable
    state space (<= 2**universe), so the set suite's history reaches
    the dense-walk device engines instead of only the host invariant
    checker (ROADMAP item 3(a)). ``universe=None`` restores the
    unbounded workload with host-only checking."""
    from jepsen_tpu import models

    node_names = util.node_names(nodes)
    cluster = FakeCluster(node_names, mode=mode, seed=seed)
    adds: g.GenLike = g.TimeLimit(
        time_limit, g.Stagger(0.001, g.unique_values("add"), seed=seed))
    if universe is not None:
        # unique_values counts 0,1,2,...: capping the COUNT at the
        # universe also caps every VALUE inside it
        adds = g.Limit(universe, adds)
    # Final reads retry (paced) until one succeeds — a fixed attempt
    # budget could be consumed entirely by a not-yet-healed partition,
    # turning a healthy run into {"valid": "unknown"}. The barrier makes
    # every worker finish its in-flight add before any read fires
    # (upstream gen/phases + gen/synchronize) — without it the last adds
    # race the read and show up as spurious "lost" elements. The
    # once-sleep is only a grace pause for the nemesis's final heal; the
    # run-time-limit bounds the retry loop if the cluster never heals.
    final_reads = g.synchronize(g.Seq(
        [{"sleep": 0.3},
         g.Stagger(0.02, g.Fn(
             lambda test, process: {"f": "read", "value": None}
             if not test.get("_set_read_ok") else None))]))
    client_seq = g.Seq([adds, final_reads])
    nem: Optional[nemesis.Nemesis] = None
    if with_nemesis:
        nem = nemesis.partition_random_halves(seed=seed)
        generator: g.GenLike = g.clients_gen(
            client_seq, partition_cycle(time_limit, nemesis_interval,
                                        seed=seed))
    else:
        generator = g.clients_gen(client_seq)
    return {
        "name": f"set-{mode}",
        "nodes": node_names,
        "cluster": cluster,
        "client": SetClient(),
        "nemesis": nem,
        "generator": generator,
        "checker": facade.compose({
            "set": facade.set_checker(),
            **({"linear": facade.linearizable(
                    models.bounded_set(universe))}
               if universe is not None else {}),
            "timeline": timeline.html(),
            "latency": perf.latency_graph(),
            "rate": perf.rate_graph(),
            "stats": facade.stats(),
        }),
        "concurrency": concurrency,
        "store": store,
        "run-time-limit": max(60.0, time_limit * 6),
        "op-timeout": 5.0,
    }
