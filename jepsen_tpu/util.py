"""Small shared utilities — upstream: ``jepsen/src/jepsen/util.clj``
(SURVEY.md §2.1). Grows alongside the harness (timeouts, retries,
majority math); for now the helpers shared by history packing and EDN.
"""
from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Any, Callable, Optional, TypeVar

T = TypeVar("T")


def hashable(v: Any) -> Any:
    """Deep-freeze a JSON/EDN-style value into a hashable equivalent
    (lists → tuples, dicts → sorted kv-tuples, sets → frozensets)."""
    if isinstance(v, list):
        return tuple(hashable(x) for x in v)
    if isinstance(v, tuple):
        return tuple(hashable(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted(((hashable(k), hashable(x)) for k, x in v.items()),
                            key=repr))
    if isinstance(v, (set, frozenset)):
        return frozenset(hashable(x) for x in v)
    return v


def hashable_seq(v: Any) -> tuple:
    """``tuple(hashable(x) for x in v)`` with the common-case fast
    path: when ``tuple(v)`` already hashes (every element deeply
    hashable — list-append read values are almost always flat int/str
    lists), return it directly. ``hashable`` is the identity on
    hashable elements, so the two forms are equal (and hash-equal);
    any nested unhashable raises TypeError from ``hash`` and takes
    the deep-freeze path. The per-element generator was ~80% of txn
    dependency inference at the 100k-txn rung (~1 µs and two calls
    per read element, x ~50M elements)."""
    try:
        tv = tuple(v)
        hash(tv)
        return tv
    except TypeError:
        return tuple(hashable(x) for x in v)


# built eagerly: a lazy first-entrant build races (two threads could
# each install their own lock and count depth without exclusion)
_GC_PAUSE_LOCK = threading.Lock()
_GC_PAUSE_DEPTH = 0
_GC_PAUSE_RESUME = False


@contextmanager
def gc_paused():
    """Pause the cyclic GC across a bulk-allocation phase. The txn
    collect/infer loops build millions of LONG-LIVED tuples; every
    gen0/gen1 collection re-scans the growing survivor set, which
    turns a linear host pass super-linear (measured 2.58 s -> 1.62 s
    on the 100k-txn rung). Nothing allocated there is cyclic garbage,
    so collection during the phase is pure overhead. Re-entrant and
    thread-counted: the first entrant disables (only if GC was on),
    the last exit re-enables — a bounded pause, never a permanent
    flip; a caller that had GC off keeps it off."""
    import gc
    global _GC_PAUSE_DEPTH, _GC_PAUSE_RESUME
    with _GC_PAUSE_LOCK:
        _GC_PAUSE_DEPTH += 1
        if _GC_PAUSE_DEPTH == 1:
            _GC_PAUSE_RESUME = gc.isenabled()
            if _GC_PAUSE_RESUME:
                gc.disable()
    try:
        yield
    finally:
        with _GC_PAUSE_LOCK:
            _GC_PAUSE_DEPTH -= 1
            if _GC_PAUSE_DEPTH == 0 and _GC_PAUSE_RESUME:
                gc.enable()


def majority(n: int) -> int:
    """Smallest majority of ``n`` nodes (upstream ``jepsen.util/majority``)."""
    return n // 2 + 1


def node_names(nodes) -> list:
    """Normalize a suite's ``nodes`` argument: a count becomes
    ``["n1", ..., "nN"]``, a bare string is ONE node name (not a char
    sequence), anything else is taken as a list of names."""
    if isinstance(nodes, int):
        return [f"n{i + 1}" for i in range(nodes)]
    if isinstance(nodes, str):
        return [nodes]
    return list(nodes)


def relative_time_nanos(start: float) -> int:
    """Nanoseconds since ``start`` (a ``time.monotonic()`` instant) —
    upstream ``jepsen.util/relative-time-nanos``."""
    return int((time.monotonic() - start) * 1e9)


def with_retry(fn: Callable[[], T], retries: int = 3,
               delay: float = 0.1,
               exceptions: tuple = (Exception,)) -> T:
    """Call ``fn``, retrying on failure (upstream ``jepsen.util/with-retry``)."""
    last: Optional[BaseException] = None
    for attempt in range(retries + 1):
        try:
            return fn()
        except exceptions as e:  # noqa: PERF203
            last = e
            if attempt < retries:
                time.sleep(delay * (2 ** attempt))
    assert last is not None
    raise last


def meh(fn: Callable[[], T]) -> Optional[T]:
    """Run ``fn``, swallowing exceptions (upstream ``jepsen.util/meh``)."""
    try:
        return fn()
    except Exception:
        return None


def map_vals(f: Callable[[Any], Any], d: dict) -> dict:
    """Map ``f`` over a dict's values (upstream ``jepsen.util/map-vals``)."""
    return {k: f(v) for k, v in d.items()}


def pprint_str(x: Any) -> str:
    """Pretty-print to a string (upstream ``jepsen.util/pprint-str``)."""
    import pprint
    return pprint.pformat(x, width=78)


def log_op(op: Any) -> None:
    """Log one operation in the jepsen console style (upstream
    ``jepsen.util/log-op``)."""
    import logging
    logging.getLogger("jepsen.ops").info(
        "%s\t%s\t%s\t%r", op.process, op.type, op.f, op.value)


class with_thread_name:
    """Context manager renaming the current thread (upstream
    ``jepsen.util/with-thread-name``) — thread names show in log lines."""

    def __init__(self, name: str):
        self.name = name

    def __enter__(self):
        import threading
        self._old = threading.current_thread().name
        threading.current_thread().name = self.name
        return self

    def __exit__(self, *exc):
        import threading
        threading.current_thread().name = self._old
