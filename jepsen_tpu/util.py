"""Small shared utilities — upstream: ``jepsen/src/jepsen/util.clj``
(SURVEY.md §2.1). Grows alongside the harness (timeouts, retries,
majority math); for now the helpers shared by history packing and EDN.
"""
from __future__ import annotations

import time
from typing import Any, Callable, Optional, TypeVar

T = TypeVar("T")


def hashable(v: Any) -> Any:
    """Deep-freeze a JSON/EDN-style value into a hashable equivalent
    (lists → tuples, dicts → sorted kv-tuples, sets → frozensets)."""
    if isinstance(v, list):
        return tuple(hashable(x) for x in v)
    if isinstance(v, tuple):
        return tuple(hashable(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted(((hashable(k), hashable(x)) for k, x in v.items()),
                            key=repr))
    if isinstance(v, (set, frozenset)):
        return frozenset(hashable(x) for x in v)
    return v


def majority(n: int) -> int:
    """Smallest majority of ``n`` nodes (upstream ``jepsen.util/majority``)."""
    return n // 2 + 1


def node_names(nodes) -> list:
    """Normalize a suite's ``nodes`` argument: a count becomes
    ``["n1", ..., "nN"]``, a bare string is ONE node name (not a char
    sequence), anything else is taken as a list of names."""
    if isinstance(nodes, int):
        return [f"n{i + 1}" for i in range(nodes)]
    if isinstance(nodes, str):
        return [nodes]
    return list(nodes)


def relative_time_nanos(start: float) -> int:
    """Nanoseconds since ``start`` (a ``time.monotonic()`` instant) —
    upstream ``jepsen.util/relative-time-nanos``."""
    return int((time.monotonic() - start) * 1e9)


def with_retry(fn: Callable[[], T], retries: int = 3,
               delay: float = 0.1,
               exceptions: tuple = (Exception,)) -> T:
    """Call ``fn``, retrying on failure (upstream ``jepsen.util/with-retry``)."""
    last: Optional[BaseException] = None
    for attempt in range(retries + 1):
        try:
            return fn()
        except exceptions as e:  # noqa: PERF203
            last = e
            if attempt < retries:
                time.sleep(delay * (2 ** attempt))
    assert last is not None
    raise last


def meh(fn: Callable[[], T]) -> Optional[T]:
    """Run ``fn``, swallowing exceptions (upstream ``jepsen.util/meh``)."""
    try:
        return fn()
    except Exception:
        return None


def map_vals(f: Callable[[Any], Any], d: dict) -> dict:
    """Map ``f`` over a dict's values (upstream ``jepsen.util/map-vals``)."""
    return {k: f(v) for k, v in d.items()}


def pprint_str(x: Any) -> str:
    """Pretty-print to a string (upstream ``jepsen.util/pprint-str``)."""
    import pprint
    return pprint.pformat(x, width=78)


def log_op(op: Any) -> None:
    """Log one operation in the jepsen console style (upstream
    ``jepsen.util/log-op``)."""
    import logging
    logging.getLogger("jepsen.ops").info(
        "%s\t%s\t%s\t%r", op.process, op.type, op.f, op.value)


class with_thread_name:
    """Context manager renaming the current thread (upstream
    ``jepsen.util/with-thread-name``) — thread names show in log lines."""

    def __init__(self, name: str):
        self.name = name

    def __enter__(self):
        import threading
        self._old = threading.current_thread().name
        threading.current_thread().name = self.name
        return self

    def __exit__(self, *exc):
        import threading
        threading.current_thread().name = self._old
