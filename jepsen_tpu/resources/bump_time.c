/* Clock-fault helper, compiled on DB nodes by jepsen_tpu.nemesis.ClockNemesis
 * (role of the upstream jepsen resources/bump-time.c; independent
 * implementation).
 *
 *   bump-time bump <delta-ms>                     jump the clock once
 *   bump-time strobe <delta-ms> <period-ms> <duration-ms>
 *                                                 flap the clock +-delta
 *   bump-time reset                               best-effort NTP-less reset
 *                                                 (clears nothing; exits 0 so
 *                                                 drivers fall through to
 *                                                 ntpdate/chrony)
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/time.h>
#include <unistd.h>

static int bump(long delta_ms) {
    struct timeval tv;
    if (gettimeofday(&tv, NULL) != 0) { perror("gettimeofday"); return 1; }
    long long us = (long long)tv.tv_sec * 1000000LL + tv.tv_usec
                 + (long long)delta_ms * 1000LL;
    tv.tv_sec  = (time_t)(us / 1000000LL);
    tv.tv_usec = (suseconds_t)(us % 1000000LL);
    if (settimeofday(&tv, NULL) != 0) { perror("settimeofday"); return 1; }
    return 0;
}

int main(int argc, char **argv) {
    if (argc < 2) { fprintf(stderr, "usage: bump-time bump|strobe|reset ...\n"); return 2; }
    if (strcmp(argv[1], "bump") == 0 && argc >= 3)
        return bump(atol(argv[2]));
    if (strcmp(argv[1], "strobe") == 0 && argc >= 5) {
        long delta = atol(argv[2]), period = atol(argv[3]), dur = atol(argv[4]);
        long elapsed = 0; int sign = 1;
        while (elapsed < dur) {
            if (bump(sign * delta)) return 1;
            sign = -sign;
            usleep((useconds_t)(period * 1000));
            elapsed += period;
        }
        return 0;
    }
    if (strcmp(argv[1], "reset") == 0)
        return 0;
    fprintf(stderr, "bad args\n");
    return 2;
}
