"""History preprocessing — upstream: ``knossos/src/knossos/history.clj``
(``index``, ``pair-index``, ``complete``) plus the history vector built by
``jepsen/src/jepsen/core.clj``'s worker loop (SURVEY.md §2.2, §3.2).

A history is a list of :class:`~jepsen_tpu.op.Op` in wall-clock order:
``invoke`` events interleaved with their ``ok`` / ``fail`` / ``info``
completions. This module turns that into the analyzable form used by every
checker:

- :func:`index` — assign dense integer ``index`` to each event.
- :func:`pair` — match each invocation with its completion (per process).
- :func:`analysis_entries` — the checker's input: failed ops stripped
  (a ``fail`` completion asserts the op did not take effect), nemesis ops
  dropped, invoke values completed from the ``ok`` event (a read's observed
  value lives on the completion), crashed ops (``info`` / dangling invokes)
  kept forever-pending. Matches knossos verdict semantics (SURVEY.md §7
  "hard parts" #4).
- :func:`pack` — structure-of-arrays int encoding for the JAX solver.

Serialization: :func:`save_jsonl` / :func:`load_jsonl` (this framework's
native crash-safe append format) and :func:`load_edn` / :func:`save_edn`
(interop with Jepsen's on-disk ``history.edn`` and the knossos ``data/``
fixtures).
"""
from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from jepsen_tpu import edn
from jepsen_tpu.op import FAIL, INFO, INVOKE, OK, Op
from jepsen_tpu.util import hashable


def index(history: Sequence[Op]) -> List[Op]:
    """Assign dense integer ``index`` to every op (upstream
    ``knossos.history/index``)."""
    return [op.with_(index=i) for i, op in enumerate(history)]


@dataclass(frozen=True)
class Pair:
    """An invocation and its completion (``None`` when the op never
    completed — the process crashed)."""
    invoke: Op
    complete: Optional[Op]

    @property
    def crashed(self) -> bool:
        return self.complete is None or self.complete.type == INFO

    @property
    def failed(self) -> bool:
        return self.complete is not None and self.complete.type == FAIL


def pair(history: Sequence[Op]) -> List[Pair]:
    """Match invocations to completions, one outstanding op per process
    (upstream ``knossos.history/pair-index``). Ops must be ``index``-ed.

    Nemesis and bare ``info`` events without a pending invocation are
    ignored — they carry no client semantics.
    """
    pending: Dict[Any, Op] = {}
    pairs: List[Pair] = []
    for op in history:
        if op.process == "nemesis":
            continue
        if op.type == INVOKE:
            if op.process in pending:
                raise ValueError(
                    f"process {op.process} invoked {op} while "
                    f"{pending[op.process]} is still pending")
            pending[op.process] = op
        else:
            inv = pending.pop(op.process, None)
            if inv is None:
                # completion with no invocation: stray info (e.g. nemesis on a
                # numeric process) — ignore, like knossos does.
                continue
            pairs.append(Pair(inv, op))
    # dangling invokes = crashed ops, forever pending
    for inv in pending.values():
        pairs.append(Pair(inv, None))
    pairs.sort(key=lambda p: p.invoke.index)
    return pairs


@dataclass(frozen=True)
class Entry:
    """One logical operation, ready for analysis.

    ``eid`` is the dense entry id (invocation order). ``inv_ev``/``ret_ev``
    are event ranks usable for real-time ordering; ``ret_ev`` is
    ``INF_EV`` (> any real rank) for crashed ops. ``op`` is the merged op:
    ``f`` from the invocation, ``value`` preferring the completion's (the
    observed result), as in ``knossos.history/complete``.
    """
    eid: int
    op: Op
    inv_ev: int
    ret_ev: int
    crashed: bool

    @property
    def process(self) -> Any:
        return self.op.process


def analysis_entries(history: Sequence[Op]) -> List[Entry]:
    """History → entries for the linearizability search.

    Drops nemesis ops and failed pairs; completes values; keeps crashed ops
    pending forever (they may have taken effect at any later point, or
    never — the searches explore both).
    """
    hist = history
    if any(op.index < 0 for op in hist):
        hist = index(list(hist))
    inf_ev = 2 * len(hist) + 2
    entries: List[Entry] = []
    for p in pair(hist):
        if p.failed:
            continue
        inv, comp = p.invoke, p.complete
        value = inv.value
        crashed = p.crashed
        if comp is not None and comp.type == OK:
            value = comp.value if comp.value is not None else inv.value
        merged = inv.with_(value=value)
        entries.append(Entry(
            eid=len(entries),
            op=merged,
            inv_ev=inv.index,
            ret_ev=comp.index if (comp is not None and not crashed) else inf_ev,
            crashed=crashed,
        ))
    return entries


@dataclass(frozen=True)
class PackedHistory:
    """Structure-of-arrays encoding of the analysis entries (SURVEY.md §7.1).

    Entries are sorted by invocation; ``inv_ev``/``ret_ev`` int32 event
    ranks (``ret_ev = inf_ev`` for crashed ops); ``op_id`` indexes into
    ``distinct_ops`` (the per-history distinct (f, value) alphabet that the
    model memo table is built over); ``crashed`` marks forever-pending ops.
    Only these arrays cross into the JAX solver.
    """
    n: int
    inv_ev: np.ndarray      # i32[n]
    ret_ev: np.ndarray      # i32[n]
    op_id: np.ndarray       # i32[n]
    crashed: np.ndarray     # bool[n]
    inf_ev: int
    distinct_ops: Tuple[Op, ...]
    entries: Tuple[Entry, ...]
    # hashable (f, value) identity per distinct op, aligned with
    # ``distinct_ops`` — precomputed at pack time so the per-key batch
    # checkers (union-alphabet mapping, memo-cache signatures) never
    # recompute ``hashable`` over thousands of keys' op values
    op_keys: Tuple[Any, ...] = ()

    @property
    def n_ok(self) -> int:
        return int(self.n - self.crashed.sum())


def pack(history: Sequence[Op]) -> PackedHistory:
    """Pack a raw history into int arrays; the model-specific transition
    table is layered on by :func:`jepsen_tpu.models.memo.memo`."""
    entries = analysis_entries(history)
    return pack_entries(entries)


def pack_entries(entries: Sequence[Entry]) -> PackedHistory:
    # the checkers' candidate scan requires invocation order; enforce it
    # here rather than trusting callers.
    entries = sorted(entries, key=lambda e: e.inv_ev)
    n = len(entries)
    inf_ev = max([2] + [e.ret_ev for e in entries] + [e.inv_ev + 1 for e in entries])
    inv_ev = np.zeros(n, np.int32)
    ret_ev = np.zeros(n, np.int32)
    op_id = np.zeros(n, np.int32)
    crashed = np.zeros(n, bool)
    distinct: Dict[Tuple[Any, Any], int] = {}
    ops: List[Op] = []
    for i, e in enumerate(entries):
        inv_ev[i] = e.inv_ev
        ret_ev[i] = e.ret_ev
        crashed[i] = e.crashed
        key = (e.op.f, hashable(e.op.value))
        if key not in distinct:
            distinct[key] = len(ops)
            ops.append(e.op)
        op_id[i] = distinct[key]
    return PackedHistory(
        n=n, inv_ev=inv_ev, ret_ev=ret_ev, op_id=op_id, crashed=crashed,
        inf_ev=int(inf_ev), distinct_ops=tuple(ops), entries=tuple(entries),
        op_keys=tuple(distinct))


def op_keys_of(packed: PackedHistory) -> Tuple[Any, ...]:
    """The hashable distinct-op identities of ``packed``, from the
    pack-time cache when present (PackedHistory instances built by
    other constructors may lack it)."""
    if len(packed.op_keys) == len(packed.distinct_ops):
        return packed.op_keys
    return tuple((op.f, hashable(op.value)) for op in packed.distinct_ops)


# -- serialization -----------------------------------------------------------

def save_jsonl(history: Iterable[Op], path: str) -> None:
    with open(path, "w") as f:
        for op in history:
            f.write(json.dumps(op.to_dict(), default=str) + "\n")


def load_jsonl(path: str) -> List[Op]:
    out: List[Op] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(Op.from_dict(json.loads(line)))
    return index(out) if out and out[0].index < 0 else out


def load_edn(path: str) -> List[Op]:
    """Read a Jepsen/knossos EDN history (a top-level vector of op maps, or
    one op map per line as in ``history.edn``)."""
    with open(path) as f:
        text = f.read()
    data = edn.loads_all(text)
    if len(data) == 1 and isinstance(data[0], list):
        data = data[0]
    ops = [Op.from_dict(edn.to_plain(d)) for d in data]
    return index(ops) if ops and ops[0].index < 0 else ops


def save_edn(history: Iterable[Op], path: str) -> None:
    with open(path, "w") as f:
        for op in history:
            f.write(edn.dumps(op.to_dict()) + "\n")
