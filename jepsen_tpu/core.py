"""Test orchestrator — upstream ``jepsen/src/jepsen/core.clj``
(SURVEY.md §2.1 L6, §3.1): interpret a *test map* into a run.

A test is a plain dict (the upstream test map — §5.6 "the test map IS the
config system"): ``{"name", "nodes", "os", "db", "client", "nemesis",
"generator", "checker", "model", "concurrency", "remote"/"cluster", ...}``.

``run(test)`` drives the full lifecycle::

    os/db setup on all nodes → open clients → spawn one worker thread per
    logical process + a nemesis thread → each worker loop pulls an op
    sketch from the generator, appends the :invoke to the shared history,
    calls client.invoke, appends the completion → join → db teardown +
    log snarfing → checker analysis → store persistence.

Worker crash semantics match upstream exactly: an ``info`` completion
(client exception / timeout) kills the logical process — the op stays
forever-pending for the checkers — and the worker continues as process
``p + concurrency`` with a freshly opened client.

The history is appended under a lock and (crash-safely) streamed to
``history.jsonl`` as it grows — the upstream holds it in memory until
``store/save!`` (SURVEY.md §5 notes this as a weakness; fixed here).
"""
from __future__ import annotations

import contextvars
import json
import logging
import threading
import time as _time
from typing import Any, Dict, List, Mapping, Optional

from jepsen_tpu import db as db_mod
from jepsen_tpu import obs
from jepsen_tpu import os_setup
from jepsen_tpu.checkers.facade import check_safe
from jepsen_tpu.client import Client
from jepsen_tpu.generators import NEMESIS, Generator, gen
from jepsen_tpu.op import FAIL, INFO, INVOKE, OK, Op

log = logging.getLogger("jepsen.core")


class History:
    """Thread-safe append-only history with optional JSONL streaming and
    an optional observer (e.g. the online checker) notified of every op
    in append order."""

    def __init__(self, stream_path: Optional[str] = None,
                 observer: Optional[Any] = None):
        self._ops: List[Op] = []
        self._lock = threading.Lock()
        self._file = open(stream_path, "w") if stream_path else None
        self._observer = observer

    def append(self, op: Op) -> Op:
        with self._lock:
            op = op.with_(index=len(self._ops))
            self._ops.append(op)
            # after close() (timed-out workers completing late) the op is
            # still recorded in memory, just not streamed
            if self._file:
                self._file.write(json.dumps(op.to_dict(), default=str) + "\n")
                self._file.flush()
            if self._observer is not None:
                try:
                    self._observer(op)
                except Exception:                       # noqa: BLE001
                    pass                # observers must not break the run
        return op

    def snapshot(self) -> List[Op]:
        with self._lock:
            return list(self._ops)

    def close(self) -> None:
        with self._lock:
            if self._file:
                self._file.close()
                self._file = None


class _Worker:
    """One logical-process worker (upstream ``core/worker``)."""

    def __init__(self, test: Mapping, run: "_Run", wid: int,
                 generator: Generator):
        self.test = test
        self.run = run
        self.wid = wid                      # worker slot, fixed
        self.process: Any = wid             # logical process, bumps on crash
        self.generator = generator
        self.client: Optional[Client] = None
        # run under a copy of the spawning thread's context so obs
        # spans recorded by the worker reach the run's capture scope
        ctx = contextvars.copy_context()
        self.thread = threading.Thread(target=lambda: ctx.run(self._loop),
                                       daemon=True,
                                       name=f"jepsen-worker-{wid}")

    # -- client lifecycle ----------------------------------------------------
    def _node(self) -> Any:
        nodes = self.test.get("nodes") or [None]
        return nodes[self.wid % len(nodes)]

    def _open_client(self) -> Optional[Client]:
        proto = self.test.get("client")
        if proto is None:
            return None
        c = proto.open(self.test, self._node())
        c.setup(self.test)
        return c

    def _close_client(self) -> None:
        if self.client is not None:
            try:
                self.client.teardown(self.test)
                self.client.close(self.test)
            except Exception:                           # noqa: BLE001
                pass
            self.client = None

    # -- op loop -------------------------------------------------------------
    def _loop(self) -> None:
        name = "run.nemesis" if self.process == NEMESIS else "run.worker"
        with obs.span(name, wid=self.wid):
            self._loop_inner()

    def _loop_inner(self) -> None:
        test, run = self.test, self.run
        try:
            self.client = self._open_client()
        except Exception as e:                          # noqa: BLE001
            log.error("worker %s: client open failed: %s", self.wid, e)
            run.active.discard(self.process)
            return
        op_timeout = test.get("op-timeout")
        while not run.stop.is_set():
            try:
                sketch = self.generator.op(test, self.process)
            except Exception as e:                      # noqa: BLE001
                log.error("generator crashed for %s: %s", self.process, e)
                break
            if sketch is None:
                break
            if "sleep" in sketch and "f" not in sketch:
                _time.sleep(float(sketch["sleep"]))
                continue
            if sketch.get("pending"):
                _time.sleep(0.001)
                continue
            inv = Op(process=self.process, type=INVOKE,
                     f=sketch.get("f"), value=sketch.get("value"),
                     time=run.now_ns())
            inv = run.history.append(inv)
            completion = self._invoke(inv, op_timeout)
            completion = completion.with_(
                process=self.process, f=inv.f, time=run.now_ns(), index=-1)
            run.history.append(completion)
            if completion.type == INFO and self.process != NEMESIS:
                # logical process died; hand its slot to a successor
                run.active.discard(self.process)
                self._close_client()
                self.process = self.process + test["concurrency"]
                run.active.add(self.process)
                try:
                    self.client = self._open_client()
                except Exception as e:                  # noqa: BLE001
                    log.error("worker %s: reopen failed: %s", self.wid, e)
                    break
        run.active.discard(self.process)
        self._close_client()

    def _invoke(self, inv: Op, op_timeout: Optional[float]) -> Op:
        client = self.client
        if client is None:
            return inv.with_(type=OK)
        try:
            if op_timeout is None:
                res = client.invoke(self.test, inv)
            else:
                res = _with_timeout(
                    lambda: client.invoke(self.test, inv), op_timeout)
            if res is None or res.type not in (OK, FAIL, INFO):
                raise ValueError(f"client returned bad completion {res!r}")
            return res
        except _TimeoutExpired:
            return inv.with_(type=INFO,
                             extra={**(inv.extra or {}), "error": "timeout"})
        except Exception as e:                          # noqa: BLE001
            return inv.with_(type=INFO, extra={
                **(inv.extra or {}),
                "error": f"{type(e).__name__}: {e}"})


class _TimeoutExpired(Exception):
    pass


def _with_timeout(fn, seconds: float):
    """Run ``fn`` on a helper thread with a deadline (upstream
    ``util/timeout`` interrupts the worker; Python threads can't be
    interrupted, so the orphaned call parks on the helper — the worker
    moves on as a new process either way)."""
    box: List[Any] = []
    err: List[BaseException] = []

    def target():
        try:
            box.append(fn())
        except BaseException as e:                      # noqa: BLE001
            err.append(e)

    t = threading.Thread(target=target, daemon=True)
    t.start()
    t.join(seconds)
    if t.is_alive():
        raise _TimeoutExpired()
    if err:
        raise err[0]
    return box[0]


class _Run:
    def __init__(self, history: History, start: float):
        self.history = history
        self.start = start
        self.stop = threading.Event()
        self.active: set = set()
        self._lock = threading.Lock()

    def now_ns(self) -> int:
        return int((_time.monotonic() - self.start) * 1e9)


def _normalize(test: Mapping) -> Dict[str, Any]:
    from jepsen_tpu.tests_base import noop_test
    t = dict(noop_test())
    t.update(test)
    if t.get("concurrency") in (None, 0):
        t["concurrency"] = max(1, len(t.get("nodes") or [1]))
    return t


def run(test: Mapping) -> Dict[str, Any]:
    """Run a complete test (upstream ``jepsen.core/run!``). Returns the
    test map extended with ``"history"``, ``"results"``, ``"start-time"``,
    and ``"dir"`` (when stored).

    The whole run executes inside an :func:`jepsen_tpu.obs.capture`
    scope with per-phase spans (setup / workers / teardown / check /
    store); ``results["obs"]`` carries the run's counters + engine
    ledger, and stored runs persist ``obs.jsonl`` + ``trace.json``
    next to the history (:func:`jepsen_tpu.store.save_obs`)."""
    with obs.capture() as obs_cap:
        return _run_captured(test, obs_cap)


def _run_captured(test: Mapping, obs_cap) -> Dict[str, Any]:
    from jepsen_tpu import store as store_mod

    test = _normalize(test)
    test["start-time"] = _time.strftime("%Y%m%dT%H%M%S")
    store_dir = None
    log_handler = None
    if test.get("store", True):
        store_dir = store_mod.create_run_dir(test)
        test["dir"] = store_dir
        log_handler = store_mod.attach_log(store_dir)
    log.info("Running test %s", test.get("name"))

    online = None
    if test.get("online-check"):
        from jepsen_tpu.checkers.facade import _model_from
        from jepsen_tpu.checkers.online import OnlineLinearizable
        try:
            online_model = _model_from(None, test)
        except ValueError:
            log.warning("online-check requested but the test map has no "
                        "model (suite %s); monitoring disabled",
                        test.get("name"))
            online_model = None
        if online_model is not None:
            online = OnlineLinearizable(
                online_model, **(test.get("online-opts") or {}))
    history = History(
        stream_path=f"{store_dir}/history.jsonl" if store_dir else None,
        observer=online.observe if online else None)
    run_state = _Run(history, _time.monotonic())
    test["active-processes"] = lambda: set(run_state.active)
    if online is not None:
        # fail fast: a violated prefix can never become valid again.
        # Chain rather than replace any caller-supplied callback.
        user_cb = online.on_violation

        def _abort(v, _cb=user_cb):
            if _cb is not None:
                _cb(v)
            run_state.stop.set()

        online.on_violation = _abort
        online.start()

    try:
        with obs.span("run.setup", test=str(test.get("name"))):
            os_setup.setup_all(test)
            db_mod.setup_all(test)

        # workers -------------------------------------------------------------
        generator = gen(test.get("generator"))
        n = int(test["concurrency"])
        workers = [_Worker(test, run_state, i, generator) for i in range(n)]
        nemesis = test.get("nemesis")
        nem_worker = None
        if nemesis is not None:
            nemesis.setup(test)
            nem_worker = _Worker(test, run_state, 0, generator)
            nem_worker.process = NEMESIS
            nem_worker.client = None
            nem_ctx = contextvars.copy_context()
            nem_worker.thread = threading.Thread(
                target=lambda: nem_ctx.run(nem_worker._loop),
                daemon=True, name="jepsen-nemesis")
            # the nemesis IS its own client
            nem_worker._open_client = lambda: nemesis     # type: ignore
            nem_worker._close_client = lambda: None       # type: ignore
        run_state.active = set(range(n)) | ({NEMESIS} if nem_worker else set())

        with obs.span("run.workers", concurrency=n,
                      nemesis=nem_worker is not None):
            for w in workers:
                w.thread.start()
            if nem_worker:
                nem_worker.thread.start()
            limit = test.get("run-time-limit")
            end = None if limit is None else _time.monotonic() + limit
            for w in workers:
                w.thread.join(None if end is None else
                              max(0.0, end - _time.monotonic()))
                if w.thread.is_alive():
                    run_state.stop.set()
            run_state.stop.set()                # client phase over
            if nem_worker:
                nem_worker.thread.join(10)
            if nemesis is not None:
                try:
                    nemesis.teardown(test)
                except Exception:                       # noqa: BLE001
                    pass
    finally:
        history.close()
        with obs.span("run.teardown"):
            try:
                if not test.get("leave-db-running"):
                    db_mod.teardown_all(test)
                if store_dir:
                    db_mod.snarf_logs(test, store_dir)
                os_setup.teardown_all(test)
            except Exception as e:                      # noqa: BLE001
                log.warning("teardown failed: %s", e)

    test["history"] = history.snapshot()
    log.info("History complete (%d ops); analyzing", len(test["history"]))

    checker = test.get("checker")
    with obs.span("run.check", ops=len(test["history"])):
        results = (check_safe(checker, test, test["history"])
                   if checker is not None else {"valid": True})
    if online is not None:
        results["online-check"] = online.stop()
        if results["online-check"].get("valid") is False:
            # the online verdict is sound (no false alarms — see
            # checkers/online.py); it must not be masked by a post-hoc
            # "unknown" (state explosion / timeout) or a missing checker
            results["valid"] = False
    # the run's own observability record: counters + engine-decision
    # ledger (assertable by callers, serialized into results.json)
    results["obs"] = obs_cap.summary()
    test["results"] = results
    if store_dir:
        with obs.span("run.store"):
            store_mod.save(test, store_dir)
        store_mod.save_obs(store_dir, obs_cap)
    log.info("Analysis complete: valid? = %s", results.get("valid"))
    if log_handler is not None:
        store_mod.detach_log(log_handler)
    return test
