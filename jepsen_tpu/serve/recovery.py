"""Shared recovery policy for the serving layer: deterministic
bounded-backoff retries, group bisection, and the device-path circuit
breaker.

Three failure disciplines, one module, so the dispatcher cannot grow
divergent ad-hoc copies:

- :class:`RetryPolicy` — a deterministic backoff schedule (no jitter:
  the chaos harness replays fault schedules and must get the same
  attempt sequence every run). Shared by the group retry and the
  hung-dispatch requeue cap.
- :func:`bisect` — split a dispatch group in half to isolate a poison
  member: a group that fails, then fails its retry, is bisected; each
  half gets one attempt and bisects further on failure, so a single
  poison request is cornered in O(log n) extra dispatches while the
  innocent majority completes.
- :class:`CircuitBreaker` — repeated device-path failures open the
  breaker and route subsequent dispatch groups to the host-side
  checkers (verdicts identical, slower) instead of feeding every
  group to a dying device; after a cooldown, a half-open probe sends
  ONE group back to the device and the result closes or re-opens it.
  States follow the classic pattern::

      closed --(N consecutive failures)--> open
      open   --(cooldown elapsed)-------> half-open (one probe)
      half-open --success--> closed
      half-open --failure--> open (cooldown restarts)

  The breaker is consulted and driven by the single dispatcher
  thread, so the state machine needs no compare-and-swap subtlety —
  the lock only guards cross-thread readers (``/healthz``,
  ``/stats``).

Counters: ``serve.retry.attempts`` / ``serve.retry.bisects`` /
``serve.retry.requeued`` / ``serve.quarantined`` (bumped by the
dispatcher at the corresponding transitions), ``serve.breaker.opened``
/ ``serve.breaker.half_open`` / ``serve.breaker.closed`` and the
numeric gauge ``serve.breaker.state`` (0 closed, 1 open, 2 half-open)
from here.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Sequence, Tuple

from jepsen_tpu import obs

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"

_STATE_CODE = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}


class RetryPolicy:
    """Deterministic bounded exponential backoff.

    ``max_retries`` full-group retries per dispatch, ``max_requeues``
    times a hung-dispatch survivor may be requeued before it times
    out. ``delay(attempt)`` is a pure function of the attempt index —
    identical schedules replay identically."""

    def __init__(self, *, max_retries: int = 1, base_s: float = 0.05,
                 factor: float = 2.0, cap_s: float = 1.0,
                 max_requeues: int = 2) -> None:
        self.max_retries = int(max_retries)
        self.base_s = float(base_s)
        self.factor = float(factor)
        self.cap_s = float(cap_s)
        self.max_requeues = int(max_requeues)

    def delay(self, attempt: int) -> float:
        return min(self.cap_s,
                   self.base_s * (self.factor ** max(0, attempt)))

    def to_json(self) -> Dict[str, Any]:
        return {"max_retries": self.max_retries,
                "base_s": self.base_s, "factor": self.factor,
                "cap_s": self.cap_s,
                "max_requeues": self.max_requeues}


def bisect(batch: Sequence) -> Tuple[List, List]:
    """Deterministic half split preserving order (the poison hunt's
    step). Requires ``len(batch) >= 2``."""
    mid = max(1, len(batch) // 2)
    return list(batch[:mid]), list(batch[mid:])


class CircuitBreaker:
    """Device-path health, summarized into a route decision.

    ``route()`` answers "where should the NEXT engine attempt run" —
    ``"device"`` normally (and for the half-open probe), ``"host"``
    while open. ``record_failure()`` / ``record_success()`` must be
    called with the outcome of every DEVICE-route attempt (host
    attempts say nothing about device health)."""

    def __init__(self, *, threshold: int = 5,
                 cooldown_s: float = 15.0) -> None:
        self.threshold = int(threshold)
        self.cooldown_s = float(cooldown_s)
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive = 0
        self._opened_at: float = 0.0
        obs.gauge("serve.breaker.state", 0)

    # -- routing ---------------------------------------------------------
    def route(self) -> str:
        with self._lock:
            if self._state == OPEN and \
                    time.monotonic() - self._opened_at >= self.cooldown_s:
                self._to(HALF_OPEN)
            return "device" if self._state in (CLOSED, HALF_OPEN) \
                else "host"

    # -- outcomes --------------------------------------------------------
    def record_failure(self) -> None:
        with self._lock:
            self._consecutive += 1
            if self._state == HALF_OPEN \
                    or (self._state == CLOSED
                        and self._consecutive >= self.threshold):
                self._to(OPEN)
            elif self._state == OPEN:
                # still failing while open (shouldn't normally be fed,
                # but a racing probe may land late): restart cooldown
                self._opened_at = time.monotonic()

    def record_success(self) -> None:
        with self._lock:
            self._consecutive = 0
            if self._state != CLOSED:
                self._to(CLOSED)

    def _to(self, state: str) -> None:
        # callers hold the lock
        if state == self._state:
            return
        self._state = state
        if state == OPEN:
            self._opened_at = time.monotonic()
            obs.count("serve.breaker.opened")
        elif state == HALF_OPEN:
            obs.count("serve.breaker.half_open")
        else:
            obs.count("serve.breaker.closed")
        obs.gauge("serve.breaker.state", _STATE_CODE[state])
        obs.decision("serve-breaker", "transition", cause=state,
                     consecutive=self._consecutive)

    # -- views -----------------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def degraded(self) -> bool:
        """True while the daemon is NOT serving from the device path
        at full health (open or probing)."""
        with self._lock:
            return self._state != CLOSED

    def to_json(self) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = {
                "state": self._state,
                "consecutive_failures": self._consecutive,
                "threshold": self.threshold,
                "cooldown_s": self.cooldown_s,
            }
            if self._state == OPEN:
                out["open_for_s"] = round(
                    time.monotonic() - self._opened_at, 3)
            return out
