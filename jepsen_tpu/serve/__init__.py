"""``jepsen_tpu.serve`` — the checker as a service (ISSUE 6 tentpole).

Every CLI check pays cold-process cost: kernel compiles, memo BFS,
operand uploads — then tears it all down. This package keeps the
engine hot: a long-lived daemon holds the compiled kernel geometries,
union transition tensors, and the persistent memo/compile caches
device-resident and serves concurrent linearizability checks over
HTTP with inference-server-style continuous batching — a request
never waits for a "full" batch, it rides the next lockstep dispatch
group whose geometry it fits.

Layers (one module each):

- :mod:`request` — request state machine + registry + per-tenant
  serve ledgers.
- :mod:`coalesce` — bounded admission queue, ``plan_buckets``-based
  geometry coalescing, oldest-tenant-first fairness, per-tenant
  in-flight caps, queue-side deadline expiry, and lane placement
  (ready groups land on dispatch lanes round-robin, least-loaded on
  ties). Pure host-side.
- :mod:`engine` — N dispatcher LANES (one thread + circuit breaker
  each) feeding ``facade.auto_check_packed`` /
  ``auto_check_many_packed`` (whose batch route is the streaming
  lockstep scheduler), deadline/cancel abort hooks, optional store
  persistence, per-lane device-time attribution, stats.
- :mod:`http` — the stdlib HTTP protocol (``POST /check``,
  ``GET /check/<id>``, ``GET /stats``) and the :class:`Daemon`
  composition root.
- :mod:`journal` — the durable admission journal (WAL): admitted
  requests survive SIGKILL, replay on restart under their original
  ids, and dedup duplicate POSTs by idempotency key. In fleet mode
  the journal also carries per-entry LEASES: N replica daemons over
  one store root partition the pending work (claim/renew/steal), so
  a SIGKILL'd replica's requests drain through the survivors.
- :mod:`recovery` — deterministic bounded-backoff retry, group
  bisection (poison quarantine), and the device-path circuit
  breaker behind degraded host-side serving.
- :mod:`faults` — the self-nemesis: test-only fault points
  (dispatch/device/prep/persist/clock-jump) the chaos harness
  (``tools/chaos.py``) arms against a real daemon.
- :mod:`session` — streaming check sessions: long-lived checks whose
  reachable-config frontier stays device-resident across
  ``POST /session/<id>/append`` blocks (donated in-place advance),
  with incremental one-bool verdicts per append, journaled replay
  across SIGKILL, and an exact close differential-identical to the
  one-shot facade chain.

Quick start::

    from jepsen_tpu import serve
    d = serve.Daemon(port=8642, store_root="store").start()
    # ... POST /check ...
    d.shutdown()

or ``python -m jepsen_tpu check-serve --port 8642``. Load/latency
measurement: ``python tools/loadgen.py --url http://localhost:8642``.
See ``docs/SERVING.md``.
"""
from jepsen_tpu.serve.coalesce import (AdmissionQueue, Backpressure,
                                       plan_admission)
from jepsen_tpu.serve.engine import Dispatcher
from jepsen_tpu.serve.http import Daemon, parse_check_body, resolve_model
from jepsen_tpu.serve.journal import Journal
from jepsen_tpu.serve.recovery import CircuitBreaker, RetryPolicy
from jepsen_tpu.serve.request import (CANCELLED, DISPATCHED, DONE,
                                      QUARANTINED, QUEUED, TIMEOUT,
                                      CheckRequest, Registry)
from jepsen_tpu.serve.session import (AdvanceAborted,
                                      DeviceFrontierEngine, Session,
                                      SessionRegistry,
                                      TxnSessionEngine)

__all__ = [
    "AdmissionQueue", "Backpressure", "plan_admission", "Dispatcher",
    "Daemon", "parse_check_body", "resolve_model", "CheckRequest",
    "Registry", "Journal", "CircuitBreaker", "RetryPolicy",
    "Session", "SessionRegistry", "DeviceFrontierEngine",
    "TxnSessionEngine", "AdvanceAborted",
    "QUEUED", "DISPATCHED", "DONE", "TIMEOUT", "CANCELLED",
    "QUARANTINED",
]
