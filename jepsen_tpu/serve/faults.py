"""Test-only fault injection for the serve stack — the self-nemesis.

Jepsen points a nemesis at the system under test; this module points
one at *ourselves*. Named fault points are threaded through the
serving hot path (and one engine-side hook), each a no-op until armed:

- ``tick``        — top of every dispatch iteration (never raises;
                    the trigger clock for scheduled faults).
- ``dispatch``    — entry of every engine attempt, BOTH the device
                    and the host route (a poison request crashes the
                    checker wherever it runs).
- ``device``      — entry of the device route only (a device-path
                    outage: the circuit breaker's food).
- ``prep``        — inside the streaming prep thread
                    (``reach._dispatch_lockstep_stream``'s producer;
                    env-gated so the engine never imports this module
                    on a clean run).
- ``persist``     — entry of the store persistence write.
- ``journal-write`` — inside ``Journal._write``: instead of raising,
                    the armed write lands a syntactically-valid but
                    garbage-shaped entry and reports success (the
                    bad-payload corruption adversary; replay must
                    quarantine it).
- ``lease-write`` — inside ``Journal.claim``: the armed claim writes
                    a bad-payload lease (junk expiry) the claimer
                    believes it holds; every reader must detect it,
                    quarantine the file, and treat the entry as
                    unclaimed.
- ``clock-jump``  — not a call site: an armed clock jump fires at its
                    scheduled ``tick`` and skews the deadline clock
                    (:func:`clock_skew`, consulted by
                    ``CheckRequest.expired``) so queued/dispatched
                    deadlines expire as if the wall clock leapt.

Arming is programmatic (:func:`arm`, tests) or via the environment
(:func:`arm_from_env`, chaos harness daemons)::

    JEPSEN_TPU_SERVE_FAULTS="dispatch@3;device@2x6;persist@1;
                             clock-jump@4:3600;poison=tenant-x"

Grammar (entries joined by ``;``):

- ``point@N``      fire on the Nth invocation of ``point`` (1-based).
- ``point@NxK``    fire on invocations N..N+K-1 (K consecutive).
- ``clock-jump@N:S``  at the Nth ``tick``, skew the deadline clock
  forward by S seconds (permanently — a jump, not a drift).
- ``poison=T``     raise at every ``dispatch`` whose group contains
  tenant T (models one malformed request that crashes any engine;
  the group-bisect retry must isolate and quarantine it).

Every fault that actually fires bumps ``serve.fault.<name>`` and
appends a ``serve-fault/injected`` decision to the obs ledger — the
chaos harness's "no silent fault" invariant cross-checks those
records against its schedule. Deterministic by construction: firing
depends only on invocation counts, never on wall time or randomness.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional, Sequence


class InjectedFault(RuntimeError):
    """Raised at an armed fault point (self-nemesis, test-only)."""


_lock = threading.RLock()
_armed: List[Dict[str, Any]] = []
_invocations: Dict[str, int] = {}
_skew_s: float = 0.0
_env_loaded = False

_ENV_VAR = "JEPSEN_TPU_SERVE_FAULTS"


def _counter_name(name: str) -> str:
    return "serve.fault." + name.replace("-", "_")


def arm(point: str, *, at: int = 1, times: int = 1,
        skew_s: Optional[float] = None,
        tenant: Optional[str] = None, name: Optional[str] = None
        ) -> None:
    """Arm one fault. ``point`` is the listening call site; ``at`` /
    ``times`` the invocation window; ``tenant`` restricts a
    ``dispatch`` fault to groups containing that tenant (and makes it
    fire on EVERY matching invocation); ``skew_s`` turns the entry
    into a clock jump applied at its ``tick`` instead of a raise."""
    with _lock:
        _armed.append({
            "point": point, "at": int(at), "times": int(times),
            "skew_s": skew_s, "tenant": tenant, "fired": 0,
            "name": name or point,
        })


def reset() -> None:
    """Disarm everything and clear the clock skew (tests)."""
    global _skew_s, _env_loaded
    with _lock:
        _armed.clear()
        _invocations.clear()
        _skew_s = 0.0
        _env_loaded = True      # an explicit reset also pins the env


def enabled() -> bool:
    return bool(_armed) or bool(os.environ.get(_ENV_VAR))


def clock_skew() -> float:
    """Seconds the deadline clock is currently jumped forward by."""
    return _skew_s


def arm_from_env(force: bool = False) -> int:
    """Parse ``JEPSEN_TPU_SERVE_FAULTS`` once (idempotent unless
    ``force``); returns how many entries were armed."""
    global _env_loaded
    with _lock:
        if _env_loaded and not force:
            return 0
        _env_loaded = True
        spec = os.environ.get(_ENV_VAR, "").strip()
        if not spec:
            return 0
        n = 0
        for raw in spec.split(";"):
            raw = raw.strip()
            if not raw:
                continue
            if raw.startswith("poison="):
                arm("dispatch", tenant=raw[len("poison="):],
                    times=1 << 30, name="poison")
                n += 1
                continue
            point, _, when = raw.partition("@")
            point = point.strip()
            arg = None
            if ":" in when:
                when, _, args = when.partition(":")
                arg = float(args)
            times = 1
            if "x" in when:
                when, _, ks = when.partition("x")
                times = int(ks)
            at = int(when or 1)
            if point == "clock-jump":
                arm("tick", at=at, times=times,
                    skew_s=arg if arg is not None else 3600.0,
                    name="clock_jump")
            else:
                arm(point, at=at, times=times, name=point)
            n += 1
        return n


def fire(point: str, tenants: Optional[Sequence[str]] = None) -> None:
    """Invoke a fault point. Raises :class:`InjectedFault` when an
    armed raising fault matches; applies clock skew for due jump
    entries; no-op otherwise. Cheap when nothing is armed."""
    global _skew_s
    if not _env_loaded:
        arm_from_env()
    if not _armed:
        return
    with _lock:
        inv = _invocations.get(point, 0) + 1
        _invocations[point] = inv
        due: Optional[Dict[str, Any]] = None
        for f in _armed:
            if f["point"] != point:
                continue
            if f["tenant"] is not None:
                if not tenants or f["tenant"] not in tenants:
                    continue
                if f["fired"] >= f["times"]:
                    continue
            elif not (f["at"] <= inv < f["at"] + f["times"]):
                continue
            f["fired"] += 1
            due = f
            break
        if due is None:
            return
        name = due["name"]
        skew = due["skew_s"]
        if skew is not None:
            _skew_s += float(skew)
    _record(name, point, inv, tenants)
    if skew is None:
        raise InjectedFault(
            f"injected fault {name!r} at {point} invocation {inv}")


def _record(name: str, point: str, inv: int,
            tenants: Optional[Sequence[str]]) -> None:
    from jepsen_tpu import obs
    obs.count(_counter_name(name))
    obs.decision("serve-fault", "injected", cause=name, point=point,
                 invocation=inv,
                 tenants=sorted(set(tenants or ())) or None)


def fired_counts() -> Dict[str, int]:
    """name -> times fired (for harness-side bookkeeping)."""
    with _lock:
        out: Dict[str, int] = {}
        for f in _armed:
            if f["fired"]:
                out[f["name"]] = out.get(f["name"], 0) + f["fired"]
        return out
