"""Request objects and the daemon-wide registry.

A :class:`CheckRequest` is one client-submitted linearizability check:
the packed history, the resolved model, per-request options, the
tenant it belongs to, and an optional deadline. The request moves
through a small state machine::

    queued -> dispatched -> done
       |          |-> timeout   (deadline passed; verdict "unknown")
       |-> timeout              (deadline passed while still queued)
    queued -> cancelled         (client DELETE before dispatch)
    queued -> rejected          (never stored: backpressure is a 429
                                 at admission, the request never
                                 enters the registry)

The :class:`Registry` is the daemon's single source of truth for
request lookup (``GET /check/<id>``), per-tenant serve ledgers, and
per-status counts. Completed requests are retained FIFO-bounded so a
long-lived daemon cannot leak memory one verdict at a time.
"""
from __future__ import annotations

import threading
import time
import uuid
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

from jepsen_tpu import history as h
from jepsen_tpu.models import Model
from jepsen_tpu.op import Op
from jepsen_tpu.serve import faults

# request lifecycle states (strings: they go straight into JSON)
QUEUED = "queued"
DISPATCHED = "dispatched"
DONE = "done"
TIMEOUT = "timeout"
CANCELLED = "cancelled"
QUARANTINED = "quarantined"     # isolated poison member of a group
                                # (bisect retry exhausted on it alone)

_TERMINAL = (DONE, TIMEOUT, CANCELLED, QUARANTINED)

# stitched per-request trace records are bounded: a pathological
# dispatch (deep fallback chains) must not grow retained terminal
# requests past the "verdicts, not gigabytes" contract
_TRACE_CAP = 64


def new_request_id() -> str:
    return uuid.uuid4().hex[:16]


@dataclass
class CheckRequest:
    """One admitted check. Mutable fields are only written under the
    registry/queue locks or by the single dispatcher thread."""
    id: str
    tenant: str
    model_name: str
    model: Model
    packed: Optional[h.PackedHistory]
    history: Sequence[Op]
    n_ops: int = 0              # survives the terminal payload drop
    opts: Dict[str, Any] = field(default_factory=dict)
    deadline: Optional[float] = None        # time.monotonic() instant
    idem_key: Optional[str] = None          # client idempotency key
    requeues: int = 0                       # hung-dispatch requeues
    journaled: bool = False                 # has a durable WAL entry
    # streaming check sessions: an append/close block rides the same
    # queue as one-shot checks, but its coalescing signature is the
    # SESSION id (same-session blocks coalesce into one ordered
    # dispatch group; the dispatcher advances the carried frontier in
    # seq order) and its journal entry is the session's, not a
    # .req.json (kind: "check" | "session-append" | "session-close")
    kind: str = "check"
    session: Optional[Any] = None           # serve.session.Session
    seq: int = 0                            # per-session append order
    # dispatch lane this request's group was placed on (stamped by
    # the coalescer's lane placement; None on the single-consumer
    # path) — surfaces in to_json so clients can see the fan-out
    lane: Optional[int] = None
    # stage timestamps (time.monotonic): admit -> coalesce (selected
    # into a dispatch group) -> dispatch (engine call starts) ->
    # collect (engine call returned) -> done (verdict published).
    # t_submit_wall anchors the monotonic deltas to the wall clock for
    # clients rendering the waterfall.
    t_submit: float = field(default_factory=time.monotonic)
    t_submit_wall: float = field(default_factory=time.time)
    t_coalesce: Optional[float] = None
    t_dispatch: Optional[float] = None
    t_collect: Optional[float] = None
    t_done: Optional[float] = None
    status: str = QUEUED
    result: Optional[Dict[str, Any]] = None
    run_dir: Optional[str] = None           # when persisted via store
    done_event: threading.Event = field(default_factory=threading.Event)
    cancel_requested: bool = False
    device_s: Optional[float] = None        # attributed device time
    # per-request stitched trace: the dispatcher thread re-emits its
    # group-level spans and any engine fallback/selection records into
    # every member's ledger (tagged with the request id), so a
    # client's GET /check/<id> sees what its own dispatch did even
    # though three threads touched it
    trace: List[Dict[str, Any]] = field(default_factory=list)

    @property
    def model_sig(self) -> tuple:
        """Coalescing compatibility key: requests sharing this
        signature may ride one dispatch group — same model (the
        union-alphabet stage A is built per model identity) AND same
        engine options (a group shares one walk, so differing caps
        cannot both be honored; clients who set none share freely).
        Session blocks key on the SESSION instead: a session's
        appends must advance its carried frontier in order, so they
        never coalesce with one-shot checks. Appends of sessions
        whose carried frontiers compile to the SAME batched walk
        share a mega-batch signature (``("session-mega",) + walk
        geometry``): the coalescer may stack thousands of such
        streams along a lane axis and advance them all in ONE kernel
        launch. Sessions that cannot participate (txn engines, host
        fallbacks, unseeded/dense carries, closes) keep the solo
        per-session-id signature. The mega signature reads the
        session's LOCK-FREE cached geometry — a stale value degrades
        grouping, never correctness: membership is re-validated under
        the session lock at stage time, and per-session seq order is
        safe because a close always queues after its appends (later
        t_submit) and the coalescer selects by oldest-request
        signature."""
        if self.session is not None:
            if self.kind == "session-append":
                g = self.session.mega_sig()
                if g is not None:
                    return ("session-mega",) + g
            return ("session", self.session.id)
        # list-valued options (the canonical "consistency" level set)
        # are tupled so the signature stays hashable: requests asking
        # for the same level set coalesce, mixed-level tenants split
        # into per-level-set groups but each group still batches
        return (type(self.model).__name__, repr(self.model),
                tuple(sorted((k, tuple(v) if isinstance(v, list)
                              else v)
                             for k, v in self.opts.items())))

    def expired(self, now: Optional[float] = None) -> bool:
        if self.deadline is None:
            return False
        # the self-nemesis clock-jump fault skews the deadline clock
        # here (0.0 unless armed), so BOTH expiry sites — the queue
        # scan and the dispatch abort hook — see the same jumped clock
        return (now if now is not None else time.monotonic()) \
            + faults.clock_skew() >= self.deadline

    @property
    def terminal(self) -> bool:
        return self.status in _TERMINAL

    def stitch(self, recs: List[Dict[str, Any]]) -> None:
        """Append dispatcher-thread records to this request's stitched
        trace, tagged with the request id (bounded at ``_TRACE_CAP``;
        overflow is counted so a truncated trace is visible)."""
        room = _TRACE_CAP - len(self.trace)
        for r in recs[:max(0, room)]:
            rec = dict(r)
            rec["id"] = self.id
            self.trace.append(rec)
        if len(recs) > room:
            from jepsen_tpu import obs
            obs.count("serve.trace_truncated", len(recs) - room)

    def waterfall(self) -> List[Dict[str, Any]]:
        """The request's life as contiguous stages relative to submit:
        ``queued`` (admission -> selected into a group), ``coalesce``
        (selection -> engine call), ``walk`` (the device dispatch),
        ``publish`` (collect -> verdict published). Only stages whose
        boundary timestamps exist appear — a queued-side timeout shows
        just its queue time."""
        out: List[Dict[str, Any]] = []

        def add(stage: str, start: Optional[float],
                end: Optional[float]) -> None:
            if start is None or end is None:
                return
            out.append({"stage": stage,
                        "start-s": round(start - self.t_submit, 6),
                        "dur-s": round(max(0.0, end - start), 6)})

        add("queued", self.t_submit, self.t_coalesce or self.t_done)
        add("coalesce", self.t_coalesce, self.t_dispatch)
        add("walk", self.t_dispatch, self.t_collect)
        add("publish", self.t_collect, self.t_done)
        return out

    def to_json(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "id": self.id, "tenant": self.tenant,
            "model": self.model_name, "status": self.status,
            "ops": int(self.n_ops),
            "submitted-at": round(self.t_submit_wall, 3),
        }
        if self.t_coalesce is not None:
            out["queue-wait-s"] = round(
                self.t_coalesce - self.t_submit, 6)
        if self.t_done is not None and self.t_coalesce is not None:
            out["service-s"] = round(
                self.t_done - self.t_coalesce, 6)
        if self.t_done is not None:
            out["latency-s"] = round(self.t_done - self.t_submit, 6)
        if self.device_s is not None:
            out["device-s"] = round(self.device_s, 9)
        if self.lane is not None:
            out["lane"] = int(self.lane)
        wf = self.waterfall()
        if wf:
            out["waterfall"] = wf
        if self.trace:
            out["trace"] = [dict(r) for r in self.trace]
        if self.result is not None:
            out["result"] = self.result
        if self.run_dir is not None:
            out["run-dir"] = self.run_dir
        return out


class Registry:
    """id -> request lookup plus per-tenant serve ledgers.

    Terminal requests are retained FIFO-bounded (``keep_done``): the
    oldest completed request is evicted when a new one completes past
    the bound, so ``GET /check/<id>`` works for recently-finished ids
    without unbounded growth. Per-tenant ledgers are bounded deques of
    structured records (admitted / dispatched / done / timeout /
    cancelled / rejected) — the serve-layer analogue of the
    engine-decision ledger, isolated per tenant."""

    # jtlint lock discipline: these attributes are only touched under
    # self._lock (methods named *_locked are called with it held) —
    # statically enforced by the `lock-discipline` pass
    _GUARDED_BY = ("_by_id", "_done_order", "_tenant_ledgers",
                   "_event_counts", "_device_s")

    def __init__(self, keep_done: int = 4096,
                 ledger_depth: int = 512,
                 max_tenants: int = 1024) -> None:
        self._lock = threading.Lock()
        # terminal-transition hook (the daemon wires the durable
        # journal's completion marker here); called OUTSIDE the lock,
        # exactly once per request, from whichever thread finished it
        self.on_terminal: Optional[Any] = None
        self._by_id: "OrderedDict[str, CheckRequest]" = OrderedDict()
        self._done_order: "deque[str]" = deque()
        self._keep_done = keep_done
        self._ledger_depth = ledger_depth
        self._max_tenants = max_tenants
        self._tenant_ledgers: Dict[str, deque] = {}
        # nested, NOT "tenant.event" flat keys: tenant names are
        # client-controlled and may themselves contain dots
        self._event_counts: Dict[str, Dict[str, int]] = {}
        # attributed device-seconds per tenant (the amortized share of
        # each dispatch group's kernel wall; see engine._dispatch)
        self._device_s: Dict[str, float] = {}

    def add(self, req: CheckRequest) -> None:
        with self._lock:
            self._by_id[req.id] = req

    def get(self, req_id: str) -> Optional[CheckRequest]:
        with self._lock:
            return self._by_id.get(req_id)

    def remove(self, req_id: str) -> None:
        """Retract a request that never really entered the system
        (admission rejected after the registry add)."""
        with self._lock:
            self._by_id.pop(req_id, None)

    def finish(self, req: CheckRequest, status: str,
               result: Optional[Dict[str, Any]] = None) -> None:
        """Transition a request to a terminal state (idempotent: the
        first terminal transition wins — a deadline firing while the
        dispatcher publishes a verdict must not flap the status)."""
        with self._lock:
            if req.terminal:
                return
            req.status = status
            if result is not None:
                req.result = result
            req.t_done = time.monotonic()
            # the lookup contract only needs the verdict from here on:
            # drop the packed arrays and the Op list (persistence, if
            # any, already happened) so keep_done retained verdicts
            # cost bytes, not histories
            req.packed = None
            req.history = ()
            self._done_order.append(req.id)
            while len(self._done_order) > self._keep_done:
                old = self._done_order.popleft()
                self._by_id.pop(old, None)
        cb = self.on_terminal
        if cb is not None:
            try:
                cb(req)
            except Exception as e:                      # noqa: BLE001
                # the hook is durability bookkeeping; a failure there
                # must never lose the in-memory terminal transition —
                # but it IS degraded durability, so it is recorded
                import logging
                from jepsen_tpu import obs
                obs.engine_fallback("serve-journal",
                                    type(e).__name__, id=req.id)
                logging.getLogger("jepsen.serve").warning(
                    "on_terminal hook failed for %s: %s", req.id, e)
        req.done_event.set()

    def bucket_tenant(self, tenant: str) -> str:
        """Tenant key for ledger/counter purposes. Tenant names are
        client-controlled, so distinct-tenant state must be bounded:
        past ``max_tenants`` known tenants, new names share one
        ``(overflow)`` bucket (and the overflow is itself counted)."""
        with self._lock:
            return self._bucket_tenant_locked(tenant)

    def _bucket_tenant_locked(self, tenant: str) -> str:
        if tenant in self._tenant_ledgers \
                or len(self._tenant_ledgers) < self._max_tenants:
            return tenant
        return "(overflow)"

    def ledger_record(self, tenant: str, event: str,
                      **fields: Any) -> None:
        rec = {"ts": round(time.time(), 6), "event": event}
        rec.update(fields)
        overflowed = False
        with self._lock:
            bucketed = self._bucket_tenant_locked(tenant)
            # one overflow count per overflowed REQUEST (admission is
            # the once-per-request event), not per ledger consult
            overflowed = bucketed != tenant and event == "admitted"
            led = self._tenant_ledgers.get(bucketed)
            if led is None:
                led = deque(maxlen=self._ledger_depth)
                self._tenant_ledgers[bucketed] = led
            led.append(rec)
            ev = self._event_counts.setdefault(bucketed, {})
            ev[event] = ev.get(event, 0) + 1
        if overflowed:
            from jepsen_tpu import obs
            obs.count("serve.tenant_overflow")

    def add_device_time(self, tenant: str, seconds: float) -> None:
        """Accumulate a request's attributed device-seconds under its
        (bounded) tenant bucket — the per-tenant cost view of the
        device-time attribution."""
        with self._lock:
            b = self._bucket_tenant_locked(tenant)
            self._device_s[b] = self._device_s.get(b, 0.0) + seconds

    def tenant_ledger(self, tenant: str) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(r) for r in self._tenant_ledgers.get(tenant, ())]

    def tenants(self) -> List[str]:
        with self._lock:
            return sorted(self._tenant_ledgers)

    def stats(self) -> Dict[str, Any]:
        """Per-tenant event counts + live request-status census."""
        with self._lock:
            census: Dict[str, int] = {}
            for req in self._by_id.values():
                census[req.status] = census.get(req.status, 0) + 1
            tenants = {t: dict(ev)
                       for t, ev in self._event_counts.items()}
            device_s = {t: round(v, 6)
                        for t, v in self._device_s.items()}
            return {"requests": census, "tenants": tenants,
                    "device-seconds": device_s}
