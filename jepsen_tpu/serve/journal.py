"""Durable admission journal: the daemon's write-ahead log of admitted
checks, so a crash (SIGKILL included) loses no admitted request.

Contract (the Jepsen discipline applied to ourselves):

- **Append before the 202.** Every admitted request is journaled —
  EDN history, engine options, tenant, deadline, client-supplied
  idempotency key — *before* the client sees its 202. A client
  holding an id therefore holds a durable claim on a verdict.
- **Completion marker.** Terminal transitions write a ``done`` marker
  carrying the final status AND the result payload, so a client
  polling ``GET /check/<id>`` across a restart gets its verdict even
  when the request completed just before the crash (the in-memory
  registry died with the process).
- **Replay.** On daemon start, entries without markers are fed back
  through the admission queue under their ORIGINAL ids. Deadlines
  are re-derived from the wall clock (a request whose deadline passed
  while the daemon was dead replays as an immediate ``timeout``, not
  as free extra time).
- **Idempotency.** Duplicate ``POST /check`` with the same
  idempotency key dedups to the original id; the key->id index is
  rebuilt from the journal at start, so the dedup window survives
  restarts (bounded by journal retention).
- **Cancellation sticks.** ``DELETE /check/<id>`` on a
  journaled-but-unreplayed entry writes its ``cancelled`` marker so a
  restart cannot resurrect cancelled work.
- **Size-bounded.** Terminal entry/marker pairs past
  ``keep_terminal`` are garbage-collected oldest-first
  (``serve.journal.gc``); pending entries are never collected.

Layout: one ``<id>.req.json`` (meta + ``history-edn``) plus one
``<id>.done.json`` marker per request under
``<store-root>/serve/journal/``. Writes go tmp-file + ``os.replace``
with an fsync, so a torn write is an absent entry (the client never
got its 202), never a corrupt one; a corrupt entry found anyway is
quarantined at replay, not looped on.

Pure host-side stdlib — no jax, unit-testable in microseconds.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional

from jepsen_tpu import edn
from jepsen_tpu import obs
from jepsen_tpu.op import Op

log = logging.getLogger("jepsen.serve.journal")

_REQ_SUFFIX = ".req.json"
_DONE_SUFFIX = ".done.json"


def history_to_edn(history) -> str:
    """One EDN op map per line — the same shape ``history.edn`` run
    artifacts use, so journal entries are readable by upstream
    tooling."""
    return "\n".join(edn.dumps(op.to_dict()) for op in history)


def history_from_edn(text: str) -> List[Op]:
    vals = edn.loads_all(text)
    return [Op.from_dict(edn.to_plain(d)) for d in vals]


class Journal:
    """The write-ahead log. Thread-safe: HTTP worker threads append,
    the dispatcher thread marks completion, ``/stats`` reads counts."""

    def __init__(self, root: str, *, keep_terminal: int = 256,
                 fsync: bool = True, gc_every: int = 32) -> None:
        self.root = root
        self.keep_terminal = int(keep_terminal)
        self.fsync = bool(fsync)
        self.gc_every = max(1, int(gc_every))
        self._lock = threading.Lock()
        self._finishes = 0
        os.makedirs(root, exist_ok=True)

    # -- low-level -------------------------------------------------------
    def _write(self, path: str, payload: Dict[str, Any]) -> None:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, default=str)
            f.flush()
            if self.fsync:
                os.fsync(f.fileno())
        os.replace(tmp, path)
        if self.fsync:
            # the rename itself must be durable: without a directory
            # fsync a host crash (not just SIGKILL) after the 202 can
            # lose the entry's directory metadata — the one failure
            # mode tmp+replace+file-fsync does not cover
            try:
                dfd = os.open(self.root, os.O_RDONLY)
                try:
                    os.fsync(dfd)
                finally:
                    os.close(dfd)
            except OSError:
                pass            # platform without dir-fsync: best effort

    def _req_path(self, req_id: str) -> str:
        return os.path.join(self.root, req_id + _REQ_SUFFIX)

    def _done_path(self, req_id: str) -> str:
        return os.path.join(self.root, req_id + _DONE_SUFFIX)

    # -- append / finish -------------------------------------------------
    def append(self, *, req_id: str, tenant: str, model_name: str,
               options: Dict[str, Any], timeout_s: Optional[float],
               idempotency_key: Optional[str], history) -> None:
        """Durably record one admitted request (called BEFORE the 202
        is returned). Raises on IO failure — an unjournalable request
        must not be admitted as if it were durable."""
        entry = {
            "id": req_id, "tenant": tenant, "model": model_name,
            "options": dict(options or {}),
            "timeout-s": timeout_s,
            "idempotency-key": idempotency_key,
            "submitted-at": round(time.time(), 6),
            "history-edn": history_to_edn(history),
        }
        self._write(self._req_path(req_id), entry)
        obs.count("serve.journal.appended")

    def finish(self, req_id: str, status: str,
               result: Optional[Dict[str, Any]] = None) -> None:
        """Mark a journaled request terminal (idempotent; the first
        marker wins — the exists-check and the write share the lock,
        so a concurrent cancel cannot clobber a published verdict's
        marker). Unknown ids are a no-op — requests admitted while
        journaling was off, or already collected."""
        done = self._done_path(req_id)
        payload = {"id": req_id, "status": status,
                   "ts": round(time.time(), 6)}
        if result is not None:
            try:
                payload["result"] = json.loads(
                    json.dumps(result, default=str))
            except (TypeError, ValueError):
                pass
        with self._lock:
            if not os.path.exists(self._req_path(req_id)) \
                    or os.path.exists(done):
                return
            try:
                self._write(done, payload)
            except OSError as e:
                # a failed marker means the entry replays after a
                # crash — at-least-once, never lost; record, don't
                # raise into the dispatcher
                log.warning("journal finish failed for %s: %s",
                            req_id, e)
                obs.engine_fallback("serve-journal",
                                    type(e).__name__, id=req_id)
                return
            self._finishes += 1
            due = self._finishes % self.gc_every == 0
        if due:
            self.gc()

    def discard(self, req_id: str) -> None:
        """Remove an entry that was never admitted (backpressure
        retraction after the append)."""
        for p in (self._req_path(req_id), self._done_path(req_id)):
            try:
                os.unlink(p)
            except OSError:
                pass

    def cancel_pending(self, req_id: str) -> bool:
        """Write a ``cancelled`` marker for a pending (unreplayed /
        unfinished) entry so a restart cannot resurrect it. Returns
        True when this call cancelled it (finish itself re-checks
        under the lock, so a racing verdict marker wins or we do —
        never a clobber)."""
        if not os.path.exists(self._req_path(req_id)) \
                or os.path.exists(self._done_path(req_id)):
            return False
        self.finish(req_id, "cancelled",
                    {"valid": "unknown", "cause": "cancelled"})
        term = self.lookup_terminal(req_id)
        return bool(term) and term.get("status") == "cancelled"

    # -- views -----------------------------------------------------------
    def _ids(self) -> Dict[str, bool]:
        """id -> has-done-marker, from one directory scan."""
        out: Dict[str, bool] = {}
        try:
            names = os.listdir(self.root)
        except OSError:
            return out
        done = {n[:-len(_DONE_SUFFIX)] for n in names
                if n.endswith(_DONE_SUFFIX)}
        for n in names:
            if n.endswith(_REQ_SUFFIX):
                rid = n[:-len(_REQ_SUFFIX)]
                out[rid] = rid in done
        return out

    def pending_ids(self) -> List[str]:
        """Unfinished entries, oldest first (by entry mtime)."""
        ids = [rid for rid, fin in self._ids().items() if not fin]

        def _mtime(rid: str) -> float:
            try:
                return os.path.getmtime(self._req_path(rid))
            except OSError:
                return 0.0
        return sorted(ids, key=lambda rid: (_mtime(rid), rid))

    def pending_count(self) -> int:
        # hot path (/healthz, per-dispatch stats): one listdir, no
        # per-entry mtime stats — pending_ids' sort order is only
        # needed by replay
        return sum(1 for fin in self._ids().values() if not fin)

    def load_entry(self, req_id: str) -> Optional[Dict[str, Any]]:
        try:
            with open(self._req_path(req_id)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def lookup_terminal(self, req_id: str) -> Optional[Dict[str, Any]]:
        """The done marker (status + persisted result), or None."""
        try:
            with open(self._done_path(req_id)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def idempotency_index(self) -> Dict[Any, str]:
        """(tenant, key) -> request id over every journaled entry
        (pending and terminal) — rebuilt at daemon start so dedup
        survives restarts. Keys are TENANT-scoped: one tenant's
        idempotency key must never map onto (or leak the status of)
        another tenant's request."""
        out: Dict[Any, str] = {}
        for rid in self._ids():
            e = self.load_entry(rid)
            if e and e.get("idempotency-key"):
                out[(str(e.get("tenant") or "anonymous"),
                     str(e["idempotency-key"]))] = rid
        return out

    # -- GC --------------------------------------------------------------
    def gc(self) -> int:
        """Collect terminal entry/marker pairs past ``keep_terminal``,
        oldest marker first. Pending entries are never touched.
        Returns how many requests were collected."""
        pairs = [(rid, self._done_path(rid))
                 for rid, fin in self._ids().items() if fin]
        excess = len(pairs) - self.keep_terminal
        if excess <= 0:
            return 0

        def _mtime(p: str) -> float:
            try:
                return os.path.getmtime(p)
            except OSError:
                return 0.0
        pairs.sort(key=lambda t: (_mtime(t[1]), t[0]))
        n = 0
        for rid, _ in pairs[:excess]:
            self.discard(rid)
            n += 1
        if n:
            obs.count("serve.journal.gc", n)
        return n

    def stats(self) -> Dict[str, Any]:
        ids = self._ids()
        pending = sum(1 for fin in ids.values() if not fin)
        return {"pending": pending,
                "terminal": len(ids) - pending,
                "keep_terminal": self.keep_terminal,
                "root": self.root}
