"""Durable admission journal: the daemon's write-ahead log of admitted
checks, so a crash (SIGKILL included) loses no admitted request.

Contract (the Jepsen discipline applied to ourselves):

- **Append before the 202.** Every admitted request is journaled —
  EDN history, engine options, tenant, deadline, client-supplied
  idempotency key — *before* the client sees its 202. A client
  holding an id therefore holds a durable claim on a verdict.
- **Completion marker.** Terminal transitions write a ``done`` marker
  carrying the final status AND the result payload, so a client
  polling ``GET /check/<id>`` across a restart gets its verdict even
  when the request completed just before the crash (the in-memory
  registry died with the process).
- **Replay.** On daemon start, entries without markers are fed back
  through the admission queue under their ORIGINAL ids. Deadlines
  are re-derived from the wall clock (a request whose deadline passed
  while the daemon was dead replays as an immediate ``timeout``, not
  as free extra time).
- **Idempotency.** Duplicate ``POST /check`` with the same
  idempotency key dedups to the original id; the key->id index is
  rebuilt from the journal at start, so the dedup window survives
  restarts (bounded by journal retention).
- **Cancellation sticks.** ``DELETE /check/<id>`` on a
  journaled-but-unreplayed entry writes its ``cancelled`` marker so a
  restart cannot resurrect cancelled work.
- **Size-bounded.** Terminal entry/marker pairs past
  ``keep_terminal`` are garbage-collected oldest-first
  (``serve.journal.gc``); pending entries are never collected.

Layout: one ``<id>.req.json`` (meta + ``history-edn``) plus one
``<id>.done.json`` marker per request under
``<store-root>/serve/journal/``. Writes go tmp-file + ``os.replace``
with an fsync, so a torn write is an absent entry (the client never
got its 202), never a corrupt one; a corrupt entry found anyway is
quarantined at replay, not looped on.

**Fleet mode** (multiple daemons over ONE journal root) adds per-entry
*leases*: ``<id>.lease.json`` carrying the claiming replica id and a
wall-clock expiry. A fresh claim is a kernel-atomic exclusive create
(``O_CREAT|O_EXCL`` — ``os.replace`` clobbers, so it cannot be the
claim primitive); renewals and expired-lease steals serialize through
a cross-process ``flock`` on ``.fleet.lock``, so two replicas racing
for one entry admit exactly one. A SIGKILL'd replica's leases expire
on the wall clock and its queued work drains through survivors (they
steal at their next journal scan). The lease suffix is disjoint from
every other suffix, so the one-shot and session views are blind to
lease files by construction.

Pure host-side stdlib — no jax, unit-testable in microseconds.
"""
from __future__ import annotations

import contextlib
import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

try:
    import fcntl
    _HAVE_FLOCK = True
# jtlint: ok fallback — import-time capability probe; _fleet_lock degrades to the in-process lock (single-replica semantics) and documents it
except ImportError:                     # pragma: no cover - non-POSIX
    _HAVE_FLOCK = False

from jepsen_tpu import edn
from jepsen_tpu import obs
from jepsen_tpu.op import Op
from jepsen_tpu.serve import faults

log = logging.getLogger("jepsen.serve.journal")

_REQ_SUFFIX = ".req.json"
_DONE_SUFFIX = ".done.json"
# streaming check sessions: one .sess.json (open meta) + one
# .a<seq>.sapp.json per append block + one .sdone.json close marker
# per session. Disjoint suffixes keep the one-shot views
# (_ids/pending_count/idempotency_index) blind to session files.
_SESS_SUFFIX = ".sess.json"
_SAPP_MID = ".a"
_SAPP_SUFFIX = ".sapp.json"
_SDONE_SUFFIX = ".sdone.json"
# fleet mode: one .lease.json per claimed entry (one-shot request id
# or session id) — replica id + wall-clock expiry
_LEASE_SUFFIX = ".lease.json"


def history_to_edn(history) -> str:
    """One EDN op map per line — the same shape ``history.edn`` run
    artifacts use, so journal entries are readable by upstream
    tooling."""
    return "\n".join(edn.dumps(op.to_dict()) for op in history)


def history_from_edn(text: str) -> List[Op]:
    vals = edn.loads_all(text)
    return [Op.from_dict(edn.to_plain(d)) for d in vals]


class Journal:
    """The write-ahead log. Thread-safe: HTTP worker threads append,
    the dispatcher thread marks completion, ``/stats`` reads counts."""

    # jtlint lock discipline: the GC cadence counter is only touched
    # under self._lock (the `lock-discipline` pass enforces this)
    _GUARDED_BY = ("_finishes",)

    def __init__(self, root: str, *, keep_terminal: int = 256,
                 fsync: bool = True, gc_every: int = 32) -> None:
        self.root = root
        self.keep_terminal = int(keep_terminal)
        self.fsync = bool(fsync)
        self.gc_every = max(1, int(gc_every))
        # extra fields merged into every lease payload this journal
        # writes (pod daemons stamp {"ranks": n} — ONE lease fronts
        # the whole multi-host replica); None = plain payloads
        self.lease_meta: Optional[Dict[str, Any]] = None
        self._lock = threading.Lock()
        self._finishes = 0
        os.makedirs(root, exist_ok=True)

    # -- low-level -------------------------------------------------------
    def _write(self, path: str, payload: Dict[str, Any]) -> None:
        # the self-nemesis corruption point: an armed "journal-write"
        # replaces this entry with a syntactically-VALID but
        # garbage-shaped payload, and the writer believes it
        # succeeded — the adversary the replay quarantine exists for
        # (a merely torn write is already an absent entry by the
        # tmp+rename discipline below)
        try:
            faults.fire("journal-write")
        # jtlint: ok fallback — fire() recorded serve-fault/injected; the corrupt write IS the injected behavior
        except faults.InjectedFault:
            with open(path, "w") as f:
                json.dump({"corrupted": True}, f)
            return
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, default=str)
            f.flush()
            if self.fsync:
                os.fsync(f.fileno())
        os.replace(tmp, path)
        if self.fsync:
            # the rename itself must be durable: without a directory
            # fsync a host crash (not just SIGKILL) after the 202 can
            # lose the entry's directory metadata — the one failure
            # mode tmp+replace+file-fsync does not cover
            try:
                dfd = os.open(self.root, os.O_RDONLY)
                try:
                    os.fsync(dfd)
                finally:
                    os.close(dfd)
            # jtlint: ok fallback — platforms without dir-fsync: file fsync already happened
            except OSError:
                pass            # platform without dir-fsync: best effort

    def _req_path(self, req_id: str) -> str:
        return os.path.join(self.root, req_id + _REQ_SUFFIX)

    def _done_path(self, req_id: str) -> str:
        return os.path.join(self.root, req_id + _DONE_SUFFIX)

    # -- append / finish -------------------------------------------------
    def append(self, *, req_id: str, tenant: str, model_name: str,
               options: Dict[str, Any], timeout_s: Optional[float],
               idempotency_key: Optional[str], history) -> None:
        """Durably record one admitted request (called BEFORE the 202
        is returned). Raises on IO failure — an unjournalable request
        must not be admitted as if it were durable."""
        entry = {
            "id": req_id, "tenant": tenant, "model": model_name,
            "options": dict(options or {}),
            "timeout-s": timeout_s,
            "idempotency-key": idempotency_key,
            "submitted-at": round(time.time(), 6),
            "history-edn": history_to_edn(history),
        }
        self._write(self._req_path(req_id), entry)
        obs.count("serve.journal.appended")

    def _write_marker(self, entry_path: str, done_path: str,
                      payload: Dict[str, Any],
                      result: Optional[Dict[str, Any]],
                      **obs_kw: Any) -> bool:
        """Shared terminal-marker writer (one-shot ``finish`` and the
        session close marker): JSON-sanitize the result, and — UNDER
        the lock — exists-check the entry, first-marker-wins check
        the done path, then write. A failed write means the entry
        replays after a crash (at-least-once, never lost): recorded,
        never raised into the dispatcher. Returns True iff THIS call
        wrote the marker."""
        if result is not None:
            try:
                payload["result"] = json.loads(
                    json.dumps(result, default=str))
            # jtlint: ok fallback — unJSONable result: marker written without payload, status kept
            except (TypeError, ValueError):
                pass
        with self._lock:
            if not os.path.exists(entry_path) \
                    or os.path.exists(done_path):
                return False
            try:
                self._write(done_path, payload)
            except OSError as e:
                log.warning("journal marker failed for %s: %s",
                            done_path, e)
                obs.engine_fallback("serve-journal",
                                    type(e).__name__, **obs_kw)
                return False
            return True

    def finish(self, req_id: str, status: str,
               result: Optional[Dict[str, Any]] = None) -> None:
        """Mark a journaled request terminal (idempotent; the first
        marker wins — the exists-check and the write share the lock,
        so a concurrent cancel cannot clobber a published verdict's
        marker). Unknown ids are a no-op — requests admitted while
        journaling was off, or already collected."""
        wrote = self._write_marker(
            self._req_path(req_id), self._done_path(req_id),
            {"id": req_id, "status": status,
             "ts": round(time.time(), 6)}, result, id=req_id)
        if not wrote:
            return
        with self._lock:
            self._finishes += 1
            due = self._finishes % self.gc_every == 0
        if due:
            self.gc()

    def discard(self, req_id: str) -> None:
        """Remove an entry that was never admitted (backpressure
        retraction after the append) — its lease file, if any, goes
        with it (a GC'd entry must not leave an orphan claim)."""
        for p in (self._req_path(req_id), self._done_path(req_id),
                  self._lease_path(req_id)):
            try:
                os.unlink(p)
            # jtlint: ok fallback — best-effort unlink of a retracted entry
            except OSError:
                pass

    def cancel_pending(self, req_id: str) -> bool:
        """Write a ``cancelled`` marker for a pending (unreplayed /
        unfinished) entry so a restart cannot resurrect it. Returns
        True when this call cancelled it (finish itself re-checks
        under the lock, so a racing verdict marker wins or we do —
        never a clobber)."""
        if not os.path.exists(self._req_path(req_id)) \
                or os.path.exists(self._done_path(req_id)):
            return False
        self.finish(req_id, "cancelled",
                    {"valid": "unknown", "cause": "cancelled"})
        term = self.lookup_terminal(req_id)
        return bool(term) and term.get("status") == "cancelled"

    # -- leases (fleet mode) ---------------------------------------------
    def _lease_path(self, entry_id: str) -> str:
        return os.path.join(self.root, entry_id + _LEASE_SUFFIX)

    @contextlib.contextmanager
    def _fleet_lock(self):
        """Cross-PROCESS critical section for lease renew/steal: the
        read-holder-then-overwrite window must be serialized across
        replicas (two stealers racing through it unserialized could
        both "win" one expired lease). ``flock`` on a shared lock
        file — replicas of one fleet share a store root on one host,
        which is exactly flock's domain; platforms without fcntl fall
        back to the in-process lock (single-replica semantics)."""
        if not _HAVE_FLOCK:
            with self._lock:
                yield
            return
        fd = os.open(os.path.join(self.root, ".fleet.lock"),
                     os.O_CREAT | os.O_RDWR, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
            yield
        finally:
            try:
                fcntl.flock(fd, fcntl.LOCK_UN)
            finally:
                os.close(fd)

    @staticmethod
    def _read_lease(path: str) -> Optional[Dict[str, Any]]:
        try:
            with open(path) as f:
                holder = json.load(f)
        # jtlint: ok fallback — absent and torn both READ as "no live holder" by design: a torn lease is stealable (its writer died mid-write or loses the fleet-locked steal race), and the steal itself records
        except (OSError, ValueError):
            return None
        # bad-PAYLOAD (parseable but garbage-shaped) is a different
        # adversary than torn: without the schema check a junk
        # expires-at would crash every scanner that floats it. A
        # corrupt lease is quarantined aside (so it cannot wedge the
        # entry) and reads as "no live holder" — detected, recorded,
        # never trusted
        bad = not isinstance(holder, dict)
        if not bad:
            try:
                float(holder.get("expires-at") or 0.0)
            # jtlint: ok fallback — recorded just below: every bad path counts serve.lease.corrupt and quarantines with a serve-lease decision
            except (TypeError, ValueError):
                bad = True
        if bad:
            obs.count("serve.lease.corrupt")
            obs.decision("serve-lease", "quarantine",
                         cause="bad-payload",
                         path=os.path.basename(path))
            with contextlib.suppress(OSError):
                os.replace(path, path + ".corrupt")
            return None
        return holder

    def claim(self, entry_id: str, *, replica: str,
              ttl_s: float) -> bool:
        """Claim one journal entry (or session id) for ``replica``
        with a wall-clock lease of ``ttl_s`` seconds. Returns True
        when this replica now holds the lease: a fresh claim (the
        kernel-atomic link-into-place fast path), a renewal of its
        own live lease, or a steal of an expired/torn one. False when
        another replica holds a live lease (or the claim write
        failed)."""
        path = self._lease_path(entry_id)
        replica = str(replica)
        payload = {"id": entry_id, "replica": replica,
                   "expires-at": round(time.time() + float(ttl_s), 6),
                   "claimed-at": round(time.time(), 6)}
        if self.lease_meta:
            payload.update(self.lease_meta)
        # the lease-file corruption point: an armed "lease-write"
        # claim lands as a bad-payload (junk expires-at) lease the
        # claimer BELIEVES it holds — siblings must detect it,
        # quarantine it, and steal the entry rather than trust it
        try:
            faults.fire("lease-write")
        # jtlint: ok fallback — fire() recorded serve-fault/injected; the bad-payload lease IS the injected behavior
        except faults.InjectedFault:
            payload = dict(payload, **{"expires-at": "garbage"})
        # fast path: write the FULL payload to a private tmp, then
        # hard-link it into place — the lease appears atomically with
        # its content (an O_EXCL create + write would expose an empty
        # file a concurrent reader mistakes for torn-and-stealable)
        tmp = path + f".{os.getpid()}.{threading.get_ident()}.tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(payload, f)
                f.flush()
                if self.fsync:
                    os.fsync(f.fileno())
            try:
                os.link(tmp, path)
            except FileExistsError:
                return self._claim_slow(path, payload, replica)
        except OSError as e:
            obs.engine_fallback("serve-lease", type(e).__name__,
                                id=entry_id)
            return False
        finally:
            with contextlib.suppress(OSError):
                os.unlink(tmp)
        obs.count("serve.lease.claimed")
        return True

    def _claim_slow(self, path: str, payload: Dict[str, Any],
                    replica: str) -> bool:
        """The existing-lease path: renew own, refuse live foreign,
        steal expired/torn — all under the fleet lock."""
        with self._fleet_lock():
            holder = self._read_lease(path)
            live = (holder is not None
                    and float(holder.get("expires-at") or 0.0)
                    > time.time())
            if live and holder.get("replica") != replica:
                return False
            try:
                self._write(path, payload)
            except OSError as e:
                obs.engine_fallback("serve-lease", type(e).__name__,
                                    id=payload["id"])
                return False
            if live:
                obs.count("serve.lease.renewed")
            elif holder is not None:
                # an expired (or torn) lease changed hands: the dead
                # replica's queued work drains through this survivor
                obs.count("serve.lease.expired")
                obs.count("serve.lease.stolen")
                obs.decision("serve-lease", "steal",
                             cause=str(holder.get("replica")),
                             id=payload["id"], by=replica)
            else:
                obs.count("serve.lease.claimed")
            return True

    def release(self, entry_id: str, replica: str) -> None:
        """Drop this replica's lease (the entry went terminal). A
        foreign lease is left alone: releasing a lease we LOST
        (expired and stolen while we were finishing) must not unlink
        the thief's live claim."""
        path = self._lease_path(entry_id)
        with self._fleet_lock():
            holder = self._read_lease(path)
            if holder is None \
                    or holder.get("replica") != str(replica):
                return
            try:
                os.unlink(path)
            # jtlint: ok fallback — best-effort unlink of an owned lease; it expires anyway
            except OSError:
                return
        obs.count("serve.lease.released")

    def lease_holder(self, entry_id: str) -> Optional[Dict[str, Any]]:
        """The raw lease payload (``replica`` / ``expires-at``), or
        None when unclaimed or torn."""
        return self._read_lease(self._lease_path(entry_id))

    def lease_live(self, entry_id: str) -> Optional[str]:
        """The replica id holding a LIVE (unexpired) lease, or None."""
        holder = self.lease_holder(entry_id)
        if holder is None or float(
                holder.get("expires-at") or 0.0) <= time.time():
            return None
        return str(holder.get("replica"))

    def leases(self) -> Dict[str, Dict[str, Any]]:
        """Every lease file's payload by entry id (chaos gates assert
        each entry is claimed by at most one live lease — trivially
        one FILE per entry; this view exposes holder + expiry)."""
        try:
            names = os.listdir(self.root)
        # jtlint: ok fallback — directory-scan view: an unlistable root degrades to the empty view, same contract as _ids/open_session_ids
        except OSError:
            return {}
        out: Dict[str, Dict[str, Any]] = {}
        for n in names:
            if n.endswith(_LEASE_SUFFIX) and not n.endswith(".tmp"):
                eid = n[:-len(_LEASE_SUFFIX)]
                holder = self.lease_holder(eid)
                if holder is not None:
                    out[eid] = holder
        return out

    # -- streaming sessions ----------------------------------------------
    def _sess_path(self, sid: str) -> str:
        return os.path.join(self.root, sid + _SESS_SUFFIX)

    def _sapp_path(self, sid: str, seq: int) -> str:
        return os.path.join(self.root,
                            f"{sid}{_SAPP_MID}{seq:06d}{_SAPP_SUFFIX}")

    def _sdone_path(self, sid: str) -> str:
        return os.path.join(self.root, sid + _SDONE_SUFFIX)

    def session_open(self, sid: str, *, tenant: str, model_name: str,
                     options: Dict[str, Any]) -> None:
        """Durably record an opened session (BEFORE its id is
        returned): the open itself must survive a SIGKILL or the
        journaled appends have no session to replay into."""
        self._write(self._sess_path(sid), {
            "session": sid, "tenant": tenant, "model": model_name,
            "options": dict(options or {}),
            "opened-at": round(time.time(), 6)})
        obs.count("serve.journal.session_opened")

    def session_append_entry(self, sid: str, seq: int,
                             history) -> None:
        """Durably record one append block (BEFORE its verdict is
        computed, let alone returned): a crash mid-advance replays
        the block and re-derives the frontier from seq order."""
        self._write(self._sapp_path(sid, seq), {
            "session": sid, "seq": int(seq),
            "appended-at": round(time.time(), 6),
            "history-edn": history_to_edn(history)})
        obs.count("serve.journal.session_appended")

    def discard_session_append(self, sid: str, seq: int) -> None:
        """Retract a block whose admission bounced (backpressure after
        the journal write — the client got a 429, not a verdict)."""
        try:
            os.unlink(self._sapp_path(sid, seq))
        # jtlint: ok fallback — best-effort unlink of a retracted append
        except OSError:
            pass

    def session_close_marker(self, sid: str,
                             result: Optional[Dict[str, Any]] = None
                             ) -> None:
        """Mark a session closed (idempotent, first marker wins — the
        shared :meth:`_write_marker` discipline): a restart neither
        replays nor resurrects it, and the close verdict survives.
        Closes drive the GC cadence too — a session-dominated daemon
        (finish() no-ops for session ids) must still collect its
        terminal files."""
        wrote = self._write_marker(
            self._sess_path(sid), self._sdone_path(sid),
            {"session": sid, "ts": round(time.time(), 6)}, result,
            session=sid)
        if not wrote:
            return
        with self._lock:
            self._finishes += 1
            due = self._finishes % self.gc_every == 0
        if due:
            self.gc()

    def open_session_ids(self) -> List[str]:
        """Sessions with an open entry and no close marker (replay
        candidates), oldest first."""
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        done = {n[:-len(_SDONE_SUFFIX)] for n in names
                if n.endswith(_SDONE_SUFFIX)}
        sids = [n[:-len(_SESS_SUFFIX)] for n in names
                if n.endswith(_SESS_SUFFIX)
                and n[:-len(_SESS_SUFFIX)] not in done]

        def _mtime(sid: str) -> float:
            try:
                return os.path.getmtime(self._sess_path(sid))
            except OSError:
                return 0.0
        return sorted(sids, key=lambda s: (_mtime(s), s))

    def load_session(self, sid: str) -> Optional[Dict[str, Any]]:
        try:
            with open(self._sess_path(sid)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def session_lookup_closed(self, sid: str
                              ) -> Optional[Dict[str, Any]]:
        try:
            with open(self._sdone_path(sid)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def session_appends(self, sid: str
                        ) -> List[Tuple[int, Dict[str, Any]]]:
        """Journaled append blocks of one session, ``(seq, entry)``
        in seq order. Corrupt entries are skipped with a recorded
        fallback (the replayer re-derives what it can; a torn append
        was never acknowledged)."""
        prefix = sid + _SAPP_MID
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        out: List[Tuple[int, Dict[str, Any]]] = []
        for n in sorted(names):
            if not (n.startswith(prefix)
                    and n.endswith(_SAPP_SUFFIX)):
                continue
            try:
                with open(os.path.join(self.root, n)) as f:
                    entry = json.load(f)
                out.append((int(entry["seq"]), entry))
            except (OSError, ValueError, KeyError) as e:
                obs.engine_fallback("serve-journal",
                                    type(e).__name__, session=sid,
                                    entry=n)
        out.sort(key=lambda t: t[0])
        return out

    def discard_session(self, sid: str) -> None:
        """Remove every file of one session (GC of closed sessions)."""
        for seq, _e in self.session_appends(sid):
            self.discard_session_append(sid, seq)
        for p in (self._sess_path(sid), self._sdone_path(sid),
                  self._lease_path(sid)):
            try:
                os.unlink(p)
            # jtlint: ok fallback — best-effort unlink during session GC
            except OSError:
                pass

    @staticmethod
    def _gc_oldest(ids: List[str], path_of, excess: int,
                   discard) -> int:
        """Shared oldest-marker-first collection (one-shot pairs and
        closed sessions): mtime-sort the marker paths, discard the
        ``excess`` oldest. Counters are the callers'."""
        if excess <= 0:
            return 0

        def _mtime(x: str) -> float:
            try:
                return os.path.getmtime(path_of(x))
            except OSError:
                return 0.0
        ids.sort(key=lambda x: (_mtime(x), x))
        n = 0
        for x in ids[:excess]:
            discard(x)
            n += 1
        return n

    def _gc_sessions(self) -> int:
        """Collect CLOSED sessions past ``keep_terminal``, oldest
        close marker first; open sessions are never touched."""
        try:
            names = os.listdir(self.root)
        except OSError:
            return 0
        closed = [n[:-len(_SDONE_SUFFIX)] for n in names
                  if n.endswith(_SDONE_SUFFIX)]
        n = self._gc_oldest(closed, self._sdone_path,
                            len(closed) - self.keep_terminal,
                            self.discard_session)
        if n:
            obs.count("serve.journal.session_gc", n)
        return n

    # -- views -----------------------------------------------------------
    def _ids(self) -> Dict[str, bool]:
        """id -> has-done-marker, from one directory scan."""
        out: Dict[str, bool] = {}
        try:
            names = os.listdir(self.root)
        except OSError:
            return out
        done = {n[:-len(_DONE_SUFFIX)] for n in names
                if n.endswith(_DONE_SUFFIX)}
        for n in names:
            if n.endswith(_REQ_SUFFIX):
                rid = n[:-len(_REQ_SUFFIX)]
                out[rid] = rid in done
        return out

    def pending_ids(self) -> List[str]:
        """Unfinished entries, oldest first (by entry mtime)."""
        ids = [rid for rid, fin in self._ids().items() if not fin]

        def _mtime(rid: str) -> float:
            try:
                return os.path.getmtime(self._req_path(rid))
            except OSError:
                return 0.0
        return sorted(ids, key=lambda rid: (_mtime(rid), rid))

    def pending_count(self) -> int:
        # hot path (/healthz, per-dispatch stats): one listdir, no
        # per-entry mtime stats — pending_ids' sort order is only
        # needed by replay
        return sum(1 for fin in self._ids().values() if not fin)

    def open_session_count(self) -> int:
        # hot path (per-dispatch stats): one listdir, no mtime sort —
        # open_session_ids' ordering is only needed by replay
        try:
            names = os.listdir(self.root)
        except OSError:
            return 0
        done = {n[:-len(_SDONE_SUFFIX)] for n in names
                if n.endswith(_SDONE_SUFFIX)}
        return sum(1 for n in names if n.endswith(_SESS_SUFFIX)
                   and n[:-len(_SESS_SUFFIX)] not in done)

    def load_entry(self, req_id: str) -> Optional[Dict[str, Any]]:
        try:
            with open(self._req_path(req_id)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def lookup_terminal(self, req_id: str) -> Optional[Dict[str, Any]]:
        """The done marker (status + persisted result), or None."""
        try:
            with open(self._done_path(req_id)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def idempotency_index(self) -> Dict[Any, str]:
        """(tenant, key) -> request id over every journaled entry
        (pending and terminal) — rebuilt at daemon start so dedup
        survives restarts. Keys are TENANT-scoped: one tenant's
        idempotency key must never map onto (or leak the status of)
        another tenant's request."""
        out: Dict[Any, str] = {}
        for rid in self._ids():
            e = self.load_entry(rid)
            if e and e.get("idempotency-key"):
                out[(str(e.get("tenant") or "anonymous"),
                     str(e["idempotency-key"]))] = rid
        return out

    # -- GC --------------------------------------------------------------
    def gc(self) -> int:
        """Collect terminal entry/marker pairs past ``keep_terminal``,
        oldest marker first. Pending entries are never touched.
        Returns how many requests were collected."""
        ids = [rid for rid, fin in self._ids().items() if fin]
        n = self._gc_oldest(ids, self._done_path,
                            len(ids) - self.keep_terminal,
                            self.discard)
        if n:
            obs.count("serve.journal.gc", n)
        return n + self._gc_sessions()

    def stats(self) -> Dict[str, Any]:
        ids = self._ids()
        pending = sum(1 for fin in ids.values() if not fin)
        try:
            leases = sum(1 for n in os.listdir(self.root)
                         if n.endswith(_LEASE_SUFFIX)
                         and not n.endswith(".tmp"))
        # jtlint: ok fallback — stats view: an unlistable root reports zero leases, same contract as the other directory-scan views
        except OSError:
            leases = 0
        return {"pending": pending,
                "terminal": len(ids) - pending,
                "sessions-open": self.open_session_count(),
                "leases": leases,
                "keep_terminal": self.keep_terminal,
                "root": self.root}
