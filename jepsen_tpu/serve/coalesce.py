"""Admission queue + coalescer: continuous multi-tenant batching.

The serving layer's scheduling problem is the inference-server one
(Orca/vLLM continuous batching): requests arrive one at a time, the
device engine wants lockstep groups of compatible lanes, and nobody
may wait for a "full" batch — a request rides the NEXT dispatch group
whose geometry it fits. The pieces:

- **Admission** (:meth:`AdmissionQueue.submit`): bounded queue.
  Admission past the bound raises :class:`Backpressure` — the HTTP
  layer turns that into a 429 instead of letting the host queue (and
  every packed history on it) grow without bound.
- **Coalescing** (:meth:`AdmissionQueue.next_batch`): the dispatcher
  thread asks for one dispatch group at a time. Queued requests are
  grouped by model signature (only same-model histories share a
  union transition tensor), the oldest signature goes first, and the
  selected requests are bucketed by history length with
  :func:`jepsen_tpu.checkers.reach_batch.plan_buckets` — the SAME
  packer the lockstep batch engine uses — so a 10k-op history never
  drags 50-op co-tenants through its padded walk. One plan group is
  returned per call; the rest stay queued and coalesce with whatever
  arrives while the device walks (that is the continuous part).
- **Fairness**: within a dispatch group tenants are served
  oldest-first (by each tenant's oldest queued request), and a
  configurable per-tenant in-flight cap keeps one chatty tenant from
  occupying every lane of every group while others starve.
- **Deadlines**: requests whose deadline passes while queued are
  completed as ``timeout`` right here (fallback stage
  ``serve-timeout`` in the obs ledger) — they never waste a lane.
- **Lane placement** (``lanes > 1``): with N dispatcher lanes (one
  per device/device group, ``serve/engine.py``), each selected group
  is placed onto the least-loaded lane, scanning from a round-robin
  pointer so equal loads rotate — the multi-queue bookkeeping of
  ``reach._LockstepDispatchState`` (``di = gi % n_dev`` plus
  per-device group counts) lifted to the admission side. A group
  placed on a busy sibling is *staged* for that lane; staged groups
  are already marked in-flight, so the drain contract (depth==0 ∧
  inflight=={}) still covers them. Session groups additionally
  exclude their session from re-selection while one of its groups is
  anywhere in flight: two lanes advancing one carried frontier would
  reorder seq.

Everything in this module is pure host-side bookkeeping — no jax, no
device — so the scheduling policy is unit-testable in microseconds
(``tests/test_serve.py``).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence

from jepsen_tpu import obs
from jepsen_tpu.serve import request as rq

# nominal slot width used for plan_buckets' padding floor: the packer
# only consults W for its SMEM-budget floor bucket, and 5 concurrent
# processes is the repo-wide default workload shape. A wrong hint
# costs pack efficiency, never correctness.
_W_HINT = 5

# lane cap for one mega-batch session group: the batched walk pads
# the lane axis to a power of two, so the cap bounds the largest
# compiled lane geometry (and the per-launch stream buffer) without
# limiting throughput — excess sessions simply ride the next group
_MEGA_GROUP_CAP = 1024


class Backpressure(RuntimeError):
    """The admission queue is at its bound; the client should retry
    later (HTTP 429)."""


def plan_admission(requests: Sequence["rq.CheckRequest"], *,
                   group: int = 32,
                   w_hint: int = _W_HINT) -> List[List[int]]:
    """Partition compatible requests into dispatch groups: length
    buckets via :func:`reach_batch.plan_buckets` (longest bucket
    first), then oldest-tenant-first WITHIN each group.

    Returns index lists into ``requests``. Fairness ordering: tenants
    are ranked by their oldest member request's submit time, requests
    within a tenant by their own submit time — so the tenant who has
    waited longest heads every group it appears in.

    Session blocks (append/close) are the exception to length
    bucketing. Blocks sharing one solo per-session signature become a
    single dispatch group in strict seq order (splitting them across
    length buckets could dispatch block 3 before block 2, and a
    carried frontier cannot be advanced out of order). Blocks sharing
    a MEGA signature span many sessions: sessions are ranked
    oldest-tenant-first (then oldest-session-first within a tenant —
    the same fairness the one-shot path applies to requests), chunked
    into groups of at most ``_MEGA_GROUP_CAP`` sessions, and each
    session's blocks stay contiguous in seq order inside its group
    (the dispatcher advances one wave of same-rank blocks per batched
    launch)."""
    from jepsen_tpu.checkers import reach_batch

    if not requests:
        return []
    if requests[0].session is not None:
        by_sess: Dict[str, List[int]] = {}
        for i, r in enumerate(requests):
            by_sess.setdefault(r.session.id, []).append(i)
        order = sorted(range(len(requests)),
                       key=lambda i: (requests[i].seq,
                                      requests[i].t_submit, i))
        if len(by_sess) == 1:
            return [order]
        oldest_of: Dict[str, float] = {}
        sess_oldest: Dict[str, float] = {}
        sess_tenant: Dict[str, str] = {}
        for r in requests:
            t = oldest_of.get(r.tenant)
            if t is None or r.t_submit < t:
                oldest_of[r.tenant] = r.t_submit
            t = sess_oldest.get(r.session.id)
            if t is None or r.t_submit < t:
                sess_oldest[r.session.id] = r.t_submit
            sess_tenant[r.session.id] = r.tenant
        ranked = sorted(
            by_sess,
            key=lambda sid: (oldest_of[sess_tenant[sid]],
                             sess_tenant[sid], sess_oldest[sid], sid))
        out: List[List[int]] = []
        for lo in range(0, len(ranked), _MEGA_GROUP_CAP):
            chunk = ranked[lo:lo + _MEGA_GROUP_CAP]
            g: List[int] = []
            for sid in chunk:
                g.extend(sorted(
                    by_sess[sid],
                    key=lambda i: (requests[i].seq,
                                   requests[i].t_submit, i)))
            out.append(g)
        return out
    lens = [max(1, int(r.packed.n)) for r in requests]
    groups = reach_batch.plan_buckets(lens, w_hint, group=group)
    oldest_of: Dict[str, float] = {}
    for r in requests:
        t = oldest_of.get(r.tenant)
        if t is None or r.t_submit < t:
            oldest_of[r.tenant] = r.t_submit
    out: List[List[int]] = []
    for g in groups:
        out.append(sorted(
            g, key=lambda i: (oldest_of[requests[i].tenant],
                              requests[i].tenant,
                              requests[i].t_submit, i)))
    return out


class AdmissionQueue:
    """Bounded multi-tenant admission queue feeding one dispatcher.

    ``max_depth`` bounds QUEUED requests (dispatched ones no longer
    count — they are bounded by ``group`` times the dispatch
    pipelining, not by this queue). ``max_inflight_per_tenant`` caps
    how many of one tenant's requests may be walking on the device at
    once; requests over the cap simply stay queued for a later group.
    ``lanes`` is the number of dispatcher consumers this queue feeds
    (1 keeps the single-dispatcher behavior bit-identical).
    """

    # jtlint lock discipline: every shared attribute — the queue, the
    # tenant in-flight counts, and ALL lane-placement state — is only
    # touched under the condition's lock (methods named *_locked are
    # called with it held)
    _GUARDED_BY = {"_nonempty": ("_queued", "_inflight", "_staged",
                                 "_lane_load", "_rr",
                                 "_inflight_sessions")}

    def __init__(self, max_depth: int = 256,
                 max_inflight_per_tenant: int = 8,
                 group: int = 32, lanes: int = 1) -> None:
        self.max_depth = int(max_depth)
        self.max_inflight_per_tenant = int(max_inflight_per_tenant)
        self.group = int(group)
        self.lanes = max(1, int(lanes))
        self._nonempty = threading.Condition(threading.Lock())
        self._queued: List[rq.CheckRequest] = []
        self._inflight: Dict[str, int] = {}     # tenant -> walking now
        # lane placement: per-lane staged (ready, placed, not yet
        # picked up) groups + per-lane load (staged + dispatching
        # groups) + the round-robin scan pointer + the set of session
        # ids with a group anywhere in flight (seq-order guard)
        self._staged: List["deque[List[rq.CheckRequest]]"] = \
            [deque() for _ in range(self.lanes)]
        self._lane_load: List[int] = [0] * self.lanes
        self._rr = 0
        self._inflight_sessions: set = set()
        self.on_timeout: Optional[Callable[[rq.CheckRequest], None]] = None

    # -- admission -------------------------------------------------------
    def submit(self, req: "rq.CheckRequest",
               force: bool = False) -> None:
        """Admit one request. ``force`` bypasses the depth bound —
        used ONLY for journal replay (already-admitted work whose 202
        was returned before the crash must not bounce off its own
        backlog) and for hung-dispatch requeues (the request already
        holds a queue slot's worth of accounting)."""
        with self._nonempty:
            if not force and len(self._queued) >= self.max_depth:
                obs.count("serve.rejected.backpressure")
                obs.engine_fallback("serve-admit", "Backpressure",
                                    tenant=req.tenant, ops=req.n_ops,
                                    depth=len(self._queued))
                raise Backpressure(
                    f"admission queue at bound ({self.max_depth})")
            self._queued.append(req)
            obs.count("serve.admitted")
            obs.gauge("serve.queue_depth", len(self._queued))
            self._nonempty.notify_all()

    def cancel(self, req_id: str) -> Optional["rq.CheckRequest"]:
        """Remove a still-queued request (client cancellation).
        Returns it, or None when it is not queued (already dispatched
        or unknown — dispatched requests cancel via their
        ``cancel_requested`` flag, observed by the group's abort
        hook)."""
        with self._nonempty:
            for i, r in enumerate(self._queued):
                if r.id == req_id:
                    del self._queued[i]
                    obs.gauge("serve.queue_depth", len(self._queued))
                    return r
        return None

    def depth(self) -> int:
        with self._nonempty:
            return len(self._queued)

    def inflight(self) -> Dict[str, int]:
        with self._nonempty:
            return {t: n for t, n in self._inflight.items() if n > 0}

    def lane_loads(self) -> List[int]:
        """Per-lane load (staged + dispatching groups) — stats view."""
        with self._nonempty:
            return list(self._lane_load)

    # -- dispatch side ---------------------------------------------------
    def _expire_queued_locked(self, now: float
                              ) -> List["rq.CheckRequest"]:
        expired = [r for r in self._queued if r.expired(now)]
        if expired:
            self._queued = [r for r in self._queued
                            if not r.expired(now)]
        return expired

    def next_batch(self, timeout: Optional[float] = None,
                   lane: Optional[int] = None
                   ) -> List["rq.CheckRequest"]:
        """Block until work is available (or ``timeout`` elapses: empty
        list) and return ONE dispatch group, marked in-flight.

        Selection: expire dead requests, pick the model signature with
        the oldest queued request, take its requests up to each
        tenant's remaining in-flight allowance, and return the first
        :func:`plan_admission` group (longest length bucket first —
        matching the lockstep scheduler's big-walk-first pipelining).
        Callers MUST pair every returned batch with
        :meth:`mark_done`.

        ``lane`` identifies the calling dispatcher lane. ``None`` is
        the single-consumer path (selection IS delivery — no placement
        bookkeeping, the pre-lanes behavior). Lane consumers first
        drain their own staged groups, then select fresh work: a fresh
        group is placed (:meth:`_place_locked`) on the least-loaded
        lane, which may be a SIBLING — then it is staged there and the
        caller selects again, so a fast lane keeps feeding slow
        siblings instead of idling."""
        deadline = (time.monotonic() + timeout
                    if timeout is not None else None)
        with self._nonempty:
            while True:
                now = time.monotonic()
                for r in self._expire_queued_locked(now):
                    self._timeout_queued(r)
                if lane is not None and self._staged[lane]:
                    return self._staged[lane].popleft()
                batch = self._select_locked()
                if batch:
                    self._mark_selected_locked(batch, now)
                    if lane is None:
                        return batch
                    target = self._place_locked()
                    self._lane_load[target] += 1
                    for r in batch:
                        r.lane = target
                    if target == lane:
                        return batch
                    self._staged[target].append(batch)
                    self._nonempty.notify_all()
                    continue
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    return []
                self._nonempty.wait(remaining)

    def _mark_selected_locked(self, batch: List["rq.CheckRequest"],
                              now: float) -> None:
        """Move a freshly-selected group into in-flight accounting.
        Done at SELECTION time (not pickup) so the drain contract —
        depth==0 ∧ inflight=={} means nothing is pending — covers
        groups staged for a busy lane too."""
        for r in batch:
            self._inflight[r.tenant] = \
                self._inflight.get(r.tenant, 0) + 1
            # coalesce stamp: selected into a dispatch group (the
            # engine stamps t_dispatch when the device call starts)
            r.t_coalesce = now
            r.status = rq.DISPATCHED
        for r in batch:
            # EVERY member session (a mega group spans many) is
            # excluded from re-selection while the group is anywhere
            # in flight — the seq-order guard
            if r.session is not None:
                self._inflight_sessions.add(r.session.id)
        obs.gauge("serve.queue_depth", len(self._queued))
        if len(batch) > 1:
            obs.count("serve.coalesced", len(batch))

    def _place_locked(self) -> int:
        """Pick the lane for a fresh group: least loaded, scanning
        from the round-robin pointer so equal loads rotate lanes (the
        ``reach._LockstepDispatchState`` multi-queue policy — strict
        round-robin under uniform load, load-aware when a lane falls
        behind on a long walk). The pointer advances past the winner."""
        best = self._rr
        for k in range(1, self.lanes):
            di = (self._rr + k) % self.lanes
            if self._lane_load[di] < self._lane_load[best]:
                best = di
        self._rr = (best + 1) % self.lanes
        return best

    def _select_locked(self) -> List["rq.CheckRequest"]:
        if not self._queued:
            return []
        # eligibility: per-tenant in-flight allowance, oldest first.
        # A session with a group already in flight (on ANY lane) is
        # skipped entirely: its carried frontier advances in seq
        # order, so a second lane must not pick up block k+1 while
        # block k is still walking.
        allowance: Dict[str, int] = {}
        eligible: List[rq.CheckRequest] = []
        for r in sorted(self._queued, key=lambda r: r.t_submit):
            if r.session is not None \
                    and r.session.id in self._inflight_sessions:
                continue
            a = allowance.get(r.tenant)
            if a is None:
                a = max(0, self.max_inflight_per_tenant
                        - self._inflight.get(r.tenant, 0))
            if a <= 0:
                allowance[r.tenant] = 0
                continue
            allowance[r.tenant] = a - 1
            eligible.append(r)
        if not eligible:
            return []
        # one model signature per dispatch group: the one whose oldest
        # eligible request has waited longest. Signatures are read
        # ONCE per request through a per-SESSION snapshot: the mega
        # signature is a lock-free cached read that a concurrent
        # close/sweep may flip mid-pass, and two reads of one
        # session's blocks straddling the flip could admit block k+1
        # while excluding block k — a seq reorder. One read per
        # session per pass makes that impossible (a stale snapshot
        # only costs grouping efficiency; stage-time re-validation
        # under the session lock owns correctness).
        sess_sig: Dict[str, Optional[tuple]] = {}
        sigs: Dict[int, tuple] = {}
        for r in eligible:
            if r.session is not None and r.kind == "session-append":
                sid = r.session.id
                if sid not in sess_sig:
                    sess_sig[sid] = r.session.mega_sig()
                g = sess_sig[sid]
                sigs[id(r)] = (("session-mega",) + g if g is not None
                               else ("session", sid))
            else:
                sigs[id(r)] = r.model_sig
        sig = sigs[id(eligible[0])]
        same = [r for r in eligible if sigs[id(r)] == sig]
        groups = plan_admission(same, group=self.group)
        # anti-starvation: dispatch the group holding the OLDEST
        # request (same[0]), not unconditionally the longest bucket —
        # a stream of fresh long histories must not preempt a short
        # one forever
        pick = next(g for g in groups if 0 in g)
        batch = [same[i] for i in pick]
        chosen = {id(r) for r in batch}
        self._queued = [r for r in self._queued
                        if id(r) not in chosen]
        return batch

    def mark_done(self, batch: Sequence["rq.CheckRequest"],
                  lane: Optional[int] = None) -> None:
        """Release the batch's tenants' in-flight slots (and, for lane
        consumers, the lane's load unit and the session's in-flight
        exclusion) and wake the dispatchers' next selection."""
        with self._nonempty:
            for r in batch:
                n = self._inflight.get(r.tenant, 0) - 1
                if n > 0:
                    self._inflight[r.tenant] = n
                else:
                    self._inflight.pop(r.tenant, None)
            for r in batch:
                if r.session is not None:
                    self._inflight_sessions.discard(r.session.id)
            if lane is not None and batch:
                self._lane_load[lane] = \
                    max(0, self._lane_load[lane] - 1)
            self._nonempty.notify_all()

    def _timeout_queued(self, req: "rq.CheckRequest") -> None:
        obs.count("serve.timeout")
        obs.engine_fallback("serve-timeout", "DeadlineExpired",
                            tenant=req.tenant, ops=req.n_ops,
                            queued_s=round(
                                time.monotonic() - req.t_submit, 6))
        cb = self.on_timeout
        if cb is not None:
            cb(req)
