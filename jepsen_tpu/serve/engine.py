"""The dispatcher: one long-lived thread between the admission queue
and the device engines.

Each iteration takes ONE coalesced dispatch group from the queue and
runs it through the same checker chains the CLI uses — so daemon
verdicts are the standalone verdicts:

- a group of one goes through :func:`facade.auto_check_packed` (the
  single-history auto chain, abortable through the segmented walk);
- a group of many goes through :func:`facade.auto_check_many_packed`,
  whose first route is the streaming lockstep batch scheduler
  (``reach._dispatch_lockstep_stream``) — the admission coalescer
  sized the group with the same ``plan_buckets`` packer, so the
  engine-side re-plan reproduces the group geometry.

Because the thread — and the process — lives across requests, the
engine-side caches stay hot: compiled kernel geometries (jax in-proc
+ persistent compilation cache), the memo/disk-memo tiers, and the
device-resident operand cache (``transfer.cached_put``). That is the
entire point of the daemon: request N+1 pays marshalling, not
compilation.

Deadlines and cancellation compose into the chain's ``should_abort``
hook: the group aborts (cleanly, at a segment boundary) once EVERY
live member is expired or cancelled; an individual member whose
deadline passes mid-walk keeps the group running for its co-tenants
but reports ``timeout`` itself. A dispatch exception never kills the
daemon — every member gets a contained ``"unknown"`` verdict and the
crash lands in the obs ledger (``serve-dispatch`` fallback).
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from jepsen_tpu import obs
from jepsen_tpu.serve import request as rq
from jepsen_tpu.serve.coalesce import AdmissionQueue

log = logging.getLogger("jepsen.serve")


def _profiler_start(path: str) -> None:
    """Module-level indirection so tests can stub the profiler."""
    import jax
    jax.profiler.start_trace(path)


def _profiler_stop() -> None:
    import jax
    jax.profiler.stop_trace()


class _TimeSeriesRing:
    """Rolling in-memory time series of serving health: one point per
    completed dispatch — req/s since the previous point, p50/p99 over
    the e2e-latency histogram delta, queue depth, and in-flight lanes.
    Bounded (default 256 points ~ the last few minutes under load);
    serialized into ``stats.json`` so the ``/engine`` dashboard can
    sparkline a daemon it does not share a process with."""

    def __init__(self, cap: int = 256) -> None:
        self._lock = threading.Lock()
        self._points: "deque[Dict[str, Any]]" = deque(maxlen=cap)
        self._prev_ts: Optional[float] = None
        self._prev_done: float = 0.0
        self._prev_hist: Optional[Dict[str, Any]] = None

    def sample(self, queue: AdmissionQueue,
               snap: Optional[Dict[str, Any]] = None) -> None:
        # `snap` shares one Recorder.snapshot() per dispatch between
        # the ring and the stats file — snapshot deep-copies the
        # (up-to-10k-record) ledger under the global obs lock, so
        # taking it once per loop iteration matters
        if snap is None:
            snap = obs.core.GLOBAL.snapshot()
        now = time.monotonic()
        done = snap["counters"].get("serve.completed", 0.0)
        hist = snap["histograms"].get("serve.e2e_s")
        depth = queue.depth()
        lanes = sum(queue.inflight().values())
        with self._lock:
            dt = (now - self._prev_ts) if self._prev_ts is not None \
                else None
            delta = obs.hist_delta(hist, self._prev_hist)
            p50 = obs.hist_quantile(delta, 0.50)
            p99 = obs.hist_quantile(delta, 0.99)
            point = {
                "ts": round(time.time(), 3),
                "req_s": (round((done - self._prev_done) / dt, 3)
                          if dt and dt > 0 else None),
                "p50_s": round(p50, 6) if p50 is not None else None,
                "p99_s": round(p99, 6) if p99 is not None else None,
                "depth": depth,
                "inflight": lanes,
            }
            self._points.append(point)
            self._prev_ts = now
            self._prev_done = done
            self._prev_hist = hist

    def points(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(p) for p in self._points]


class Dispatcher:
    """Owns the dispatch thread. ``start()``/``stop()`` bracket the
    daemon's life; ``drain()`` waits for the queue to empty (tests,
    graceful shutdown)."""

    def __init__(self, queue: AdmissionQueue, registry: "rq.Registry",
                 *, engine_kw: Optional[Dict[str, Any]] = None,
                 store_root: Optional[str] = None,
                 persist: bool = False) -> None:
        self.queue = queue
        self.registry = registry
        self.engine_kw = dict(engine_kw or {})
        self.store_root = store_root
        self.persist = persist and store_root is not None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.dispatch_counts: Dict[str, int] = {}
        self._counts_lock = threading.Lock()
        self.ring = _TimeSeriesRing()
        # on-demand profiling (POST /profile): arm -> the next N
        # dispatches run under jax.profiler.trace, capture persisted
        # under the store root
        self._profile_lock = threading.Lock()
        self._profile_left = 0
        self._profile_dir: Optional[str] = None
        self._profile_active = False
        queue.on_timeout = self._finish_timeout_queued

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "Dispatcher":
        # warm the persistent caches once, before the first request
        from jepsen_tpu.checkers import reach
        reach._ensure_persistent_caches()
        self._thread = threading.Thread(target=self._loop,
                                        name="serve-dispatch",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
        # flush a still-open profiler capture: an armed profile that
        # never saw enough dispatches must not leave the trace
        # recording (and its promised capture dir empty) forever
        self._profile_force_stop()

    def drain(self, timeout: float = 60.0) -> bool:
        """Wait until no request is queued or walking. Judged from
        the QUEUE's state alone: a batch moves queued → in-flight
        atomically under the queue lock inside ``next_batch`` and
        leaves in-flight only in ``mark_done`` (after its results
        published), so depth==0 ∧ inflight=={} has no window where a
        batch is about to dispatch — a dispatcher-side idle flag
        would."""
        if self._thread is None:        # never started: nothing will
            return self.queue.depth() == 0  # ever drain the queue
        end = time.monotonic() + timeout
        while time.monotonic() < end:
            if self.queue.depth() == 0 and not self.queue.inflight():
                return True
            time.sleep(0.01)
        return False

    # -- the loop --------------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.is_set():
            batch = self.queue.next_batch(timeout=0.1)
            if not batch:
                continue
            self._profile_maybe_start()
            try:
                self._dispatch(batch)
            finally:
                self.queue.mark_done(batch)
                obs.gauge("serve.inflight", 0)
                self._profile_maybe_stop()
                snap = obs.core.GLOBAL.snapshot()
                self.ring.sample(self.queue, snap)
                self._write_stats_file(snap)

    # -- on-demand profiling ---------------------------------------------
    def arm_profile(self, dispatches: int) -> str:
        """Arm ``jax.profiler.trace`` around the next N dispatches;
        the capture persists under ``<store-root>/serve/profile-<ts>/``.
        Raises RuntimeError when already armed or without a store
        root (the capture needs somewhere durable to land)."""
        if self.store_root is None:
            raise RuntimeError("profiling needs a store root")
        from jepsen_tpu import store
        with self._profile_lock:
            if self._profile_left > 0 or self._profile_active:
                raise RuntimeError(
                    f"profile already armed "
                    f"({self._profile_left} dispatches left)")
            d = store.serve_profile_dir(self.store_root)
            self._profile_dir = d
            self._profile_left = int(dispatches)
            return d

    def profile_state(self) -> Dict[str, Any]:
        with self._profile_lock:
            return {"armed": int(self._profile_left),
                    "active": bool(self._profile_active),
                    "dir": self._profile_dir}

    def _profile_maybe_start(self) -> None:
        with self._profile_lock:
            if self._profile_left <= 0 or self._profile_active:
                return
            path = self._profile_dir
            try:
                _profiler_start(path)
                self._profile_active = True
                obs.decision("serve-profile", "route",
                             cause="start", dir=path,
                             dispatches=self._profile_left)
            except Exception as e:                      # noqa: BLE001
                log.warning("profiler start failed: %s", e)
                obs.engine_fallback("serve-profile",
                                    type(e).__name__)
                self._profile_left = 0

    def _profile_maybe_stop(self) -> None:
        with self._profile_lock:
            if not self._profile_active:
                return
            self._profile_left -= 1
            if self._profile_left > 0:
                return
            self._profile_stop_locked()

    def _profile_force_stop(self) -> None:
        """Stop and flush an active capture regardless of how many
        armed dispatches remain (daemon shutdown)."""
        with self._profile_lock:
            if self._profile_active:
                self._profile_stop_locked()
            self._profile_left = 0

    def _profile_stop_locked(self) -> None:
        try:
            _profiler_stop()
            obs.count("serve.profile.captures")
        except Exception as e:                          # noqa: BLE001
            log.warning("profiler stop failed: %s", e)
            obs.engine_fallback("serve-profile", type(e).__name__)
        self._profile_active = False
        self._profile_left = 0

    def _dispatch(self, batch: List["rq.CheckRequest"]) -> None:
        req0 = batch[0]
        model = req0.model
        sig = f"{req0.model_name}/H{len(batch)}"
        with self._counts_lock:
            self.dispatch_counts[sig] = \
                self.dispatch_counts.get(sig, 0) + 1
        obs.count("serve.dispatched", len(batch))
        obs.gauge("serve.inflight", len(batch))
        t0 = time.monotonic()
        for r in batch:
            # dispatch stamp + queue-wait histogram: admit -> selected
            # into this group (t_coalesce, stamped by next_batch)
            r.t_dispatch = t0
            obs.histogram("serve.queue_wait_s",
                          max(0.0, (r.t_coalesce or t0) - r.t_submit))
            self.registry.ledger_record(
                r.tenant, "dispatched", id=r.id, group=len(batch),
                ops=int(r.packed.n))

        def _aborted() -> bool:
            # clean group cancellation: fires only when NO member
            # still wants the verdict (composed into the segmented
            # walk's abort polling by the facade chain)
            if self._stop.is_set():
                return True
            now = time.monotonic()
            return all(r.cancel_requested or r.expired(now)
                       for r in batch)

        # per-request engine options apply to the whole dispatch: the
        # coalescer only groups requests whose options are IDENTICAL
        # (they are part of the compatibility signature), so batch[0]
        # speaks for every member
        kw = dict(self.engine_kw)
        kw.update(req0.opts)
        kw["should_abort"] = _aborted
        # quantize the lane count to a power of two by replicating the
        # LONGEST member (its verdict is recomputed and discarded;
        # padding with the longest keeps the group's padded step count
        # unchanged): a serving daemon sees every group width 1..group
        # over its life, and each distinct H is a distinct compiled
        # kernel geometry — the pad bounds that churn to log2(group)
        # geometries a warmup can prime. JEPSEN_TPU_SERVE_NO_PAD=1
        # dispatches raw widths.
        n_real = len(batch)
        packed_list = [r.packed for r in batch]
        # transactional groups: the txn chain is host inference + the
        # closure kernel (whose geometry pads to a power of two
        # INTERNALLY), so the lane-count pad below — a dense-walk
        # geometry concern — does not apply
        from jepsen_tpu.txn.ops import ListAppend as _ListAppend
        is_txn = isinstance(model, _ListAppend)
        pad = 0
        if n_real > 1 and not is_txn \
                and not os.environ.get("JEPSEN_TPU_SERVE_NO_PAD"):
            Hq = 1 << (n_real - 1).bit_length()
            # never pad past the configured group width: the
            # engine-side re-plan splits oversized groups, which would
            # both defeat the pad and break the admission/engine plan
            # agreement
            cap = int(self.engine_kw.get("group") or 0) or 32
            Hq = min(Hq, max(cap, n_real))
            longest = max(packed_list, key=lambda p: p.n)
            pad = max(0, Hq - n_real)
            if pad > 0:
                packed_list = packed_list + [longest] * pad
                obs.count("serve.pad_lanes", pad)
        # the dispatcher thread's own obs records (fallbacks, engine
        # selections from the facade chain, the serve-dispatch crash
        # containment) are captured here and re-emitted into every
        # member request's stitched trace below — ledgers are
        # thread-isolated, so without this a client-side
        # obs.capture() around submit/poll would never see them
        with obs.capture() as cap:
            try:
                from jepsen_tpu.checkers import facade
                with obs.span("serve.dispatch",
                              model=req0.model_name,
                              lanes=len(batch)):
                    if is_txn:
                        # one txn chain per member: host dependency
                        # inference is per-history; the closure
                        # kernel geometry is shared across members
                        # via its power-of-two pad + jit cache
                        results = [facade.auto_check_txn(
                            list(r.history), kw) for r in batch]
                    elif len(batch) == 1:
                        results = [facade.auto_check_packed(
                            model, req0.packed, kw)]
                    else:
                        results = facade.auto_check_many_packed(
                            model, packed_list, kw)[:n_real]
            except Exception as e:                      # noqa: BLE001
                log.warning("serve dispatch crashed: %r", e,
                            exc_info=e)
                obs.engine_fallback("serve-dispatch",
                                    type(e).__name__,
                                    lanes=len(batch))
                err = {"valid": "unknown",
                       "error": f"{type(e).__name__}: {e}"}
                results = [dict(err) for _ in batch]
        t_collect = time.monotonic()
        elapsed = t_collect - t0

        # device-time attribution: the group's measured kernel wall is
        # amortized over its lanes — each member (one real lane) gets
        # wall/lanes, the replicated pad lanes' share is padding waste
        # (a first-class counter). share*n_real + waste == wall, so
        # attributed device-seconds reconcile with dispatch wall by
        # construction (asserted within 2% in tests).
        lanes = n_real + pad
        share = elapsed / lanes
        waste = share * pad
        obs.histogram("serve.dispatch_wall_s", elapsed)
        obs.count("serve.device_s", share * n_real)
        obs.count("serve.pad_waste_s", waste)

        # stitched per-request trace: the group-level dispatch record
        # plus every ledger record the dispatch produced, re-emitted
        # per member with the request id. Fallbacks/swallows also land
        # in the member's TENANT serve ledger, so "no silent fallback"
        # stays assertable from the client side (GET /check/<id> and
        # GET /stats), not just from inside the daemon process.
        engine_recs = [r for r in cap.ledger
                       if r.get("event") in ("selected", "fallback",
                                             "swallowed", "route",
                                             "skipped")]
        disp_rec = {"ts": round(time.time(), 6),
                    "stage": "serve-dispatch", "event": "dispatch",
                    "group": lanes, "real": n_real, "pad": pad,
                    "wall_s": round(elapsed, 6),
                    "device_s": round(share, 9),
                    "pad_waste_s": round(waste, 9)}
        now = time.monotonic()
        for req, res in zip(batch, results):
            req.t_collect = t_collect
            req.device_s = share
            req.stitch([disp_rec] + engine_recs)
            self.registry.add_device_time(req.tenant, share)
            for r in engine_recs:
                if r.get("event") in ("fallback", "swallowed"):
                    self.registry.ledger_record(
                        req.tenant, f"engine-{r['event']}",
                        id=req.id, stage=r.get("stage"),
                        cause=r.get("cause"))
            self._finish(req, res, elapsed, now)

    # -- completion ------------------------------------------------------
    def _finish(self, req: "rq.CheckRequest", res: Dict[str, Any],
                elapsed: float, now: float) -> None:
        if req.cancel_requested:
            status = rq.CANCELLED
            obs.count("serve.cancelled")
        elif req.expired(now) and res.get("valid") not in (True, False):
            # the walk was aborted (or still unknown) past the
            # deadline: a timeout, not a verdict
            status = rq.TIMEOUT
            res = {"valid": "unknown", "cause": "deadline",
                   **{k: v for k, v in res.items() if k != "valid"}}
            obs.count("serve.timeout")
            obs.engine_fallback("serve-timeout", "DeadlineExpired",
                                tenant=req.tenant, ops=req.packed.n,
                                dispatched=True)
        else:
            # a conclusive verdict that merely finished late is still
            # the verdict — deadline enforcement is about not burning
            # device time, not about discarding finished work
            status = rq.DONE
            obs.count("serve.completed")
            # latency histograms observed exactly where serve.completed
            # bumps, so the CI invariant "e2e histogram count equals
            # completed requests" holds at every /metrics scrape
            obs.histogram("serve.e2e_s", now - req.t_submit)
            obs.histogram("serve.service_s",
                          now - (req.t_coalesce or req.t_dispatch
                                 or req.t_submit))
        if self.persist and status == rq.DONE:
            try:
                # provisional done stamp so the PERSISTED waterfall
                # carries its publish stage (registry.finish re-stamps
                # a hair later; the live GET view uses that one)
                req.t_done = now
                req.run_dir = self._persist(req, res)
            except Exception as e:                      # noqa: BLE001
                log.warning("serve persist failed for %s: %s",
                            req.id, e)
        self.registry.finish(req, status, res)
        self.registry.ledger_record(
            req.tenant, status, id=req.id,
            valid=res.get("valid"), engine=res.get("engine"),
            dispatch_s=round(elapsed, 6),
            device_s=round(req.device_s or 0.0, 9),
            latency_s=round(now - req.t_submit, 6))
        obs.count(
            f"serve.tenant.{self.registry.bucket_tenant(req.tenant)}"
            f".{status}")

    def _finish_timeout_queued(self, req: "rq.CheckRequest") -> None:
        """Queue-side deadline expiry (never dispatched)."""
        self.registry.finish(req, rq.TIMEOUT,
                             {"valid": "unknown", "cause": "deadline",
                              "queued-only": True})
        self.registry.ledger_record(req.tenant, rq.TIMEOUT, id=req.id,
                                    queued_only=True)
        obs.count(
            f"serve.tenant.{self.registry.bucket_tenant(req.tenant)}"
            f".timeout")

    # -- persistence -----------------------------------------------------
    def _persist(self, req: "rq.CheckRequest",
                 res: Dict[str, Any]) -> str:
        """Write the request as a browsable store run
        (:func:`jepsen_tpu.store.save_check` —
        ``<root>/serve-<model>/<ts>-<id>/``) so the existing
        ``web.py`` results browser renders daemon traffic exactly
        like CLI runs."""
        from jepsen_tpu import store
        assert self.store_root is not None
        out = dict(res)
        out["serve"] = {"id": req.id, "tenant": req.tenant,
                        "latency-s": round(
                            time.monotonic() - req.t_submit, 6),
                        "device-s": round(req.device_s or 0.0, 9),
                        "waterfall": req.waterfall(),
                        "trace": [dict(r) for r in req.trace]}
        return store.save_check(self.store_root,
                                f"serve-{req.model_name}", req.id,
                                list(req.history), out)

    # -- stats -----------------------------------------------------------
    def stats(self, snap: Optional[Dict[str, Any]] = None
              ) -> Dict[str, Any]:
        if snap is None:
            snap = obs.core.GLOBAL.snapshot()
        counters = {k: v for k, v in snap["counters"].items()
                    if k.startswith(("serve.", "engine.", "lockstep.",
                                     "compile_cache.", "memo_cache.",
                                     "transfer."))}
        with self._counts_lock:
            dispatch = dict(self.dispatch_counts)
        out = {
            "queue": {"depth": self.queue.depth(),
                      "max_depth": self.queue.max_depth,
                      "inflight": self.queue.inflight(),
                      "group": self.queue.group,
                      "max_inflight_per_tenant":
                          self.queue.max_inflight_per_tenant},
            "dispatch": dispatch,
            "counters": counters,
            # headline digests of the serve-path latency histograms
            # (full bucket ladders live on GET /metrics)
            "histograms": {k: obs.hist_summary(h)
                           for k, h in snap["histograms"].items()
                           if k.startswith("serve.")},
            "timeseries": self.ring.points(),
            "profile": self.profile_state(),
        }
        out.update(self.registry.stats())
        return out

    def _write_stats_file(self, snap: Optional[Dict[str, Any]] = None
                          ) -> None:
        """Drop the latest stats snapshot under the store root
        (best-effort) so the results browser's ``/engine`` page can
        render a daemon it does not share a process with."""
        if not self.store_root:
            return
        try:
            d = os.path.join(self.store_root, "serve")
            os.makedirs(d, exist_ok=True)
            tmp = os.path.join(d, ".stats.json.tmp")
            with open(tmp, "w") as f:
                json.dump({"ts": time.time(), **self.stats(snap)}, f,
                          default=str)
            os.replace(tmp, os.path.join(d, "stats.json"))
        except Exception:                               # noqa: BLE001
            pass                # stats are advisory, never fatal
