"""The dispatcher: long-lived lane threads between the admission
queue and the device engines.

The dispatcher runs N *lanes* (default 1), one thread per device or
device group: the coalescer places each ready group onto a lane
(round-robin, least-loaded tie-break — ``serve/coalesce.py``), so one
daemon saturates a multi-chip mesh instead of serializing every group
through one consumer. Per-lane state is isolated: each lane owns its
own circuit breaker (a poisoned lane degrades to host-side serving
alone; its siblings keep the device path) and its own device-ran
attribution flag, so ``serve.device_s`` + ``serve.pad_waste_s`` ==
dispatch wall holds per lane and in the per-lane
``serve.lane.<k>.{device_s,pad_waste_s}`` sums.

Each lane iteration takes ONE coalesced dispatch group from the queue
and runs it through the same checker chains the CLI uses — so daemon
verdicts are the standalone verdicts:

- a group of one goes through :func:`facade.auto_check_packed` (the
  single-history auto chain, abortable through the segmented walk);
- a group of many goes through :func:`facade.auto_check_many_packed`,
  whose first route is the streaming lockstep batch scheduler
  (``reach._dispatch_lockstep_stream``) — the admission coalescer
  sized the group with the same ``plan_buckets`` packer, so the
  engine-side re-plan reproduces the group geometry.

Because the thread — and the process — lives across requests, the
engine-side caches stay hot: compiled kernel geometries (jax in-proc
+ persistent compilation cache), the memo/disk-memo tiers, and the
device-resident operand cache (``transfer.cached_put``). That is the
entire point of the daemon: request N+1 pays marshalling, not
compilation.

Deadlines and cancellation compose into the chain's ``should_abort``
hook: the group aborts (cleanly, at a segment boundary) once EVERY
live member is expired or cancelled; an individual member whose
deadline passes mid-walk keeps the group running for its co-tenants
but reports ``timeout`` itself. A dispatch exception never kills the
daemon — it enters the recovery ladder (``serve/recovery.py``):
deterministic bounded-backoff retry of the whole group, then group
bisection to corner a poison member (quarantined with a structured
error; the innocent majority completes), with a host-side rescue
before any quarantine. Repeated device-path failures open a circuit
breaker that routes groups to the host checkers (verdicts identical,
slower) until a half-open probe heals it; a dispatch hung past its
wall-clock cap aborts via the same ``should_abort`` composition and
its survivors requeue. Every rung lands in the obs ledger
(``serve-dispatch`` / ``serve-retry`` / ``serve-quarantine`` /
``serve-breaker`` / ``serve-hang``).
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from jepsen_tpu import obs
from jepsen_tpu.checkers import dispatch_core
from jepsen_tpu.serve import faults, recovery
from jepsen_tpu.serve import request as rq
from jepsen_tpu.serve.coalesce import AdmissionQueue

log = logging.getLogger("jepsen.serve")


class _StagedDispatch:
    """One serve group staged-but-uncollected on its lane: the engine
    launch is queued on device (``handle`` — a
    :class:`reach.StagedMany`), per-request dispatch bookkeeping is
    done, and the queue slot is still held (released by
    :meth:`Dispatcher._collect_one` after publish, so the drain
    contract is unchanged). ``cap_recs`` carries the obs ledger
    records the stage produced, merged with the collect's capture
    into every member's stitched trace."""

    __slots__ = ("batch", "lane_idx", "kw", "hang", "t0", "pad",
                 "n_real", "handle", "cap_recs")

    def __init__(self, batch, lane_idx, kw, hang, t0, pad, n_real,
                 handle, cap_recs):
        self.batch = batch
        self.lane_idx = lane_idx
        self.kw = kw
        self.hang = hang
        self.t0 = t0
        self.pad = pad
        self.n_real = n_real
        self.handle = handle
        self.cap_recs = cap_recs

    def ready(self) -> bool:
        return self.handle.ready()


def _profiler_start(path: str) -> None:
    """Module-level indirection so tests can stub the profiler."""
    import jax
    jax.profiler.start_trace(path)


def _profiler_stop() -> None:
    import jax
    jax.profiler.stop_trace()


class _TimeSeriesRing:
    """Rolling in-memory time series of serving health: one point per
    completed dispatch — req/s since the previous point, p50/p99 over
    the e2e-latency histogram delta, queue depth, and in-flight lanes.
    Bounded (default 256 points ~ the last few minutes under load);
    serialized into ``stats.json`` so the ``/engine`` dashboard can
    sparkline a daemon it does not share a process with."""

    def __init__(self, cap: int = 256) -> None:
        self._lock = threading.Lock()
        self._points: "deque[Dict[str, Any]]" = deque(maxlen=cap)
        self._prev_ts: Optional[float] = None
        self._prev_done: float = 0.0
        self._prev_hist: Optional[Dict[str, Any]] = None

    def sample(self, queue: AdmissionQueue,
               snap: Optional[Dict[str, Any]] = None) -> None:
        # `snap` shares one Recorder.snapshot() per dispatch between
        # the ring and the stats file — snapshot deep-copies the
        # (up-to-10k-record) ledger under the global obs lock, so
        # taking it once per loop iteration matters
        if snap is None:
            snap = obs.core.GLOBAL.snapshot()
        now = time.monotonic()
        done = snap["counters"].get("serve.completed", 0.0)
        hist = snap["histograms"].get("serve.e2e_s")
        depth = queue.depth()
        lanes = sum(queue.inflight().values())
        with self._lock:
            dt = (now - self._prev_ts) if self._prev_ts is not None \
                else None
            delta = obs.hist_delta(hist, self._prev_hist)
            p50 = obs.hist_quantile(delta, 0.50)
            p99 = obs.hist_quantile(delta, 0.99)
            point = {
                "ts": round(time.time(), 3),
                "req_s": (round((done - self._prev_done) / dt, 3)
                          if dt and dt > 0 else None),
                "p50_s": round(p50, 6) if p50 is not None else None,
                "p99_s": round(p99, 6) if p99 is not None else None,
                "depth": depth,
                "inflight": lanes,
            }
            self._points.append(point)
            self._prev_ts = now
            self._prev_done = done
            self._prev_hist = hist

    def points(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(p) for p in self._points]


class _Lane:
    """One dispatch lane's isolated state. ``breaker`` is this lane's
    own circuit breaker (cloned from the prototype's policy): device
    failures on lane k open lane k's breaker only, so a poisoned lane
    degrades to host-side serving while siblings keep the device
    path. ``device_ran`` is the per-dispatch attribution flag — only
    ever touched by this lane's own thread, no lock."""

    def __init__(self, idx: int,
                 breaker: recovery.CircuitBreaker) -> None:
        self.idx = idx
        self.breaker = breaker
        self.device_ran = False
        self.thread: Optional[threading.Thread] = None
        # pipelined dispatch state (only ever touched by this lane's
        # own thread, no lock): attr_mark is the attribution clock —
        # the end of this lane's last collected interval, so a group
        # whose stage→collect wall overlaps a predecessor books only
        # the un-attributed slice (device_s + pad_waste_s keeps
        # partitioning the lane's busy wall exactly; the overlapped
        # remainder is the pipeline win, counted pipeline.overlap_s)
        self.attr_mark = 0.0
        self.window_peak = 0


class Dispatcher:
    """Owns the dispatch thread. ``start()``/``stop()`` bracket the
    daemon's life; ``drain()`` waits for the queue to empty (tests,
    graceful shutdown)."""

    def __init__(self, queue: AdmissionQueue, registry: "rq.Registry",
                 *, engine_kw: Optional[Dict[str, Any]] = None,
                 store_root: Optional[str] = None,
                 persist: bool = False,
                 retry_policy: Optional[recovery.RetryPolicy] = None,
                 breaker: Optional[recovery.CircuitBreaker] = None,
                 dispatch_deadline_s: Optional[float] = None,
                 journal: Optional[Any] = None,
                 lanes: int = 1) -> None:
        self.queue = queue
        self.registry = registry
        self.engine_kw = dict(engine_kw or {})
        self.store_root = store_root
        self.persist = persist and store_root is not None
        # recovery discipline (serve/recovery.py): deterministic
        # bounded retry + bisect quarantine, the device-path circuit
        # breaker, and the hung-dispatch wall-clock cap past which the
        # group's should_abort fires and survivors requeue
        self.retry = retry_policy or recovery.RetryPolicy()
        # per-lane breaker isolation: the passed breaker (or a fresh
        # default) becomes lane 0's, and each further lane gets its
        # own clone of the same policy — `self.breaker` stays the
        # lane-0 alias for single-lane callers and existing tests
        proto = breaker or recovery.CircuitBreaker()
        self.lanes_n = max(1, int(lanes))
        self._lanes = [_Lane(0, proto)]
        for i in range(1, self.lanes_n):
            self._lanes.append(_Lane(i, recovery.CircuitBreaker(
                threshold=proto.threshold,
                cooldown_s=proto.cooldown_s)))
        self.breaker = proto
        self.dispatch_deadline_s = dispatch_deadline_s
        self.journal = journal          # durable WAL (set by Daemon)
        self.sessions = None            # SessionRegistry (set by Daemon)
        # pod mode: device seconds this rank spends are mirrored into
        # dist.device_s so per-host spend reconciles across the pod's
        # ranks (serve.device_s stays the daemon-local attribution)
        try:
            from jepsen_tpu.parallel import distributed
            self._n_ranks = distributed.process_info()[1]
        # jtlint: ok fallback — capability probe: no jax on the protocol-only path, single-process attribution
        except Exception:                               # noqa: BLE001
            self._n_ranks = 1
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.dispatch_counts: Dict[str, int] = {}
        self._counts_lock = threading.Lock()
        # max staged-window depth seen on any lane (pipeline evidence
        # for /stats and the CI pipeline-smoke gate)
        self._inflight_peak = 0
        self.ring = _TimeSeriesRing()
        # on-demand profiling (POST /profile): arm -> the next N
        # dispatches run under jax.profiler.trace, capture persisted
        # under the store root
        self._profile_lock = threading.Lock()
        self._profile_left = 0
        self._profile_dir: Optional[str] = None
        self._profile_active = False
        queue.on_timeout = self._finish_timeout_queued

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "Dispatcher":
        # warm the persistent caches once, before the first request
        from jepsen_tpu.checkers import reach
        reach._ensure_persistent_caches()
        obs.gauge("serve.lanes", self.lanes_n)
        for lane in self._lanes:
            t = threading.Thread(target=self._loop, args=(lane,),
                                 name=f"serve-dispatch-{lane.idx}",
                                 daemon=True)
            lane.thread = t
            t.start()
        # lane 0's thread doubles as the "is the dispatcher running"
        # handle (drain() and older callers check it)
        self._thread = self._lanes[0].thread
        return self

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        end = time.monotonic() + timeout
        for lane in self._lanes:
            t = lane.thread
            if t is not None:
                t.join(max(0.1, end - time.monotonic()))
        # flush a still-open profiler capture: an armed profile that
        # never saw enough dispatches must not leave the trace
        # recording (and its promised capture dir empty) forever
        self._profile_force_stop()

    def drain(self, timeout: float = 60.0) -> bool:
        """Wait until no request is queued or walking. Judged from
        the QUEUE's state alone: a batch moves queued → in-flight
        atomically under the queue lock inside ``next_batch`` and
        leaves in-flight only in ``mark_done`` (after its results
        published), so depth==0 ∧ inflight=={} has no window where a
        batch is about to dispatch — a dispatcher-side idle flag
        would."""
        if self._thread is None:        # never started: nothing will
            return self.queue.depth() == 0  # ever drain the queue
        end = time.monotonic() + timeout
        while time.monotonic() < end:
            if self.queue.depth() == 0 and not self.queue.inflight():
                return True
            time.sleep(0.01)
        return False

    # -- the loop --------------------------------------------------------
    def _loop(self, lane: "_Lane") -> None:
        """The lane thread: a bounded window of K staged groups in
        flight (ISSUE 20 tentpole). While group k's walk runs on
        device, this thread stages group k+1 (host pack + puts +
        kernel launch via :meth:`_stage`) and collects any READY
        predecessors; a full window blocks on the oldest group's
        collect (counted ``pipeline.stall_s`` — the host running
        ahead of the device). Batches the stage probe declines
        (sessions, txn, singletons, breaker-open, ineligible engine
        routes) drain the window first — publish order stays FIFO —
        then run the unchanged blocking path. K=1
        (``JEPSEN_TPU_NO_PIPELINE=1``) never stages: every iteration
        is the historical pull→dispatch→mark_done loop, bit-identical
        verdicts AND accounting."""
        window: deque = deque()
        while not self._stop.is_set():
            k = dispatch_core.pipeline_k(
                "serve", default=dispatch_core.SERVE_PIPE_K)
            batch = None
            if len(window) < k:
                # with staged work pending, poll fast: a short pull
                # timeout keeps ready predecessors draining even when
                # the queue is idle
                batch = self.queue.next_batch(
                    timeout=(0.01 if window else 0.1), lane=lane.idx)
            if batch:
                self._profile_maybe_start()
                staged = None
                if k > 1:
                    try:
                        staged = self._stage(batch, lane)
                    except Exception as e:              # noqa: BLE001
                        # jtlint: ok fallback — a stage-probe crash
                        # must not strand the batch: the blocking
                        # path below redoes it from scratch
                        log.warning("stage probe crashed: %r", e,
                                    exc_info=e)
                        obs.count("pipeline.stage_error")
                        staged = None
                if staged is not None:
                    obs.count("pipeline.staged")
                    window.append(staged)
                    if len(window) > lane.window_peak:
                        lane.window_peak = len(window)
                        obs.gauge(
                            f"pipeline.lane.{lane.idx}.inflight_peak",
                            lane.window_peak)
                        self._note_inflight_peak(lane.window_peak)
                    # collect ready predecessors without blocking the
                    # next stage
                    while window and window[0].ready():
                        self._collect_one(window.popleft(), lane)
                    continue
                # not stageable: preserve FIFO publish order — drain
                # the window, then run the blocking path
                while window:
                    self._collect_one(window.popleft(), lane)
                self._run_blocking(batch, lane)
                continue
            if window:
                stalled = len(window) >= k
                t_w = time.monotonic()
                self._collect_one(window.popleft(), lane)
                if stalled:
                    obs.count("pipeline.stall_s",
                              time.monotonic() - t_w)
        # shutdown: collect everything still in flight so no request
        # is stranded un-published
        while window:
            self._collect_one(window.popleft(), lane)

    def _note_inflight_peak(self, peak: int) -> None:
        with self._counts_lock:
            if peak > self._inflight_peak:
                self._inflight_peak = peak
                obs.gauge("pipeline.inflight_peak", peak)

    def _run_blocking(self, batch: List["rq.CheckRequest"],
                      lane: "_Lane") -> None:
        """One blocking dispatch iteration — the pre-pipeline loop
        body, unchanged: dispatch, last-resort containment, queue
        release + stats sampling."""
        try:
            self._dispatch(batch, lane)
        except Exception as e:                          # noqa: BLE001
            # LAST-resort containment: the recovery ladder inside
            # _dispatch handles engine failures; anything escaping
            # it (bookkeeping bugs, injected tick faults) must not
            # kill the lane thread or strand the batch
            log.error("dispatch iteration crashed: %r", e,
                      exc_info=e)
            obs.engine_fallback("serve-dispatch",
                                type(e).__name__,
                                lanes=len(batch), iteration=True)
            now = time.monotonic()
            for r in batch:
                if not r.terminal:
                    self._finish(r, {"valid": "unknown",
                                     "error": f"{type(e).__name__}"
                                              f": {e}"},
                                 0.0, now)
        finally:
            self.queue.mark_done(batch, lane=lane.idx)
            obs.gauge("serve.inflight", 0)
            self._profile_maybe_stop()
            snap = obs.core.GLOBAL.snapshot()
            self.ring.sample(self.queue, snap)
            self._write_stats_file(snap)

    def _collect_one(self, staged: "_StagedDispatch",
                     lane: "_Lane") -> None:
        """Collect + publish one staged group, releasing its queue
        slot only AFTER its results land (the drain contract:
        depth==0 ∧ inflight=={} still means every verdict is
        published)."""
        batch = staged.batch
        try:
            self._collect_staged(staged, lane)
        except Exception as e:                          # noqa: BLE001
            # the same last-resort containment as the blocking loop
            log.error("staged collect crashed: %r", e, exc_info=e)
            obs.engine_fallback("serve-dispatch", type(e).__name__,
                                lanes=len(batch), iteration=True)
            now = time.monotonic()
            for r in batch:
                if not r.terminal:
                    self._finish(r, {"valid": "unknown",
                                     "error": f"{type(e).__name__}"
                                              f": {e}"},
                                 0.0, now)
        finally:
            self.queue.mark_done(batch, lane=lane.idx)
            obs.gauge("serve.inflight", 0)
            self._profile_maybe_stop()
            snap = obs.core.GLOBAL.snapshot()
            self.ring.sample(self.queue, snap)
            self._write_stats_file(snap)

    # -- on-demand profiling ---------------------------------------------
    def arm_profile(self, dispatches: int) -> str:
        """Arm ``jax.profiler.trace`` around the next N dispatches;
        the capture persists under ``<store-root>/serve/profile-<ts>/``.
        Raises RuntimeError when already armed or without a store
        root (the capture needs somewhere durable to land)."""
        if self.store_root is None:
            raise RuntimeError("profiling needs a store root")
        from jepsen_tpu import store
        with self._profile_lock:
            if self._profile_left > 0 or self._profile_active:
                raise RuntimeError(
                    f"profile already armed "
                    f"({self._profile_left} dispatches left)")
            d = store.serve_profile_dir(self.store_root)
            self._profile_dir = d
            self._profile_left = int(dispatches)
            return d

    def profile_state(self) -> Dict[str, Any]:
        with self._profile_lock:
            return {"armed": int(self._profile_left),
                    "active": bool(self._profile_active),
                    "dir": self._profile_dir}

    def _profile_maybe_start(self) -> None:
        with self._profile_lock:
            if self._profile_left <= 0 or self._profile_active:
                return
            path = self._profile_dir
            try:
                _profiler_start(path)
                self._profile_active = True
                obs.decision("serve-profile", "route",
                             cause="start", dir=path,
                             dispatches=self._profile_left)
            except Exception as e:                      # noqa: BLE001
                log.warning("profiler start failed: %s", e)
                obs.engine_fallback("serve-profile",
                                    type(e).__name__)
                self._profile_left = 0

    def _profile_maybe_stop(self) -> None:
        with self._profile_lock:
            if not self._profile_active:
                return
            self._profile_left -= 1
            if self._profile_left > 0:
                return
            self._profile_stop_locked()

    def _profile_force_stop(self) -> None:
        """Stop and flush an active capture regardless of how many
        armed dispatches remain (daemon shutdown)."""
        with self._profile_lock:
            if self._profile_active:
                self._profile_stop_locked()
            self._profile_left = 0

    def _profile_stop_locked(self) -> None:
        try:
            _profiler_stop()
            obs.count("serve.profile.captures")
        except Exception as e:                          # noqa: BLE001
            log.warning("profiler stop failed: %s", e)
            obs.engine_fallback("serve-profile", type(e).__name__)
        self._profile_active = False
        self._profile_left = 0

    # -- engine attempts (the recovery ladder's rungs) -------------------
    @staticmethod
    def _is_txn(model) -> bool:
        from jepsen_tpu.txn.ops import ListAppend
        return isinstance(model, ListAppend)

    def _padded_list(self, batch: List["rq.CheckRequest"]):
        """Quantize the lane count to a power of two by replicating
        the LONGEST member (its verdict is recomputed and discarded;
        padding with the longest keeps the group's padded step count
        unchanged): a serving daemon sees every group width 1..group
        over its life, and each distinct H is a distinct compiled
        kernel geometry — the pad bounds that churn to log2(group)
        geometries a warmup can prime. JEPSEN_TPU_SERVE_NO_PAD=1
        dispatches raw widths. Transactional groups never pad (the
        txn closure kernel pads its own geometry internally)."""
        packed_list = [r.packed for r in batch]
        pad = self._pad_count(len(batch), self._is_txn(batch[0].model))
        if pad > 0:
            longest = max(packed_list, key=lambda p: p.n)
            packed_list = packed_list + [longest] * pad
        return packed_list, pad

    def _pad_count(self, n_real: int, is_txn: bool) -> int:
        if n_real <= 1 or is_txn \
                or os.environ.get("JEPSEN_TPU_SERVE_NO_PAD"):
            return 0
        Hq = 1 << (n_real - 1).bit_length()
        # never pad past the configured group width: the engine-side
        # re-plan splits oversized groups, which would both defeat the
        # pad and break the admission/engine plan agreement
        cap = int(self.engine_kw.get("group") or 0) or 32
        Hq = min(Hq, max(cap, n_real))
        return max(0, Hq - n_real)

    def _run_engine(self, batch: List["rq.CheckRequest"],
                    kw: Dict[str, Any], lane: "_Lane",
                    feed_breaker: bool = True) -> List[Dict[str, Any]]:
        """ONE engine attempt for the (sub)group: consult the LANE's
        circuit breaker for the route, run it, feed the outcome back.
        Raises on failure — recovery policy lives in
        :meth:`_run_recover`.

        ``feed_breaker=False`` (the bisect hunt's sub-attempts) still
        records SUCCESSES (they are honest evidence of device health)
        but not failures: one poison request failing its way down a
        bisect ladder is log2(n) failures from a single bad REQUEST,
        and must not open a breaker that speaks for the DEVICE."""
        from jepsen_tpu.checkers import facade
        tenants = [r.tenant for r in batch]
        # the self-nemesis "dispatch" point models a poison request
        # that crashes the checker on EVERY route; "device" models a
        # device-path outage (the breaker's food)
        faults.fire("dispatch", tenants=tenants)
        if lane.breaker.route() == "host":
            obs.count("serve.breaker.degraded_dispatches")
            obs.decision("serve-breaker", "route", cause="host",
                         lanes=len(batch), lane=lane.idx)
            return self._run_host(batch, kw, fire_point=False)
        req0 = batch[0]
        try:
            faults.fire("device", tenants=tenants)
            # attribution flag: some device work ran this dispatch
            # iteration (even a failed attempt spent device time)
            lane.device_ran = True
            with obs.span("serve.dispatch",
                          model=req0.model_name, lanes=len(batch)):
                if self._is_txn(req0.model):
                    # one txn chain per member: host dependency
                    # inference is per-history; the closure kernel
                    # geometry is shared across members via its
                    # power-of-two pad + jit cache
                    results = [facade.auto_check_txn(
                        list(r.history), kw) for r in batch]
                elif len(batch) == 1:
                    results = [facade.auto_check_packed(
                        req0.model, req0.packed, kw)]
                else:
                    packed_list, pad = self._padded_list(batch)
                    if pad:
                        obs.count("serve.pad_lanes", pad)
                    results = facade.auto_check_many_packed(
                        req0.model, packed_list, kw)[:len(batch)]
        except Exception:
            if feed_breaker:
                lane.breaker.record_failure()
            raise
        lane.breaker.record_success()
        return results

    def _run_host(self, batch: List["rq.CheckRequest"],
                  kw: Dict[str, Any],
                  fire_point: bool = True) -> List[Dict[str, Any]]:
        """The degraded route: host-side checkers, per member —
        verdicts identical to the device chain (the Python WGL oracle
        / forced-host txn closure are the same reference the engines
        are differentially tested against), just slower. Used while
        the breaker is open and as the singleton quarantine rescue."""
        from jepsen_tpu.checkers import facade, wgl_ref
        if fire_point:
            faults.fire("dispatch",
                        tenants=[r.tenant for r in batch])
        req0 = batch[0]
        out = []
        with obs.span("serve.dispatch-host",
                      model=req0.model_name, lanes=len(batch)):
            for r in batch:
                if self._is_txn(r.model):
                    res = facade.auto_check_txn(
                        list(r.history), dict(kw, force_host=True))
                else:
                    res = wgl_ref.check_packed(
                        r.model, r.packed,
                        **facade._engine_kw(kw, facade._WGL_KW))
                    res["engine"] = res.get("engine", "wgl-cpu")
                res["degraded"] = True
                out.append(res)
        return out

    def _run_recover(self, batch: List["rq.CheckRequest"],
                     kw: Dict[str, Any],
                     retries_left: int, lane: "_Lane",
                     top_level: bool = True) -> List[Dict[str, Any]]:
        """The recovery ladder: attempt → deterministic bounded-backoff
        retry → group bisect to corner the poison member → host-side
        rescue → quarantine. Innocent members of a poisoned group
        complete; only the member that fails ALONE (on both routes) is
        quarantined, with a structured error and an obs record."""
        attempt = 0
        err: Optional[Exception] = None
        while True:
            try:
                return self._run_engine(batch, kw, lane,
                                        feed_breaker=top_level)
            except Exception as e:                      # noqa: BLE001
                err = e
                log.warning("serve dispatch failed (lanes=%d, "
                            "attempt=%d): %r", len(batch), attempt, e,
                            exc_info=e)
                obs.engine_fallback("serve-dispatch",
                                    type(e).__name__,
                                    lanes=len(batch), attempt=attempt)
            if self._stop.is_set():
                return [{"valid": "unknown",
                         "error": f"{type(err).__name__}: {err}"}
                        for _ in batch]
            if retries_left <= 0:
                break
            retries_left -= 1
            obs.count("serve.retry.attempts")
            time.sleep(self.retry.delay(attempt))
            attempt += 1
        if len(batch) > 1:
            # isolate the poison: halves get one attempt each and
            # bisect further on failure — O(log n) extra dispatches
            # to corner one bad member while the rest complete
            obs.count("serve.retry.bisects")
            obs.decision("serve-retry", "bisect", lanes=len(batch),
                         cause=type(err).__name__)
            lo, hi = recovery.bisect(batch)
            return self._run_recover(lo, kw, 0, lane,
                                     top_level=False) \
                + self._run_recover(hi, kw, 0, lane,
                                    top_level=False)
        # a singleton that failed its attempts: one last host-side
        # rescue (device flakiness must not quarantine an innocent
        # request), then quarantine with a structured error
        req = batch[0]
        try:
            obs.decision("serve-retry", "host-rescue", id=req.id)
            return self._run_host(batch, kw)
        except Exception as e:                          # noqa: BLE001
            obs.count("serve.quarantined")
            obs.engine_fallback("serve-quarantine", type(e).__name__,
                                id=req.id, tenant=req.tenant,
                                ops=int(req.n_ops))
            log.warning("quarantining request %s: %r", req.id, e)
            return [{"valid": "unknown", "quarantined": True,
                     "cause": "quarantined",
                     "error": f"{type(e).__name__}: {e}"}]

    def _session_abort(self, t0: float):
        """The session advance's ``should_abort`` hook: the dispatch
        deadline applied to a streaming block. Composed into the
        session's engine steps (``session._advance_engine`` polls it
        between feed/advance/probe), so a hung advance aborts and the
        session takes its ordinary permanent host fallback instead of
        wedging the lane forever. Returns None when no deadline is
        configured (the hook costs a closure per block otherwise)."""
        deadline_s = self.dispatch_deadline_s
        if deadline_s is None:
            return None
        fired = [False]

        def _aborted() -> bool:
            if self._stop.is_set():
                return True
            if time.monotonic() - t0 > deadline_s:
                if not fired[0]:
                    fired[0] = True
                    obs.engine_fallback("serve-hang",
                                        "DispatchDeadline",
                                        session=True,
                                        deadline_s=deadline_s)
                return True
            return False
        return _aborted

    def _dispatch_session(self, batch: List["rq.CheckRequest"],
                          lane: "_Lane") -> None:
        """Session blocks: advance the carried frontier through each
        append (seq order — the coalescer sorted the group), resolve
        the close. No recovery ladder, no breaker, no lane pad: the
        session owns its own fallback contract (exactly one
        ``session-advance`` obs fallback → host monitor), so a block
        that fails here still produces its verdict — host-side. No
        device-time attribution either: the advance wall gets its own
        counter so serve.device_s stays the one-shot walks' number."""
        from jepsen_tpu.serve.session import SessionClosed
        req0 = batch[0]
        sess = req0.session
        sig = f"session/{req0.model_name}/A{len(batch)}"
        with self._counts_lock:
            self.dispatch_counts[sig] = \
                self.dispatch_counts.get(sig, 0) + 1
        obs.count("serve.dispatched", len(batch))
        obs.count(f"serve.lane.{lane.idx}.dispatched")
        obs.gauge("serve.inflight", len(batch))
        t0 = time.monotonic()
        for r in batch:
            r.t_dispatch = time.monotonic()
            obs.histogram("serve.queue_wait_s",
                          max(0.0, (r.t_coalesce or t0) - r.t_submit))
            self.registry.ledger_record(
                r.tenant, "dispatched", id=r.id, group=len(batch),
                ops=int(r.n_ops), session=sess.id, kind=r.kind)
            with obs.capture() as cap:
                try:
                    if r.kind == "session-close":
                        res = sess.close()
                        if self.sessions is not None:
                            self.sessions.mark_closed(sess)
                        if self.journal is not None:
                            self.journal.session_close_marker(
                                sess.id, res)
                    else:
                        res = sess.advance_block(
                            list(r.history), seq=r.seq,
                            should_abort=self._session_abort(
                                r.t_dispatch))
                # jtlint: ok fallback — append/close client race: the member gets a 'closed' verdict
                except SessionClosed as e:
                    res = {"valid": "unknown", "cause": "closed",
                           "error": str(e)}
                except Exception as e:                  # noqa: BLE001
                    # the session's own ladder should have contained
                    # this; a residual crash is recorded, never fatal
                    log.warning("session block %s crashed: %r",
                                r.id, e, exc_info=e)
                    obs.engine_fallback("serve-dispatch",
                                        type(e).__name__,
                                        session=sess.id, id=r.id)
                    if r.kind == "session-close" and not sess.closed:
                        # a close that crashed must not wedge the
                        # session: clearing the in-flight flag lets
                        # the client retry (appends stay refused only
                        # while a close is genuinely pending)
                        sess.closing = False
                    res = {"valid": "unknown",
                           "error": f"{type(e).__name__}: {e}"}
            now = time.monotonic()
            r.t_collect = now
            r.stitch([{"ts": round(time.time(), 6),
                       "stage": "session-advance", "event": "advance",
                       "session": sess.id, "seq": r.seq,
                       "wall_s": round(now - r.t_dispatch, 6)}]
                     + [rec for rec in cap.ledger
                        if rec.get("event") in ("fallback", "route",
                                                "selected")])
            for rec in cap.ledger:
                if rec.get("event") == "fallback":
                    self.registry.ledger_record(
                        r.tenant, "engine-fallback", id=r.id,
                        stage=rec.get("stage"), cause=rec.get("cause"))
            obs.histogram("serve.session.append_s", now - r.t_submit)
            self._finish(r, res, now - r.t_dispatch, now)
        obs.count("serve.session.advance_wall_s",
                  time.monotonic() - t0)

    def _dispatch_session_group(self, batch: List["rq.CheckRequest"],
                                lane: "_Lane") -> None:
        """Mega-batch session group: append blocks of MANY sessions
        sharing one walk geometry, advanced in waves — wave ``w`` is
        every member session's ``w``-th queued block, and one wave is
        ONE batched kernel launch (``session.advance_group``). Member
        isolation is the group-advance contract: one member's device
        death falls that session to its host monitor while the rest
        of the wave completes on device. Like the solo session path:
        no recovery ladder, no breaker, no device-time attribution."""
        from jepsen_tpu.serve import session as sessmod
        by_sess: Dict[str, List["rq.CheckRequest"]] = {}
        for r in batch:
            by_sess.setdefault(r.session.id, []).append(r)
        sig = f"session-mega/L{len(by_sess)}/A{len(batch)}"
        with self._counts_lock:
            self.dispatch_counts[sig] = \
                self.dispatch_counts.get(sig, 0) + 1
        obs.count("serve.dispatched", len(batch))
        obs.count(f"serve.lane.{lane.idx}.dispatched")
        obs.gauge("serve.inflight", len(batch))
        t0 = time.monotonic()
        waves = max(len(rs) for rs in by_sess.values())
        wave_list = [[rs[w] for rs in by_sess.values() if w < len(rs)]
                     for w in range(waves)]
        stamped: set = set()

        def _stamp(w: int) -> None:
            # per-wave admission bookkeeping (t_dispatch, queue-wait,
            # tenant ledger).  Idempotent so the pipelined path can run
            # it for wave w+1 while wave w walks on device, and the
            # serial fallthrough below still covers every wave.
            if w >= waves or w in stamped:
                return
            stamped.add(w)
            ts = time.monotonic()
            for r in wave_list[w]:
                r.t_dispatch = ts
                obs.histogram(
                    "serve.queue_wait_s",
                    max(0.0, (r.t_coalesce or ts) - r.t_submit))
                self.registry.ledger_record(
                    r.tenant, "dispatched", id=r.id,
                    group=len(batch), ops=int(r.n_ops),
                    session=r.session.id, kind=r.kind,
                    mega=len(wave_list[w]))

        overlap = (dispatch_core.pipeline_k(
            "session-mega", default=dispatch_core.SERVE_PIPE_K) > 1)
        for w, wave in enumerate(wave_list):
            tw = time.monotonic()
            _stamp(w)
            with obs.capture() as cap:
                try:
                    results = sessmod.advance_group(
                        [(r.session, list(r.history), r.seq)
                         for r in wave],
                        should_abort=self._session_abort(tw),
                        overlap_fn=((lambda nw=w + 1: _stamp(nw))
                                    if overlap else None))
                except Exception as e:                  # noqa: BLE001
                    # the group advance's own ladders should have
                    # contained this; a residual crash is recorded,
                    # never fatal, and every member gets a verdict
                    log.warning("mega session wave crashed: %r", e,
                                exc_info=e)
                    obs.engine_fallback("serve-dispatch",
                                        type(e).__name__,
                                        mega=len(wave))
                    results = [{"valid": "unknown",
                                "error": f"{type(e).__name__}: {e}"}
                               for _ in wave]
            now = time.monotonic()
            recs = [rec for rec in cap.ledger
                    if rec.get("event") in ("fallback", "route",
                                            "selected")]
            for r, res in zip(wave, results):
                r.t_collect = now
                # group-level records (no session tag — e.g. the ONE
                # session-mega launch fallback) stitch to every
                # member; session-tagged ones only to their owner
                mine = [rec for rec in recs
                        if rec.get("session") in (None, True,
                                                  r.session.id)]
                r.stitch([{"ts": round(time.time(), 6),
                           "stage": "session-advance",
                           "event": "advance", "session": r.session.id,
                           "seq": r.seq, "mega": len(wave),
                           "wall_s": round(now - tw, 6)}] + mine)
                for rec in mine:
                    if rec.get("event") == "fallback":
                        self.registry.ledger_record(
                            r.tenant, "engine-fallback", id=r.id,
                            stage=rec.get("stage"),
                            cause=rec.get("cause"))
                obs.histogram("serve.session.append_s",
                              now - r.t_submit)
                self._finish(r, res, now - r.t_dispatch, now)
        obs.count("serve.session.advance_wall_s",
                  time.monotonic() - t0)

    def _dispatch(self, batch: List["rq.CheckRequest"],
                  lane: Optional["_Lane"] = None) -> None:
        # single-lane callers (tests drive _dispatch directly) default
        # to lane 0 — the pre-lanes behavior
        if lane is None:
            lane = self._lanes[0]
        # the self-nemesis trigger clock (scheduled clock jumps fire
        # here); never raises for the shipped fault grammar
        faults.fire("tick")
        if batch[0].session is not None:
            if len({r.session.id for r in batch}) > 1:
                # multi-session group: the coalescer only builds one
                # when every block is an append sharing a mega-batch
                # walk-geometry signature
                self._dispatch_session_group(batch, lane)
            else:
                self._dispatch_session(batch, lane)
            return
        req0 = batch[0]
        sig = f"{req0.model_name}/H{len(batch)}"
        with self._counts_lock:
            self.dispatch_counts[sig] = \
                self.dispatch_counts.get(sig, 0) + 1
        obs.count("serve.dispatched", len(batch))
        obs.count(f"serve.lane.{lane.idx}.dispatched")
        obs.gauge("serve.inflight", len(batch))
        t0 = time.monotonic()
        for r in batch:
            # dispatch stamp + queue-wait histogram: admit -> selected
            # into this group (t_coalesce, stamped by next_batch)
            r.t_dispatch = t0
            obs.histogram("serve.queue_wait_s",
                          max(0.0, (r.t_coalesce or t0) - r.t_submit))
            self.registry.ledger_record(
                r.tenant, "dispatched", id=r.id, group=len(batch),
                ops=int(r.packed.n))

        hang = [False]

        def _aborted() -> bool:
            # clean group cancellation: fires only when NO member
            # still wants the verdict (composed into the segmented
            # walk's abort polling by the facade chain) — or when the
            # dispatch itself hangs past its wall-clock cap, in which
            # case survivors are requeued rather than finished
            if self._stop.is_set():
                return True
            if self.dispatch_deadline_s is not None \
                    and time.monotonic() - t0 > self.dispatch_deadline_s:
                if not hang[0]:
                    hang[0] = True
                    obs.engine_fallback("serve-hang",
                                        "DispatchDeadline",
                                        lanes=len(batch),
                                        deadline_s=self
                                        .dispatch_deadline_s)
                return True
            now = time.monotonic()
            return all(r.cancel_requested or r.expired(now)
                       for r in batch)

        # per-request engine options apply to the whole dispatch: the
        # coalescer only groups requests whose options are IDENTICAL
        # (they are part of the compatibility signature), so batch[0]
        # speaks for every member
        kw = dict(self.engine_kw)
        kw.update(req0.opts)
        kw["should_abort"] = _aborted
        n_real = len(batch)
        # pad for ATTRIBUTION (the engine attempts compute their own
        # replication pad per subgroup; this is the full-group value,
        # so device_s + pad_waste_s == dispatch_wall_s by construction)
        pad = self._pad_count(n_real, self._is_txn(req0.model))
        # the dispatcher thread's own obs records (fallbacks, engine
        # selections from the facade chain, retry/bisect/quarantine
        # records from the recovery ladder) are captured here and
        # re-emitted into every member request's stitched trace below
        # — ledgers are thread-isolated, so without this a client-side
        # obs.capture() around submit/poll would never see them
        lane.device_ran = False
        with obs.capture() as cap:
            try:
                results = self._run_recover(batch, kw,
                                            self.retry.max_retries,
                                            lane)
            except Exception as e:                      # noqa: BLE001
                # the ladder itself must be crash-contained too
                log.warning("serve recovery ladder crashed: %r", e,
                            exc_info=e)
                obs.engine_fallback("serve-dispatch",
                                    type(e).__name__,
                                    lanes=len(batch))
                err = {"valid": "unknown",
                       "error": f"{type(e).__name__}: {e}"}
                results = [dict(err) for _ in batch]
        self._publish(batch, results, lane, t0, pad, n_real,
                      cap.ledger, hang, lane.device_ran)

    def _publish(self, batch: List["rq.CheckRequest"], results,
                 lane: "_Lane", t0: float, pad: int, n_real: int,
                 cap_ledger, hang, device_ran: bool) -> None:
        """Results → attribution → stitched traces → finish/requeue:
        the publish tail shared by the blocking dispatch and the
        pipelined collect."""
        if len(results) != len(batch):
            # alignment is the publish contract: a short list would
            # silently strand the tail members un-finished forever
            obs.engine_fallback("serve-dispatch", "ResultMisaligned",
                                lanes=len(batch), got=len(results))
            results = (list(results)
                       + [{"valid": "unknown",
                           "error": "result misaligned"}]
                      * len(batch))[:len(batch)]
        t_collect = time.monotonic()
        elapsed = t_collect - t0
        # the lane attribution clock: with K groups staged on this
        # lane their stage→collect walls overlap, so each group books
        # only the slice of lane wall since the previous collect
        # (collects are FIFO on the lane's own thread, so these
        # intervals partition the lane's busy wall and the device_s +
        # pad_waste_s == dispatch-wall identity stays EXACT under
        # interleaving; serial dispatches have attr_mark <= t0 and
        # book their full elapsed — bit-identical to the pre-pipeline
        # accounting). The overlapped remainder is the pipeline's win,
        # counted pipeline.overlap_s.
        attributed = max(0.0, t_collect - max(t0, lane.attr_mark))
        lane.attr_mark = t_collect
        overlap = elapsed - attributed
        if overlap > 1e-9:
            obs.count("pipeline.overlap_s", overlap)
            obs.count(f"pipeline.lane.{lane.idx}.overlap_s", overlap)

        # device-time attribution: the group's measured kernel wall is
        # amortized over its lanes — each member (one real lane) gets
        # wall/lanes, the replicated pad lanes' share is padding waste
        # (a first-class counter). share*n_real + waste == wall, so
        # attributed device-seconds reconcile with dispatch wall by
        # construction (asserted within 2% in tests). The per-lane
        # copies make the same identity hold for each dispatch lane
        # alone: sum_k lane.k.device_s + lane.k.pad_waste_s covers
        # every device second the daemon spent, attributed to the
        # lane that spent it.
        lanes = n_real + pad
        if device_ran:
            share = attributed / lanes
            waste = share * pad
            obs.histogram("serve.dispatch_wall_s", attributed)
            obs.count("serve.device_s", share * n_real)
            if self._n_ranks > 1:
                obs.count("dist.device_s", share * n_real)
            obs.count("serve.pad_waste_s", waste)
            obs.count(f"serve.lane.{lane.idx}.device_s",
                      share * n_real)
            obs.count(f"serve.lane.{lane.idx}.pad_waste_s", waste)
        else:
            # breaker-open dispatch served entirely host-side: no
            # kernel wall, no pad lanes — booking it as device time
            # would corrupt the attribution operators read during a
            # degraded period (its wall gets its own counter)
            share = waste = 0.0
            obs.count("serve.breaker.host_wall_s", elapsed)

        # stitched per-request trace: the group-level dispatch record
        # plus every ledger record the dispatch produced, re-emitted
        # per member with the request id. Fallbacks/swallows also land
        # in the member's TENANT serve ledger, so "no silent fallback"
        # stays assertable from the client side (GET /check/<id> and
        # GET /stats), not just from inside the daemon process.
        engine_recs = [r for r in cap_ledger
                       if r.get("event") in ("selected", "fallback",
                                             "swallowed", "route",
                                             "skipped")]
        disp_rec = {"ts": round(time.time(), 6),
                    "stage": "serve-dispatch", "event": "dispatch",
                    "group": lanes, "real": n_real, "pad": pad,
                    "wall_s": round(elapsed, 6),
                    "device_s": round(share, 9),
                    "pad_waste_s": round(waste, 9)}
        now = time.monotonic()
        for req, res in zip(batch, results):
            req.t_collect = t_collect
            req.device_s = share
            req.stitch([disp_rec] + engine_recs)
            self.registry.add_device_time(req.tenant, share)
            for r in engine_recs:
                if r.get("event") in ("fallback", "swallowed"):
                    self.registry.ledger_record(
                        req.tenant, f"engine-{r['event']}",
                        id=req.id, stage=r.get("stage"),
                        cause=r.get("cause"))
            if (hang[0] and res.get("valid") not in (True, False)
                    and not res.get("quarantined")
                    and not req.cancel_requested
                    and not req.expired(now)
                    and req.requeues < self.retry.max_requeues):
                # a hung dispatch was aborted past its wall-clock cap:
                # this survivor still wants its verdict — requeue it
                # (bounded by the shared retry policy) instead of
                # publishing the abort's "unknown"
                self._requeue(req)
            else:
                self._finish(req, res, elapsed, now)

    # -- the pipelined stage/collect pair --------------------------------
    def _stage(self, batch: List["rq.CheckRequest"],
               lane: "_Lane") -> Optional["_StagedDispatch"]:
        """STAGE one one-shot group: probe the staged engine route
        (:func:`facade.stage_check_many_packed` — host pack + device
        puts + kernel launches, nothing fetched) and, if it admits,
        commit the per-request dispatch bookkeeping and return the
        in-flight handle. Returns None — with NO request-visible side
        effects — when the group is not stageable (sessions, txn,
        singletons, breaker-open lane, per-request opts that force
        another route, engine gates closed), so the caller's blocking
        path runs exactly as before the pipeline existed."""
        from jepsen_tpu.checkers import facade
        req0 = batch[0]
        if req0.session is not None or len(batch) < 2:
            return None
        if self._is_txn(req0.model):
            return None
        if lane.breaker.route() == "host":
            return None
        if faults.enabled():
            # armed fault injection exercises the blocking path's fire
            # points (tick/dispatch/device) — staging would skip them
            return None
        kw = dict(self.engine_kw)
        kw.update(req0.opts)
        if kw.get("force_host"):
            return None
        t0 = time.monotonic()
        hang = [False]

        def _aborted() -> bool:
            if self._stop.is_set():
                return True
            if self.dispatch_deadline_s is not None \
                    and time.monotonic() - t0 > self.dispatch_deadline_s:
                if not hang[0]:
                    hang[0] = True
                    obs.engine_fallback("serve-hang",
                                        "DispatchDeadline",
                                        lanes=len(batch),
                                        deadline_s=self
                                        .dispatch_deadline_s)
                return True
            now = time.monotonic()
            return all(r.cancel_requested or r.expired(now)
                       for r in batch)

        kw["should_abort"] = _aborted
        n_real = len(batch)
        pad = self._pad_count(n_real, False)
        packed_list, _pad = self._padded_list(batch)
        with obs.capture() as cap:
            with obs.span("pipeline.stage", model=req0.model_name,
                          lanes=len(batch)):
                handle = facade.stage_check_many_packed(
                    req0.model, packed_list, kw)
        if handle is None:
            return None
        # committed: the group IS dispatched — same bookkeeping as the
        # blocking path's pre-engine half
        faults.fire("tick")
        sig = f"{req0.model_name}/H{len(batch)}"
        with self._counts_lock:
            self.dispatch_counts[sig] = \
                self.dispatch_counts.get(sig, 0) + 1
        obs.count("serve.dispatched", len(batch))
        obs.count(f"serve.lane.{lane.idx}.dispatched")
        obs.gauge("serve.inflight", len(batch))
        if pad:
            obs.count("serve.pad_lanes", pad)
        for r in batch:
            r.t_dispatch = t0
            obs.histogram("serve.queue_wait_s",
                          max(0.0, (r.t_coalesce or t0) - r.t_submit))
            self.registry.ledger_record(
                r.tenant, "dispatched", id=r.id, group=len(batch),
                ops=int(r.packed.n))
        return _StagedDispatch(batch, lane.idx, kw, hang, t0, pad,
                               n_real, handle, list(cap.ledger))

    def _collect_staged(self, staged: "_StagedDispatch",
                        lane: "_Lane") -> None:
        """COLLECT one staged group: fetch its verdict words, publish
        through the shared tail. A collect-side failure (jax surfaces
        walk errors at first fetch) feeds the lane breaker and drops
        into the UNCHANGED recovery ladder — retry → bisect → host
        rescue → quarantine — on the retained requests, so a staged
        group that dies gets exactly the pre-pipeline treatment."""
        batch = staged.batch
        req0 = batch[0]
        lane.device_ran = True      # the stage launched device work
        with obs.capture() as cap:
            try:
                with obs.span("serve.dispatch",
                              model=req0.model_name,
                              lanes=len(batch)):
                    results = staged.handle.collect()[:len(batch)]
                lane.breaker.record_success()
            except Exception as e:                      # noqa: BLE001
                # jtlint: ok fallback — collect death enters the
                # ordinary recovery ladder below; the fallback record
                # mirrors the blocking path's per-attempt record
                log.warning("staged collect failed (lanes=%d): %r",
                            len(batch), e, exc_info=e)
                obs.engine_fallback("serve-dispatch",
                                    type(e).__name__,
                                    lanes=len(batch), staged=True)
                lane.breaker.record_failure()
                try:
                    results = self._run_recover(
                        batch, staged.kw, self.retry.max_retries, lane)
                except Exception as e2:                 # noqa: BLE001
                    # the ladder itself must be crash-contained too
                    log.warning("serve recovery ladder crashed: %r",
                                e2, exc_info=e2)
                    obs.engine_fallback("serve-dispatch",
                                        type(e2).__name__,
                                        lanes=len(batch))
                    err = {"valid": "unknown",
                           "error": f"{type(e2).__name__}: {e2}"}
                    results = [dict(err) for _ in batch]
        self._publish(batch, results, lane, staged.t0, staged.pad,
                      staged.n_real, staged.cap_recs + cap.ledger,
                      staged.hang, True)

    # -- completion ------------------------------------------------------
    def _requeue(self, req: "rq.CheckRequest") -> None:
        req.requeues += 1
        req.status = rq.QUEUED
        req.t_coalesce = req.t_dispatch = req.t_collect = None
        obs.count("serve.retry.requeued")
        obs.decision("serve-retry", "requeued", id=req.id,
                     requeues=req.requeues)
        self.registry.ledger_record(req.tenant, "requeued", id=req.id,
                                    requeues=req.requeues)
        self.queue.submit(req, force=True)

    def _finish(self, req: "rq.CheckRequest", res: Dict[str, Any],
                elapsed: float, now: float) -> None:
        if req.cancel_requested:
            status = rq.CANCELLED
            obs.count("serve.cancelled")
        elif res.get("quarantined"):
            # the bisect ladder cornered this member as the poison:
            # structured terminal state, never a silent "unknown"
            # (counters/ledger records bumped where the quarantine
            # decision was made, in _run_recover)
            status = rq.QUARANTINED
        elif req.expired(now) and res.get("valid") not in (True, False):
            # the walk was aborted (or still unknown) past the
            # deadline: a timeout, not a verdict
            status = rq.TIMEOUT
            res = {"valid": "unknown", "cause": "deadline",
                   **{k: v for k, v in res.items() if k != "valid"}}
            obs.count("serve.timeout")
            obs.engine_fallback("serve-timeout", "DeadlineExpired",
                                tenant=req.tenant, ops=req.n_ops,
                                dispatched=True)
        else:
            # a conclusive verdict that merely finished late is still
            # the verdict — deadline enforcement is about not burning
            # device time, not about discarding finished work
            status = rq.DONE
            obs.count("serve.completed")
            # latency histograms observed exactly where serve.completed
            # bumps, so the CI invariant "e2e histogram count equals
            # completed requests" holds at every /metrics scrape
            obs.histogram("serve.e2e_s", now - req.t_submit)
            obs.histogram("serve.service_s",
                          now - (req.t_coalesce or req.t_dispatch
                                 or req.t_submit))
        if self.persist and status == rq.DONE \
                and req.session is None:
            # session blocks are not persisted as store runs: their
            # durable record is the session journal (replayable), and
            # a browsable run per append would bury real runs
            try:
                # provisional done stamp so the PERSISTED waterfall
                # carries its publish stage (registry.finish re-stamps
                # a hair later; the live GET view uses that one)
                req.t_done = now
                req.run_dir = self._persist(req, res)
            except Exception as e:                      # noqa: BLE001
                # never silent: the verdict still publishes, but the
                # missing run dir is a recorded degradation
                log.warning("serve persist failed for %s: %s",
                            req.id, e)
                obs.engine_fallback("serve-persist",
                                    type(e).__name__, id=req.id)
        self.registry.finish(req, status, res)
        self.registry.ledger_record(
            req.tenant, status, id=req.id,
            valid=res.get("valid"), engine=res.get("engine"),
            dispatch_s=round(elapsed, 6),
            device_s=round(req.device_s or 0.0, 9),
            latency_s=round(now - req.t_submit, 6))
        obs.count(
            f"serve.tenant.{self.registry.bucket_tenant(req.tenant)}"
            f".{status}")

    def _finish_timeout_queued(self, req: "rq.CheckRequest") -> None:
        """Queue-side deadline expiry (never dispatched)."""
        self.registry.finish(req, rq.TIMEOUT,
                             {"valid": "unknown", "cause": "deadline",
                              "queued-only": True})
        self.registry.ledger_record(req.tenant, rq.TIMEOUT, id=req.id,
                                    queued_only=True)
        obs.count(
            f"serve.tenant.{self.registry.bucket_tenant(req.tenant)}"
            f".timeout")

    # -- persistence -----------------------------------------------------
    def _persist(self, req: "rq.CheckRequest",
                 res: Dict[str, Any]) -> str:
        """Write the request as a browsable store run
        (:func:`jepsen_tpu.store.save_check` —
        ``<root>/serve-<model>/<ts>-<id>/``) so the existing
        ``web.py`` results browser renders daemon traffic exactly
        like CLI runs."""
        from jepsen_tpu import store
        faults.fire("persist", tenants=[req.tenant])
        assert self.store_root is not None
        out = dict(res)
        out["serve"] = {"id": req.id, "tenant": req.tenant,
                        "latency-s": round(
                            time.monotonic() - req.t_submit, 6),
                        "device-s": round(req.device_s or 0.0, 9),
                        "waterfall": req.waterfall(),
                        "trace": [dict(r) for r in req.trace]}
        return store.save_check(self.store_root,
                                f"serve-{req.model_name}", req.id,
                                list(req.history), out)

    # -- stats -----------------------------------------------------------
    def stats(self, snap: Optional[Dict[str, Any]] = None
              ) -> Dict[str, Any]:
        if snap is None:
            snap = obs.core.GLOBAL.snapshot()
        counters = {k: v for k, v in snap["counters"].items()
                    if k.startswith(("serve.", "engine.", "lockstep.",
                                     "compile_cache.", "memo_cache.",
                                     "transfer.", "pipeline."))}
        with self._counts_lock:
            dispatch = dict(self.dispatch_counts)
        out = {
            "queue": {"depth": self.queue.depth(),
                      "max_depth": self.queue.max_depth,
                      "inflight": self.queue.inflight(),
                      "group": self.queue.group,
                      "max_inflight_per_tenant":
                          self.queue.max_inflight_per_tenant},
            "dispatch": dispatch,
            "counters": counters,
            # headline digests of the serve-path latency histograms
            # (full bucket ladders live on GET /metrics)
            "histograms": {k: obs.hist_summary(h)
                           for k, h in snap["histograms"].items()
                           if k.startswith("serve.")},
            "timeseries": self.ring.points(),
            "profile": self.profile_state(),
            # degradation surface: breaker state + retry policy, so
            # /stats, stats.json, and the /engine dashboard all see
            # the same health the chaos harness asserts on. With
            # multiple lanes, "breaker" stays lane 0's (back-compat)
            # and the per-lane view + any-lane-degraded aggregate
            # live under "lanes".
            "breaker": self.breaker.to_json(),
            "degraded": any(ln.breaker.degraded
                            for ln in self._lanes),
            "retry": self.retry.to_json(),
            "lanes": {
                "n": self.lanes_n,
                "loads": self.queue.lane_loads(),
                "breakers": [ln.breaker.to_json()
                             for ln in self._lanes],
            },
        }
        if self.journal is not None:
            out["journal"] = self.journal.stats()
        if self.sessions is not None:
            # open-session census: count, oldest age, per-tenant —
            # the /engine dashboard's "open sessions" row
            out["sessions"] = self.sessions.census()
        out.update(self.registry.stats())
        return out

    def _write_stats_file(self, snap: Optional[Dict[str, Any]] = None
                          ) -> None:
        """Drop the latest stats snapshot under the store root
        (best-effort) so the results browser's ``/engine`` page can
        render a daemon it does not share a process with."""
        if not self.store_root:
            return
        try:
            d = os.path.join(self.store_root, "serve")
            os.makedirs(d, exist_ok=True)
            # per-thread tmp name: N lane threads write stats
            # concurrently, and two writers sharing one tmp path
            # would interleave open("w")/replace and tear stats.json
            tmp = os.path.join(
                d, f".stats.json.{threading.get_ident()}.tmp")
            with open(tmp, "w") as f:
                json.dump({"ts": time.time(), **self.stats(snap)}, f,
                          default=str)
            os.replace(tmp, os.path.join(d, "stats.json"))
        # jtlint: ok fallback — per-dispatch stats are advisory, never fatal
        except Exception:                               # noqa: BLE001
            pass                # stats are advisory, never fatal
