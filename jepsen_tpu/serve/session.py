"""Streaming check sessions: device-resident online verification.

A *session* is a long-lived check (ROADMAP item 2): ``POST /session``
opens one, each ``POST /session/<id>/append`` ships an event block and
returns the incremental one-bool verdict seconds after the ops ran —
not at teardown — and ``POST /session/<id>/close`` resolves the
unsettled tail and returns the exact final verdict + witness,
differential-identical to ``facade.auto_check_packed`` (or
``auto_check_txn``) on the concatenated history.

The two incremental engines:

- **Register-family models** (:class:`DeviceFrontierEngine`): the
  settled-prefix/unsettled-tail discipline and the
  fail-fast-is-permanent semantics are *exactly*
  :mod:`jepsen_tpu.checkers.online`'s — this engine IS
  ``online.NativeStreamEngine`` (the C++ monitor core does slot
  assignment, settle-queue snapshots, and wildcard interning) with
  one substitution: the settled-returns walk happens on the
  accelerator through :class:`jepsen_tpu.checkers.reach.FrontierCarry`
  — the reachable-config frontier ``R [S, M]`` stays device-resident
  across appends (the dense body's carry donated so XLA advances it
  in place; the word-packed body's carry is a few machine words and
  deliberately not donated), and each append ships only its block's
  narrow ``(ret_slot, slot_ops)`` operands plus one alive-bool
  fetch. The
  unsettled-tail alarm walks a bounded tail from the carried set
  without touching it (non-donating probe).
- **``txn-list-append``** (:class:`TxnSessionEngine`): the inferred
  ww/wr/rw adjacency grows incrementally
  (:class:`jepsen_tpu.txn.infer.IncrementalInfer` — reads settle once
  every observed value has a known appender, so edges are monotone
  and an early cycle alarm is sound) and the boolean-matmul closure
  re-closes only the dirty row/column blocks per append batch
  (:class:`jepsen_tpu.txn.cycles.IncrementalClosure`), making
  ``txn/cycles.py`` an online anomaly detector.

Fallback contract (the engine-stack discipline): any device-path
death records exactly ONE ``session-advance`` obs fallback and the
session falls PERMANENTLY back to the host path —
:class:`~jepsen_tpu.checkers.online.OnlineLinearizable` replaying the
accumulated stream for register models, the host SCC booleans over
the accumulated graph for txn — with identical verdicts. Capacity
declines (dense overflow, no native lib) are recorded route
decisions, not fallbacks.

Sessions ride the daemon's existing machinery: appends are
:class:`~jepsen_tpu.serve.request.CheckRequest`s whose coalescing
signature is the session id (so queued appends of one session
coalesce into ONE ordered dispatch group — continuous batching of
appends — while one-shot checks flow around them in the same
dispatcher loop), they are journaled before their response so a
SIGKILL'd daemon replays the stream and re-derives the frontier, and
every verdict lands in the standard registry/ledger plumbing.
"""
from __future__ import annotations

import logging
import threading
import contextlib
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Sequence

from jepsen_tpu import history as h
from jepsen_tpu import obs
from jepsen_tpu.checkers import online, reach_word
from jepsen_tpu.models import Model
from jepsen_tpu.op import Op
from jepsen_tpu.serve import faults

log = logging.getLogger("jepsen.serve.session")

# engine options a session forwards to its incremental engines and the
# close-time exact check (the daemon already allow-lists client opts)
_ENG_KW = ("max_states", "max_slots", "max_dense")


def new_session_id() -> str:
    import uuid
    return "s" + uuid.uuid4().hex[:15]


class SessionClosed(RuntimeError):
    """Appends after close are a client error (HTTP 409)."""


class AdvanceAborted(RuntimeError):
    """A session advance exceeded the dispatcher's wall-clock cap
    (``--dispatch-deadline``): raised from the ``should_abort`` hook
    between engine steps. Deliberately an ordinary Exception to the
    advance ladder — the session takes its ordinary PERMANENT host
    fallback (one ``session-advance`` obs fallback, host monitor
    replays the accumulated stream), exactly like any other device-
    path death, so a hung device advance cannot wedge a lane while
    the verdict contract stays intact."""


class TenantSessionCap(RuntimeError):
    """One tenant hit its open-session cap (HTTP 429 with cause
    ``tenant-cap`` — the global bound stays a plain RuntimeError)."""


# -- register-family device engine ----------------------------------------

class DeviceFrontierEngine(online.NativeStreamEngine):
    """``online.NativeStreamEngine`` with the settled-returns walk on
    the accelerator: feed/settle bookkeeping stays in the C++ monitor
    core (``jt_mon_feed`` / the new ``jt_mon_drain``), the carried
    frontier lives device-resident in a
    :class:`reach.FrontierCarry`. Geometry changes (memo rebuild on
    a fresh alphabet entry, slot growth) sync the frontier host-side,
    re-encode exactly like the host engine, and reseed the carry —
    rare events that stabilize once the alphabet does."""

    def __init__(self, model: Model, **kw: Any) -> None:
        super().__init__(model, **kw)
        self._carry = None                  # reach.FrontierCarry

    # -- geometry-change sync (device -> host mirror first) -------------
    def _sync_host(self) -> None:
        if self._carry is not None and self.R is not None:
            self.R = self._carry.fetch()
            self._carry = None

    def _rebuild_memo(self) -> None:
        self._sync_host()
        super()._rebuild_memo()
        self._carry = None

    def _grow_W(self, W2: int) -> None:
        self._sync_host()
        super()._grow_W(W2)
        self._carry = None

    def _ensure_carry(self):
        if self._carry is None:
            from jepsen_tpu.checkers import reach
            S = self.R.shape[0]
            # P is built LAZILY: the word-packed body only needs the
            # flat table, and materializing the O(O*S^2) dense tensor
            # for it would burn memory the walk never touches
            self._carry = reach.FrontierCarry(
                None, self.W, 1 << self.W, self.R,
                table=self.memo.table,
                p_build=lambda: reach._build_P(self.memo, S))
        return self._carry

    # -- the walks (device) ----------------------------------------------
    def stage_advance(self, run_over: bool = False):
        """First half of :meth:`advance`: drain the monitor and
        return the staged walk operands ``(carry, ret_slot, slot_ops,
        binds)``, or None when there is nothing to walk. The split
        exists for the mega-batch dispatcher: N same-geometry
        sessions stage, ONE batched kernel advances every carry, each
        session commits — and :meth:`advance` itself is the
        degenerate one-member composition, so the solo and batched
        paths cannot drift."""
        if self.violation is not None:
            return None
        self._drain()
        if run_over:
            # base-class semantics: stragglers resolve as crashed,
            # making the final incremental verdict the exact one
            self._resolve_stragglers()
        if self.memo is None:
            return None
        _s, queued, _l, _w, front_ok = self._mon.stats()
        if queued == 0 or not front_ok:
            return None
        rows, slots, binds = self._mon.drain(queued, self.W)
        if len(slots) == 0:
            return None
        return (self._ensure_carry(), slots, rows, binds)

    def commit_advance(self, staged,
                       dead: int) -> Optional[Dict[str, Any]]:
        """Second half of :meth:`advance`: account the walked block
        and resolve the exact death index into a violation."""
        _carry, slots, _rows, binds = staged
        n = len(slots) if dead < 0 else dead + 1
        self.settled_returns += n
        self.walked_events += n
        if dead >= 0:
            self.violation = self._violation_at(int(binds[dead]))
        return self.violation

    def advance(self, run_over: bool = False
                ) -> Optional[Dict[str, Any]]:
        if self.violation is not None:
            return self.violation
        staged = self.stage_advance(run_over)
        if staged is None:
            return None
        carry, slots, rows, _binds = staged
        dead = carry.advance(slots, rows)
        return self.commit_advance(staged, dead)

    def tail_alarm(self) -> Optional[Dict[str, Any]]:
        if self.violation is not None or self.memo is None:
            return None
        self._drain()
        _s, queued, _l, _w, _f = self._mon.stats()
        if queued == 0:
            return None         # nothing unsettled: nothing to alarm on
        rows, slots, binds = self._mon.tail(self._TAIL_CAP, self.W)
        if len(slots) == 0:
            return None
        dead = self._ensure_carry().probe(slots, rows)
        if dead >= 0:
            self.violation = self._violation_at(int(binds[dead]))
            self.violation["tail-alarm"] = True
        return self.violation


# -- txn incremental engine -----------------------------------------------

class TxnSessionEngine:
    """Incremental Elle-style anomaly detection for one session:
    host-side stateful inference + the device-resident dirty-block
    closure. Direct anomalies (non-prefix reads, duplicate appends,
    G1a) fail the session the moment they are proven, exactly like a
    frontier death fails a register session."""

    def __init__(self, *, max_dense_txns: Optional[int] = None,
                 consistency: Optional[Any] = None) -> None:
        from jepsen_tpu.txn import cycles, lattice
        from jepsen_tpu.txn.infer import IncrementalInfer
        # lattice mode: the session was opened with a "consistency"
        # option — the incremental closure carries the K=4 lane stack
        # and every advance reports per-level holds; validity gates on
        # the REQUESTED levels, like the post-hoc check
        self.levels: Optional[List[str]] = (
            None if consistency is None
            else lattice.canon_levels(consistency))
        self.infer = IncrementalInfer()
        self.closure = cycles.IncrementalClosure(
            max_dense_txns=max_dense_txns,
            lattice=self.levels is not None)
        # the self-nemesis hook, fired right before the device
        # closure — AFTER inference consumed the block, so the
        # session's fallback can resume with an empty re-feed (the
        # host classify reads the full accumulated graph)
        self.fire_hook = lambda: None
        self.host_mode = False              # permanent after decline
        self.violation: Optional[Dict[str, Any]] = None
        if self.levels is not None:
            self.booleans = {k: False for k in cycles.LATTICE_KEYS}
            self.holds: Optional[Dict[str, bool]] = \
                lattice.holds_from(self.booleans)
        else:
            self.booleans = {
                "cyc_ww": False, "cyc_wwwr": False,
                "cyc_full": False, "gsingle": False}
            self.holds = None

    def _classify(self) -> Optional[Dict[str, Any]]:
        from jepsen_tpu.txn import host_ref, lattice
        if self.levels is not None:
            # per-process session-guarantee prefix scans are host
            # work either way; holds are monotone under extension
            # (cumulative booleans + monotone scans), so the sticky
            # first violation is sound
            scans = lattice.session_scans(self.infer.txns)
            self.holds = lattice.holds_from(
                self.booleans, session_violated=bool(scans))
            if all(self.holds[lvl] for lvl in self.levels):
                return None
            graph = self.infer.graph()
            starts, ends = self.infer.intervals()
            gsia = host_ref.gsia_scan(graph, starts, ends) is not None
            present = lattice._class_presence(self.booleans, scans,
                                              gsia)
            anomalies = [c for lvl in lattice.LEVELS
                         for c in lattice.LEVEL_ANOMALIES[lvl]
                         if present.get(c)]
            out = {"valid": False, "engine": "session-txn",
                   "consistency": list(self.levels),
                   "holds": dict(self.holds),
                   "weakest-violated":
                       lattice.weakest_violated(self.holds),
                   "anomalies": anomalies,
                   "booleans": dict(self.booleans)}
            if anomalies:
                out["anomaly"] = anomalies[0]
            if scans:
                out["session-violations"] = scans[:8]
            return out
        anomalies = host_ref.derive_anomalies(self.booleans)
        if anomalies:
            return {"valid": False, "engine": "session-txn",
                    "anomalies": anomalies, "anomaly": anomalies[0],
                    "booleans": dict(self.booleans)}
        return None

    def advance_block(self, ops: Sequence[Op]) -> Optional[Dict]:
        """Feed one append block; returns the violation (sticky) or
        None. Raises on device failure — the session owns the
        exactly-one-fallback contract."""
        from jepsen_tpu.txn import cycles, host_ref
        if self.violation is not None:
            return self.violation
        self.infer.feed_block(ops)
        if self.infer.direct:
            kinds = sorted({d["type"] for d in self.infer.direct})
            self.violation = {
                "valid": False, "engine": "session-txn-infer",
                "anomalies": kinds, "anomaly": kinds[0],
                "direct": [dict(d) for d in self.infer.direct[:32]]}
            return self.violation
        src, dst, et = self.infer.drain_new_edges()
        if self.levels is not None:
            # the commit-order lane rides the same dirty-block feed:
            # completion-ordered arrival means cm edges only ever
            # point INTO the new txns, so the drain is a delta too
            csrc, cdst = self.infer.drain_new_cm()
            if csrc.size:
                import numpy as _np
                from jepsen_tpu.txn.infer import CM
                src = _np.concatenate([_np.asarray(src, _np.int64),
                                       _np.asarray(csrc, _np.int64)])
                dst = _np.concatenate([_np.asarray(dst, _np.int64),
                                       _np.asarray(cdst, _np.int64)])
                et = _np.concatenate([
                    _np.asarray(et, _np.int64),
                    _np.full(csrc.size, CM, _np.int64)])
        if self.host_mode:
            self.booleans = self._host_booleans()
        else:
            try:
                self.fire_hook()
                self.booleans = self.closure.add_block(
                    max(self.infer.n, 1), src, dst, et)
            except cycles.ClosureOverflow as e:
                # capacity decline, not a device death: recorded
                # route, host booleans from here on
                obs.decision("session-advance", "route",
                             cause=f"txn-overflow:{e}")
                self.host_mode = True
                self.booleans = self._host_booleans()
        self.violation = self._classify()
        return self.violation

    def _host_booleans(self) -> Dict[str, bool]:
        from jepsen_tpu.txn import host_ref
        g = self.infer.graph()
        booleans = dict(host_ref.classify_booleans(g))
        if self.levels is not None:
            starts, ends = self.infer.intervals()
            booleans.update(host_ref.lattice_classify_booleans(
                g, starts, ends))
        return booleans

    def to_host(self) -> None:
        """Device closure died: continue host-side permanently (the
        session already recorded the one fallback)."""
        self.host_mode = True

    def close_incremental(self) -> Dict[str, Any]:
        """Resolve stragglers and return the final incremental
        verdict (the authoritative exact check is the session's).
        The post-resolution classification reuses the ordinary
        :meth:`advance_block` ladder with an empty feed, so the
        close path cannot drift from the append path."""
        if self.violation is None:
            self.infer.resolve_stragglers()
            self.advance_block([])
        if self.violation is not None:
            return dict(self.violation)
        out = {"valid": True, "engine": "session-txn",
               "txns": self.infer.n,
               "booleans": dict(self.booleans)}
        if self.levels is not None:
            out["consistency"] = list(self.levels)
            out["holds"] = dict(self.holds)
        return out

    def in_flight(self) -> int:
        return len(self.infer._live) + self.infer.pending_reads()


# -- the session ----------------------------------------------------------

class Session:
    """One long-lived check: carried engine state, the accumulated
    op stream (close + fallback replay), and the sticky first
    violation. Appends are serialized under the session lock (the
    dispatcher already serializes same-session dispatch groups; the
    lock additionally covers journal replay and HTTP status reads)."""

    # jtlint lock discipline: session state is only touched under
    # self.lock; the listed helpers are called with it held (or from
    # __init__, before the session is shared) — statically enforced
    # by the `lock-discipline` pass
    _GUARDED_BY = {"lock": ("ops", "ops_total", "closed", "closing",
                            "result", "violation", "seq", "appends",
                            "replayed", "fallbacks")}
    _LOCK_ASSUMED = ("_route", "_to_host_monitor", "_advance_engine",
                     "_append_verdict", "_close_incremental",
                     "_exact_final", "_update_mega_sig",
                     "_stage_block", "_finish_block")

    def __init__(self, sid: str, tenant: str, model_name: str,
                 model: Model, opts: Optional[Dict[str, Any]] = None
                 ) -> None:
        from jepsen_tpu.txn.ops import ListAppend
        self.id = sid
        self.tenant = tenant
        self.model_name = model_name
        self.model = model
        self.opts = dict(opts or {})
        self.created_wall = time.time()
        self.created_mono = time.monotonic()
        # idle-TTL clock: bumped on every append (and replayed
        # append); an open session whose clock goes stale past the
        # registry's idle_ttl_s is force-closed by the daemon sweeper
        self.last_active_mono = self.created_mono
        self.lock = threading.RLock()
        self.seq = 0                        # admitted append blocks
        self.ops: List[Op] = []
        self.ops_total = 0                  # survives the close drop
        self.closed = False
        self.closing = False
        self.result: Optional[Dict[str, Any]] = None
        self.violation: Optional[Dict[str, Any]] = None
        self.fallbacks = 0
        self.appends = 0
        self.replayed = 0
        self.is_txn = isinstance(model, ListAppend)
        self._host: Optional[online.OnlineLinearizable] = None
        self._eng: Any = None
        self.engine_name = "session-host"
        # cached mega-batch walk-geometry signature (None = cannot
        # participate). Written ONLY under the lock (at the end of
        # every append/close/fallback transition); read lock-free by
        # the coalescer's signature property — a stale read degrades
        # grouping efficiency, never correctness, because group
        # membership is re-validated at stage time under the lock.
        self._mega_sig: Optional[tuple] = None
        self._route()

    # -- route selection -------------------------------------------------
    def _eng_kw(self) -> Dict[str, Any]:
        return {k: v for k, v in self.opts.items() if k in _ENG_KW}

    def _route(self) -> None:
        import os
        if self.is_txn:
            self._eng = TxnSessionEngine(
                max_dense_txns=self.opts.get("max_dense_txns"),
                consistency=self.opts.get("consistency"))
            self._eng.fire_hook = (
                lambda: faults.fire("session-advance",
                                    tenants=[self.tenant]))
            self.engine_name = "session-txn-mxu"
            return
        from jepsen_tpu.checkers import preproc_native
        if os.environ.get("JEPSEN_TPU_NO_SESSION_DEVICE"):
            obs.decision("session-advance", "route", cause="opt-out",
                         session=self.id)
            self._to_host_monitor(record_fallback=False)
            return
        if not preproc_native.available():
            # the device engine's settle bookkeeping is the C++
            # monitor core; without it the host monitor (which has
            # its own pure-Python tier) is the route, not a crash
            obs.decision("session-advance", "route",
                         cause="no-native-monitor", session=self.id)
            self._to_host_monitor(record_fallback=False)
            return
        self._eng = DeviceFrontierEngine(self.model, **self._eng_kw())
        self.engine_name = "session-frontier-device"

    def _to_host_monitor(self, record_fallback: bool,
                         exc: Optional[BaseException] = None) -> None:
        """Switch PERMANENTLY to the host online monitor, replaying
        the accumulated stream (its own incremental engine re-derives
        the state; overflow degrades to prefix re-checking inside the
        monitor — the same ladder live runs always had)."""
        if record_fallback:
            self.fallbacks += 1
            obs.engine_fallback("session-advance",
                                type(exc).__name__ if exc else "error",
                                session=self.id, ops=len(self.ops))
            obs.count("serve.session.fallback")
            log.warning("session %s device path died (%r); host "
                        "monitor fallback", self.id, exc)
        if self.is_txn:
            self._eng.to_host()
            self.engine_name = "session-txn-host"
            return
        kw = self._eng_kw()
        mon = online.OnlineLinearizable(self.model, **kw)
        for op in self.ops:
            mon.observe(op)
        mon.flush()
        self._host = mon
        self._eng = None
        self.engine_name = "session-host-monitor"
        if mon.violation is not None and self.violation is None:
            self.violation = dict(mon.violation)
        self._update_mega_sig()

    # -- mega-batch eligibility ------------------------------------------
    def mega_sig(self) -> Optional[tuple]:
        """The session's walk-geometry signature for mega-batch
        grouping (same tuple for every session whose carried frontier
        compiles to the same batched walk), or None when it cannot
        participate: txn sessions, host-fallen sessions, closed/
        closing/violated ones, dense carries, and sessions whose
        carry has not been seeded yet (their first advance runs solo
        and seeds it). Lock-free cached read — see ``_mega_sig``."""
        return self._mega_sig

    def _update_mega_sig(self) -> None:
        sig = None
        if not (self.closed or self.closing or self.is_txn
                or self._host is not None
                or self.violation is not None):
            carry = getattr(self._eng, "_carry", None)
            if carry is not None:
                sig = reach_word.mega_geometry(carry)
        self._mega_sig = sig

    # -- appends ---------------------------------------------------------
    def advance_block(self, ops: Sequence[Op],
                      seq: Optional[int] = None,
                      should_abort: Optional[Any] = None
                      ) -> Dict[str, Any]:
        """Feed one event block and return the incremental verdict +
        tail-alarm status. Fail-fast is permanent: once a violation
        is proven, every later append returns it unchanged (the
        sticky verdict — linearizability/serializability are
        prefix-closed, nothing can repair them).

        ``should_abort`` (the dispatcher's deadline hook) is polled
        between engine steps; when it fires, the device advance
        aborts via :class:`AdvanceAborted` and the ordinary permanent
        host fallback below produces the verdict — the host replay
        path never polls it (it IS the fallback target; aborting it
        would leave no verdict at all)."""
        with self.lock:
            if self.closed:
                raise SessionClosed(f"session {self.id} is closed")
            self.last_active_mono = time.monotonic()
            self.appends += 1
            self.ops.extend(ops)
            self.ops_total = len(self.ops)
            obs.count("serve.session.appends")
            obs.count("serve.session.append_ops", len(ops))
            tail_hit = False
            if self.violation is None:
                try:
                    # the self-nemesis hook (register path): chaos/
                    # tests force the device path to die here — the
                    # host monitor replays the FULL accumulated
                    # stream, so firing before the feed loses
                    # nothing. The txn hook fires inside the engine,
                    # after inference consumed the block.
                    if not self.is_txn:
                        faults.fire("session-advance",
                                    tenants=[self.tenant])
                    v = self._advance_engine(ops, should_abort)
                except online._Overflow as e:
                    # capacity, not death: recorded route decision
                    obs.decision("session-advance", "route",
                                 cause=f"overflow:{type(e).__name__}",
                                 session=self.id)
                    self._to_host_monitor(record_fallback=False)
                    v = self.violation
                except Exception as e:                  # noqa: BLE001
                    # the device path died: exactly ONE obs fallback,
                    # then the host monitor re-derives the state from
                    # the journal-backed accumulated stream
                    if self.is_txn:
                        obs.engine_fallback(
                            "session-advance", type(e).__name__,
                            session=self.id, ops=len(self.ops))
                        obs.count("serve.session.fallback")
                        self.fallbacks += 1
                        self._eng.to_host()
                        self.engine_name = "session-txn-host"
                        v = self._eng.advance_block([])
                    else:
                        self._to_host_monitor(record_fallback=True,
                                              exc=e)
                        v = self.violation
                if v is not None and self.violation is None:
                    self.violation = dict(v)
                tail_hit = bool((v or {}).get("tail-alarm"))
            self._update_mega_sig()
            return self._append_verdict(len(ops), tail_hit, seq)

    def _advance_engine(self, ops: Sequence[Op],
                        should_abort: Optional[Any] = None
                        ) -> Optional[Dict[str, Any]]:
        def _check_abort() -> None:
            # polled between engine steps (feed / frontier walk /
            # tail probe): the granularity the one-shot segmented
            # walk's abort hook has per segment, applied to the
            # session's per-block device calls
            if should_abort is not None and should_abort():
                raise AdvanceAborted(
                    "session advance aborted past the dispatch "
                    "deadline")
        if self._host is not None:
            # the host monitor is the fallback TARGET: it never
            # aborts (aborting it would leave the block verdict-less)
            for op in ops:
                self._host.observe(op)
            self._host.flush()
            return self._host.violation
        if self.is_txn:
            _check_abort()
            return self._eng.advance_block(ops)
        self._eng.feed_many(list(ops))
        _check_abort()
        v = self._eng.advance()
        _check_abort()
        if v is None:
            v = self._eng.tail_alarm()
        return v

    def _append_verdict(self, block_ops: int, tail_hit: bool,
                        seq: Optional[int] = None) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "session": self.id,
            "seq": self.seq if seq is None else seq,
            "block-ops": block_ops, "ops": len(self.ops),
            "valid-so-far": self.violation is None,
            "tail-alarm": tail_hit,
            "engine": self.engine_name,
        }
        if self._host is not None:
            out["in-flight"] = (self._host._engine.in_flight()
                                if self._host._engine is not None
                                else None)
        elif self.is_txn:
            out["txns"] = self._eng.infer.n
            out["in-flight"] = self._eng.in_flight()
            if self._eng.holds is not None:
                # lattice mode: every append reports the per-level
                # verdict frontier (monotone — levels only degrade)
                out["holds"] = dict(self._eng.holds)
        else:
            out["settled-returns"] = self._eng.settled_returns
            out["in-flight"] = self._eng.in_flight()
        if self.violation is not None:
            out["violation"] = dict(self.violation)
        return out

    # -- mega-batch member protocol --------------------------------------
    def _stage_block(self, ops: Sequence[Op], seq: Optional[int],
                     should_abort: Optional[Any], geom: tuple):
        """First half of :meth:`advance_block` for one mega-group
        member (lock held by :func:`advance_group`): feed the block
        and stage the frontier-walk operands. Returns ``("staged",
        st)`` when the member joined the batched launch, or
        ``("done", verdict)`` when it completed on its own — device
        engine ineligible, nothing to walk, capacity routed, geometry
        regrown out of the group, or fallen to host. Every branch
        reproduces the exact solo :meth:`advance_block` ladder."""
        if self.closed:
            raise SessionClosed(f"session {self.id} is closed")
        if (self.violation is not None or self.is_txn
                or self._host is not None):
            # the sticky / txn / host paths never stage device walks:
            # the ordinary append (re-entrant lock) is the semantics
            return ("done", self.advance_block(ops, seq, should_abort))
        self.last_active_mono = time.monotonic()
        self.appends += 1
        self.ops.extend(ops)
        self.ops_total = len(self.ops)
        obs.count("serve.session.appends")
        obs.count("serve.session.append_ops", len(ops))
        try:
            faults.fire("session-advance", tenants=[self.tenant])
            if should_abort is not None and should_abort():
                raise AdvanceAborted(
                    "session advance aborted past the dispatch "
                    "deadline")
            self._eng.feed_many(list(ops))
            st = self._eng.stage_advance()
            v = None
            if st is not None:
                if reach_word.mega_geometry(st[0]) != geom:
                    # the feed regrew the walk geometry (memo rebuild
                    # on a fresh alphabet entry / slot growth): this
                    # member advances solo on its already-staged
                    # operands; the rest of the group stays batched
                    obs.decision("session-mega", "regrow",
                                 session=self.id)
                    dead = st[0].advance(st[1], st[2])
                    v = self._eng.commit_advance(st, dead)
                    st = None
            if st is None:
                return ("done", self._finish_block(len(ops), seq, v))
            return ("staged", st)
        except online._Overflow as e:
            obs.decision("session-advance", "route",
                         cause=f"overflow:{type(e).__name__}",
                         session=self.id)
            self._to_host_monitor(record_fallback=False)
            return ("done", self._finish_block(len(ops), seq,
                                               self.violation))
        except Exception as e:                          # noqa: BLE001
            self._to_host_monitor(record_fallback=True, exc=e)
            return ("done", self._finish_block(len(ops), seq,
                                               self.violation))

    def _finish_block(self, block_ops: int, seq: Optional[int],
                      v: Optional[Dict[str, Any]]) -> Dict[str, Any]:
        """Second half of :meth:`advance_block` for a mega-group
        member: the walk verdict is in — run the tail alarm (device
        members only) and produce the append verdict, with the same
        fallback ladder a solo tail probe has."""
        try:
            if (v is None and self._host is None and not self.is_txn
                    and self._eng is not None):
                v = self._eng.tail_alarm()
        except Exception as e:                          # noqa: BLE001
            self._to_host_monitor(record_fallback=True, exc=e)
            v = self.violation
        if v is not None and self.violation is None:
            self.violation = dict(v)
        tail_hit = bool((v or {}).get("tail-alarm"))
        self._update_mega_sig()
        return self._append_verdict(block_ops, tail_hit, seq)

    # -- close -----------------------------------------------------------
    def close(self) -> Dict[str, Any]:
        """Resolve the unsettled tail (the incremental verdict becomes
        the exact full-history one) and return the authoritative final
        verdict + witness: ``facade.auto_check_packed`` /
        ``auto_check_txn`` on the concatenated history — the
        differential identity the protocol promises — cross-asserted
        against the incremental verdict (a divergence is a recorded
        bug, never silent)."""
        with self.lock:
            if self.closed:
                return dict(self.result or {})
            inc = self._close_incremental()
            final = self._exact_final()
            inc_valid = inc.get("valid")
            if inc_valid in (True, False) \
                    and final.get("valid") in (True, False) \
                    and inc_valid is not final["valid"]:
                obs.count("serve.session.divergence")
                log.error("session %s incremental/exact divergence: "
                          "%r vs %r", self.id, inc_valid,
                          final.get("valid"))
                final["incremental-divergence"] = True
            # lattice sessions promise MORE than the boolean verdict:
            # the incremental per-level holds must equal the exact
            # post-hoc ones level-for-level
            if isinstance(inc.get("holds"), dict) \
                    and isinstance(final.get("holds"), dict) \
                    and inc["holds"] != final["holds"]:
                obs.count("serve.session.divergence")
                log.error("session %s lattice holds divergence: "
                          "%r vs %r", self.id, inc["holds"],
                          final["holds"])
                final["incremental-divergence"] = True
            final["session"] = self.id
            final["appends"] = self.appends
            final["session-ops"] = len(self.ops)
            final["session-engine"] = self.engine_name
            final["incremental"] = {
                k: inc.get(k) for k in
                ("valid", "engine", "settled-returns", "ops-checked",
                 "txns", "anomalies", "holds", "weakest-violated")
                if inc.get(k) is not None}
            self.closed = True
            self.result = final
            # the retention contract is the verdict, not the stream:
            # drop the accumulated ops and the carried engine state
            # (device frontier / closure masks / host monitor) so the
            # keep_closed retained sessions cost bytes, not histories
            # and dead device buffers
            self.ops_total = len(self.ops)
            self.ops = []
            self._eng = None
            self._host = None
            self._update_mega_sig()
            obs.count("serve.session.closed")
            return dict(final)

    def _close_incremental(self) -> Dict[str, Any]:
        try:
            if self._host is not None:
                return self._host.stop()
            if self.is_txn:
                return self._eng.close_incremental()
            v = self._eng.advance(run_over=True)
            if v is not None:
                return dict(v)
            return {"valid": True, "engine": self.engine_name,
                    "settled-returns": self._eng.settled_returns}
        except online._Overflow as e:
            # capacity at close is the same ROUTE decision it is at
            # append time — never a recorded device death (the
            # exactly-one-fallback accounting chaos asserts on)
            obs.decision("session-advance", "route",
                         cause=f"overflow:{type(e).__name__}",
                         session=self.id, close=True)
            self._to_host_monitor(record_fallback=False)
            return self._host.stop()
        # jtlint: ok fallback — violation already proven+sticky; the close death is moot
        except Exception as e:                          # noqa: BLE001
            # a death during tail resolution follows the same
            # one-fallback ladder; the host monitor's stop() is exact
            if self.violation is not None:
                return dict(self.violation)
            if self.is_txn:
                obs.engine_fallback("session-advance",
                                    type(e).__name__, session=self.id,
                                    close=True)
                obs.count("serve.session.fallback")
                self.fallbacks += 1
                self._eng.to_host()
                return self._eng.close_incremental()
            self._to_host_monitor(record_fallback=True, exc=e)
            return self._host.stop()

    def _exact_final(self) -> Dict[str, Any]:
        from jepsen_tpu.checkers import facade
        if not self.ops:
            return {"valid": True, "engine": "session-empty", "ops": 0}
        # ALWAYS reindex in arrival order: blocks may carry
        # client-supplied per-block indices (each starting at 0), and
        # packing would re-sort duplicates across block boundaries —
        # scrambling the stream the incremental engines walked in
        # arrival order. Reindexing makes arrival order authoritative
        # for the exact check too.
        ops = h.index(list(self.ops))
        try:
            if self.is_txn:
                return facade.auto_check_txn(ops, dict(self.opts))
            return facade.auto_check_packed(self.model, h.pack(ops),
                                            dict(self.opts))
        except Exception as e:                          # noqa: BLE001
            obs.checker_swallowed("session-close", type(e).__name__,
                                  ops=len(ops))
            return {"valid": "unknown",
                    "error": f"{type(e).__name__}: {e}"}

    # -- views -----------------------------------------------------------
    def status(self) -> Dict[str, Any]:
        with self.lock:
            out = {
                "session": self.id, "tenant": self.tenant,
                "model": self.model_name,
                "status": "closed" if self.closed else "open",
                "seq": self.seq, "appends": self.appends,
                "ops": self.ops_total,
                "age-s": round(time.monotonic() - self.created_mono,
                               3),
                "engine": self.engine_name,
                "valid-so-far": self.violation is None,
                "replayed-appends": self.replayed,
            }
            if self.violation is not None:
                out["violation"] = dict(self.violation)
            if self.result is not None:
                out["result"] = dict(self.result)
            return out


# -- the registry ---------------------------------------------------------

# -- mega-batch group advance ----------------------------------------------

# lane count below which the per-session dispatch path wins: one
# staged member gains nothing from a batched launch, and the gather/
# scatter overhead is pure loss at width 1
_MEGA_CROSSOVER_DEFAULT = 2


def mega_crossover() -> int:
    """The measured ``session-mega`` crossover width from the
    persisted autotune table (``bench.py``'s session_mux probe
    records it), else the heuristic default. Groups narrower than
    this advance per-session."""
    from jepsen_tpu.checkers import autotune
    w = autotune.winner("session-mega", "crossover")
    if w is not None:
        try:
            return max(1, int(w))
        # jtlint: ok fallback — a malformed table entry reads as the heuristic default
        except ValueError:
            pass
    return _MEGA_CROSSOVER_DEFAULT


def advance_group(entries: Sequence[tuple],
                  should_abort: Optional[Any] = None,
                  force: bool = False,
                  overlap_fn: Optional[Any] = None
                  ) -> List[Dict[str, Any]]:
    """Advance one append block on EACH member session of a
    same-geometry mega-group through ONE batched frontier walk.

    ``entries`` is a list of ``(session, ops, seq)`` — at most one
    block per session per call (the dispatcher waves sessions with
    several queued blocks). Returns the per-member append verdicts,
    aligned with ``entries``; a member that raced a close completes
    with the dispatcher's ``closed`` verdict shape instead of
    raising, so one straggler cannot abort the group.

    Member isolation: a member whose device path dies falls THAT
    session to its permanent host monitor (the ordinary
    exactly-one-``session-advance``-fallback ladder) while the rest
    of the group completes. A failure of the batched launch itself
    records ONE ``session-mega`` obs fallback and re-advances every
    staged member solo on its already-staged operands (the monitor
    drains are consumed — re-walking the same operands is the only
    sound retry).

    Lock order: member locks are acquired in list order and held
    across stage -> launch -> commit. The coalescer keeps a session
    in at most one in-flight group and no other code path acquires
    two session locks, so the ordering cannot deadlock.

    ``force=True`` bypasses the persisted crossover width and always
    takes the batched path (the bench probe measures mega-vs-solo at
    every width; honoring a previously recorded crossover there would
    silently re-measure solo-vs-solo).

    ``overlap_fn`` (ISSUE 20: the mega path's stage/collect overlap
    window) runs between the batched LAUNCH and its fetch — host
    bookkeeping the dispatcher would otherwise serialize behind the
    walk (the next wave's stamps/ledger) executes while the device
    walks this wave; its wall lands in ``pipeline.overlap_s``. It is
    best-effort: a crash inside it is contained (the wave still
    collects), and it is NOT called when the group takes the
    per-session path."""
    results: List[Optional[Dict[str, Any]]] = [None] * len(entries)
    if not entries:
        return []
    geom = entries[0][0].mega_sig()
    if geom is None or (not force
                        and len(entries) < mega_crossover()):
        # below the measured crossover (or a signature that went
        # stale between selection and dispatch): per-session wins
        return [s.advance_block(o, seq=q, should_abort=should_abort)
                for s, o, q in entries]
    with contextlib.ExitStack() as stack:
        for s, _o, _q in entries:
            stack.enter_context(s.lock)
        staged: List[tuple] = []                # (idx, sess, st)
        for k, (sess, ops, seq) in enumerate(entries):
            try:
                if sess.mega_sig() != geom:
                    # cached-signature drift since selection (close /
                    # fallback / regrowth raced the queue): solo path
                    results[k] = sess.advance_block(
                        ops, seq=seq, should_abort=should_abort)
                    continue
                kind, payload = sess._stage_block(ops, seq,
                                                  should_abort, geom)
            # jtlint: ok fallback — append/close member race: the member gets a 'closed' verdict
            except SessionClosed as e:
                results[k] = {"valid": "unknown", "cause": "closed",
                              "error": str(e)}
                continue
            if kind == "done":
                results[k] = payload
            else:
                staged.append((k, sess, payload))
        if staged:
            t0 = time.monotonic()
            obs.count("serve.session.mega.groups")
            obs.count("serve.session.mega.lanes", len(staged))
            deads = None
            inf = None
            try:
                inf = reach_word.launch_frontiers_mega(
                    [st[0] for _k, _s, st in staged],
                    [(st[1], st[2]) for _k, _s, st in staged])
            except Exception as e:                      # noqa: BLE001
                # the batched launch died as a whole: ONE session-mega
                # record; every staged member re-advances solo below
                obs.engine_fallback("session-mega", type(e).__name__,
                                    lanes=len(staged))
            if overlap_fn is not None and inf is not None:
                t_ov = time.monotonic()
                try:
                    overlap_fn()
                # jtlint: ok fallback — the overlap window is best-effort host bookkeeping; the wave's collect must not die for it
                except Exception as e:                  # noqa: BLE001
                    obs.checker_swallowed("session-mega-overlap",
                                          type(e).__name__)
                obs.count("pipeline.overlap_s",
                          time.monotonic() - t_ov)
            if inf is not None:
                try:
                    deads = reach_word.collect_frontiers_mega(inf)
                except Exception as e:                  # noqa: BLE001
                    # the batched FETCH died (async dispatch surfaces
                    # walk errors at first consumption): the same ONE
                    # session-mega record + per-member solo re-advance
                    obs.engine_fallback("session-mega",
                                        type(e).__name__,
                                        lanes=len(staged),
                                        collect=True)
            for j, (k, sess, st) in enumerate(staged):
                ops_k, seq_k = entries[k][1], entries[k][2]
                try:
                    dead = deads[j] if deads is not None \
                        else st[0].advance(st[1], st[2])
                    v = sess._eng.commit_advance(st, dead)
                    results[k] = sess._finish_block(len(ops_k), seq_k,
                                                    v)
                except Exception as e:                  # noqa: BLE001
                    sess._to_host_monitor(record_fallback=True, exc=e)
                    results[k] = sess._finish_block(len(ops_k), seq_k,
                                                    sess.violation)
            obs.count("serve.session.mega.scatter_s",
                      time.monotonic() - t0)
    return results


class SessionRegistry:
    """id -> session lookup + the open-session census ``/stats`` and
    the ``/engine`` dashboard render. Closed sessions are retained
    FIFO-bounded (their close result stays queryable without letting
    a long-lived daemon leak one session at a time); the open-session
    count is bounded by refusing opens past ``max_open``."""

    # jtlint lock discipline (see Session above)
    _GUARDED_BY = ("_by_id", "_closed_order")

    def __init__(self, max_open: int = 1024,
                 keep_closed: int = 256,
                 tenant_max_open: int = 64,
                 idle_ttl_s: Optional[float] = None) -> None:
        self._lock = threading.Lock()
        self._by_id: "OrderedDict[str, Session]" = OrderedDict()
        self._closed_order: "deque[str]" = deque()
        self._max_open = max_open
        self._keep_closed = keep_closed
        self.tenant_max_open = tenant_max_open
        self.idle_ttl_s = idle_ttl_s

    def add(self, sess: Session) -> None:
        with self._lock:
            n_open = 0
            n_tenant = 0
            for s in self._by_id.values():
                if not s.closed:
                    n_open += 1
                    if s.tenant == sess.tenant:
                        n_tenant += 1
            if n_open >= self._max_open:
                raise RuntimeError(
                    f"open-session bound reached ({self._max_open})")
            if (self.tenant_max_open
                    and n_tenant >= self.tenant_max_open):
                # one tenant must not exhaust the global bound for
                # everyone else (the fairness discipline the one-shot
                # queue already has, applied to long-lived sessions)
                obs.count("serve.session.tenant_cap")
                raise TenantSessionCap(
                    f"tenant {sess.tenant!r} open-session cap "
                    f"reached ({self.tenant_max_open})")
            self._by_id[sess.id] = sess
        obs.count("serve.session.opened")
        self._gauge()

    def idle_open(self, ttl_s: float) -> List[Session]:
        """Open sessions whose last append is more than ``ttl_s``
        seconds ago (the daemon sweeper force-closes these)."""
        now = time.monotonic()
        with self._lock:
            return [s for s in self._by_id.values()
                    if not s.closed
                    and now - s.last_active_mono > ttl_s]

    def get(self, sid: str) -> Optional[Session]:
        with self._lock:
            return self._by_id.get(sid)

    def mark_closed(self, sess: Session) -> None:
        with self._lock:
            self._closed_order.append(sess.id)
            while len(self._closed_order) > self._keep_closed:
                old = self._closed_order.popleft()
                s = self._by_id.get(old)
                if s is not None and s.closed:
                    self._by_id.pop(old, None)
        self._gauge()

    def _gauge(self) -> None:
        obs.gauge("serve.session.open", self.open_count())

    def open_count(self) -> int:
        with self._lock:
            return sum(1 for s in self._by_id.values()
                       if not s.closed)

    def census(self) -> Dict[str, Any]:
        """The /stats + /engine view: open count, oldest open age,
        per-tenant open counts, total appends/ops across live
        sessions."""
        now = time.monotonic()
        with self._lock:
            open_s = [s for s in self._by_id.values() if not s.closed]
            per_tenant: Dict[str, int] = {}
            for s in open_s:
                per_tenant[s.tenant] = per_tenant.get(s.tenant, 0) + 1
            return {
                "open": len(open_s),
                "closed": len(self._closed_order),
                "oldest-age-s": (round(max(
                    now - s.created_mono for s in open_s), 3)
                    if open_s else None),
                "per-tenant": per_tenant,
                "appends": sum(s.appends for s in open_s),
                "ops": sum(s.ops_total for s in open_s),
                "tenant-cap": self.tenant_max_open,
                "idle-ttl-s": self.idle_ttl_s,
                "oldest-idle-s": (round(max(
                    now - s.last_active_mono for s in open_s), 3)
                    if open_s else None),
            }
