"""The wire protocol: stdlib ``ThreadingHTTPServer``, no dependencies
(matching ``web.py``'s style). Three routes:

- ``POST /check`` — submit a history. JSON body::

      {"model": "cas-register",            # models.<name> constructor
       "history": [{"process":0,"type":"invoke","f":"read"}, ...],
       "tenant": "team-a",                 # or X-Tenant header
       "timeout-s": 30.0,                  # optional deadline
       "idempotency-key": "job-17",        # optional dedup key:
                                           # duplicate POSTs return
                                           # the ORIGINAL id (the
                                           # window survives restarts
                                           # via the journal)
       "options": {"max_states": 100000}}  # engine kw (allow-listed)

  ``Content-Type: application/edn`` parses the SAME shape from EDN
  (an upstream Jepsen ``history.edn`` pasted as the ``:history``
  value works). Replies ``202 {"id": ..., "status": "queued"}``,
  ``400`` on malformed input, ``429`` + ``Retry-After`` under
  backpressure.
- ``GET /check/<id>`` — status/result. ``result`` carries the full
  checker verdict (witness included) once ``status`` is terminal,
  plus the stage ``waterfall`` (admit→coalesce→walk→publish), the
  stitched dispatcher ``trace``, and the request's attributed
  ``device-s``. A quarantined request (the isolated poison member of
  a crashed dispatch group) answers a structured **500**. Verdicts
  published just before a crash answer from the journal's completion
  marker after restart. ``DELETE /check/<id>`` cancels a queued
  request (journal-only entries get their cancelled marker, so a
  restart cannot resurrect them).
- ``GET /stats`` — queue depths, per-tenant ledger counts, cache
  counters, per-geometry dispatch counts, latency-histogram digests,
  breaker/journal state, and the rolling time-series ring.
  ``GET /healthz`` — liveness + degradation (breaker state, journal
  backlog).
- ``GET /metrics`` — Prometheus text exposition (every counter,
  numeric gauge, and latency histogram with ``_bucket``/``_sum``/
  ``_count`` series; scrape-ready).
- ``POST /profile`` — ``{"dispatches": N}`` arms ``jax.profiler``
  around the next N dispatches; the capture persists under
  ``<store-root>/serve/profile-<ts>/``.
- ``POST /session`` — open a streaming check session (long-lived
  check, device-resident carried frontier);
  ``POST /session/<id>/append`` ships one event block and returns
  the incremental verdict + tail-alarm status synchronously (202 +
  request id past ``wait-s``); ``POST /session/<id>/close``
  resolves the tail and returns the exact final verdict + witness
  (differential-identical to the one-shot chain);
  ``GET /session/<id>`` is the status view. Opens and appends are
  journaled before their acknowledgement, so sessions ride a
  SIGKILL: replay re-derives the frontier under the original id.

Fleet mode (``replica_id=``): N daemons share ONE journal root.
Every admitted request and open session carries a lease (replica id
+ wall-clock expiry) in the journal; a replica only dispatches work
it holds the lease on, so the same entry is never double-dispatched.
A background scan (every ``lease_ttl_s / 3``) renews the replica's
own leases and adopts work whose holder stopped renewing — a
SIGKILL'd replica's claims expire and drain through the survivors.
Any replica answers ``GET /check/<id>`` (done markers live in the
shared journal); duplicate POSTs dedup across replicas through the
shared idempotency index. Sessions are PINNED to their claiming
replica (the carried frontier is device state): an append landing on
the wrong replica answers 409 with the pin while the lease is live,
and adopts the session by journal replay once it expires.
"""
from __future__ import annotations

import json
import logging
import threading
import time
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple

import numpy as np

from jepsen_tpu import edn
from jepsen_tpu import history as h
from jepsen_tpu import obs
from jepsen_tpu.op import Op
from jepsen_tpu.serve import faults, recovery
from jepsen_tpu.serve import journal as jr
from jepsen_tpu.serve import request as rq
from jepsen_tpu.serve import session as sn
from jepsen_tpu.serve.coalesce import AdmissionQueue, Backpressure
from jepsen_tpu.serve.engine import Dispatcher

log = logging.getLogger("jepsen.serve")

# engine options a client may set per request — bounded to the knobs
# that cannot destabilize co-tenants (no devices=, no interpret=)
_CLIENT_OPTS = ("max_states", "max_slots", "max_dense", "time_limit",
                "max_dense_txns", "consistency")


def _filter_opts(raw: Any, strict: bool = True) -> Dict[str, Any]:
    """Allow-list client options and canonicalize ``"consistency"``
    to the sorted tuple-of-levels form (so every spelling of the same
    level set coalesces into the same signature). ``strict`` raises
    on an unknown level (the admission path's 400); the replay paths
    pass False — an invalid value cannot have been admitted, so it is
    dropped rather than wedging the replay loop."""
    opts = {k: v for k, v in (raw or {}).items() if k in _CLIENT_OPTS}
    if "consistency" in opts:
        from jepsen_tpu.txn import lattice
        try:
            opts["consistency"] = list(
                lattice.canon_levels(opts["consistency"]))
        except ValueError:
            if strict:
                raise
            obs.decision("serve-opts", "drop", cause="bad-consistency")
            del opts["consistency"]
    return opts

_MODEL_NAMES = ("register", "cas-register", "mutex", "multi-register",
                "set-model", "fifo-queue", "unordered-queue",
                "noop-model", "txn-list-append")


def resolve_model(name: str):
    """Model name -> fresh model instance (the CLI's vocabulary:
    ``cas-register`` -> ``models.cas_register()``). The transactional
    marker ``txn-list-append`` routes its dispatch groups through
    ``facade.auto_check_txn`` instead of the linearizable engines —
    and, because the model type is part of the coalescing signature,
    txn requests coalesce into their own groups by construction."""
    if name == "txn-list-append":
        from jepsen_tpu.txn import ops as txn_ops
        return txn_ops.list_append_model()
    from jepsen_tpu import models
    if name not in _MODEL_NAMES:
        raise ValueError(f"unknown model {name!r}; "
                         f"have {list(_MODEL_NAMES)}")
    return getattr(models, name.replace("-", "_"))()


def parse_check_body(body: bytes, content_type: str,
                     default_tenant: str = "anonymous"
                     ) -> Tuple[str, str, list, Dict[str, Any],
                                Optional[float], Optional[str]]:
    """Decode a POST /check body -> (tenant, model_name, ops,
    options, timeout_s, idempotency_key). Raises ValueError on
    malformed input."""
    text = body.decode("utf-8")
    if "edn" in (content_type or ""):
        vals = edn.loads_all(text)
        if len(vals) != 1:
            raise ValueError("expected one EDN map")
        data = edn.to_plain(vals[0])
    else:
        data = json.loads(text)
    if not isinstance(data, dict):
        raise ValueError("body must be a map")
    raw_hist = data.get("history")
    if not isinstance(raw_hist, list) or not raw_hist:
        raise ValueError("'history' must be a non-empty list of ops")
    ops = [Op.from_dict(edn.to_plain(d) if not isinstance(d, dict)
                        else d) for d in raw_hist]
    if ops and ops[0].index < 0:
        ops = h.index(ops)
    model_name = str(data.get("model", "cas-register"))
    # tenant names are client-controlled and key bounded per-tenant
    # state: cap the length here, cardinality in the registry
    tenant = str(data.get("tenant") or default_tenant)[:64]
    options = _filter_opts(data.get("options"))
    timeout_s = data.get("timeout-s", data.get("timeout_s"))
    if timeout_s is not None:
        timeout_s = float(timeout_s)
        if timeout_s <= 0:
            raise ValueError("'timeout-s' must be positive")
    # client-supplied idempotency key: duplicate POSTs with the same
    # key dedup to the original request id (bounded-length, like
    # tenant names — it keys bounded daemon state)
    idem = data.get("idempotency-key", data.get("idempotency_key"))
    idem = str(idem)[:128] if idem is not None else None
    return tenant, model_name, ops, options, timeout_s, idem


class _Server(ThreadingHTTPServer):
    """The stdlib threading server with a listen backlog sized for
    burst arrivals: the default 5 drops (RST) concurrent connects the
    accept loop has not reached yet, which a thousand-session open
    wave hits immediately. The backlog is pending CONNECTS only —
    admission backpressure still bounds accepted work."""
    request_queue_size = 128


class Daemon:
    """Everything the serving layer owns: registry, admission queue,
    dispatcher thread, HTTP server. ``start()`` returns after the
    socket is listening; ``shutdown()`` is graceful — stops admitting,
    drains in-flight work, then stops the dispatcher.

    Binds LOOPBACK by default: unlike the read-only results browser,
    this endpoint accepts work (unauthenticated compute + store
    writes) — exposing it (``host="0.0.0.0"``) is a deliberate act."""

    def __init__(self, *, port: int = 8642, host: str = "127.0.0.1",
                 queue_depth: int = 256,
                 max_inflight_per_tenant: int = 8,
                 group: int = 32,
                 engine_kw: Optional[Dict[str, Any]] = None,
                 store_root: Optional[str] = None,
                 persist: bool = False,
                 max_body_bytes: int = 32 << 20,
                 journal: bool = True,
                 journal_keep_terminal: int = 256,
                 retry_policy: Optional[recovery.RetryPolicy] = None,
                 breaker: Optional[recovery.CircuitBreaker] = None,
                 dispatch_deadline_s: Optional[float] = None,
                 session_tenant_cap: int = 64,
                 session_idle_ttl_s: Optional[float] = 3600.0,
                 lanes: int = 1,
                 replica_id: Optional[str] = None,
                 lease_ttl_s: float = 10.0) -> None:
        # the queue bounds request COUNT; this bounds request BYTES —
        # both are needed for "backpressure, never OOM": worst-case
        # queued history memory is queue_depth * max_body_bytes-ish
        self.max_body_bytes = int(max_body_bytes)
        # self-nemesis faults arm from the environment here so a
        # chaos-harness daemon subprocess carries its fault schedule
        faults.arm_from_env()
        self.registry = rq.Registry()
        self.queue = AdmissionQueue(
            max_depth=queue_depth,
            max_inflight_per_tenant=max_inflight_per_tenant,
            group=group, lanes=lanes)
        # durable admission journal (WAL): admitted requests are
        # journaled before their 202 and replayed on restart — only
        # with a store root (durability needs somewhere durable)
        self.journal: Optional[jr.Journal] = None
        if journal and store_root is not None:
            from jepsen_tpu import store
            self.journal = jr.Journal(
                store.serve_journal_dir(store_root),
                keep_terminal=journal_keep_terminal)
        # fleet mode: several replicas over one journal root, work
        # partitioned by per-entry lease. A replica id without a
        # journal would be a fleet with no shared state to fleet over.
        self.replica_id = str(replica_id) if replica_id else None
        self.lease_ttl_s = float(lease_ttl_s)
        self.fleet = (self.replica_id is not None
                      and self.journal is not None)
        self._fleet_stop = threading.Event()
        self._fleet_thread: Optional[threading.Thread] = None
        # pod mode: a multi-host (jax.distributed) daemon is ONE fleet
        # replica — rank 0 owns the lease and the HTTP socket; ranks
        # > 0 are compute peers (run_compute_peer, never a Daemon).
        # process_info degrades to (0, 1) single-process, so this is
        # dormant off-pod.
        try:
            from jepsen_tpu.parallel import distributed
            self.rank, self.n_ranks = distributed.process_info()
        # jtlint: ok fallback — capability probe: no jax on the protocol-only path, single-process roles
        except Exception:                               # noqa: BLE001
            self.rank, self.n_ranks = 0, 1
        if self.n_ranks > 1:
            obs.gauge("dist.processes", self.n_ranks)
            obs.gauge("dist.rank", self.rank)
            if self.journal is not None:
                # the lease payload carries the pod shape: a sibling
                # replica inspecting the lease sees it fronts n ranks
                self.journal.lease_meta = {"ranks": self.n_ranks}
        # (tenant, idempotency key) -> request id (bounded; seeded
        # from the journal so the dedup window survives restarts;
        # tenant-scoped so one tenant's key cannot map onto — or leak
        # the status of — another tenant's request)
        self._idem_lock = threading.Lock()
        self._idem: "OrderedDict[Any, str]" = OrderedDict()
        # ids whose admission is IN FLIGHT on some HTTP worker thread:
        # a concurrent duplicate that hits the index before the winner
        # finishes journaling must dedup to the winner, not race past
        # it (check-then-act would admit both)
        self._admitting: set = set()
        if self.journal is not None:
            self._idem.update(self.journal.idempotency_index())
        # the coalescer's group width rides into the engine-side
        # re-plan (facade filters it to check_many's `group=`): both
        # planners must agree on the dispatch width or the admission
        # bucketing would be re-split downstream
        ekw = {"group": group}
        ekw.update(engine_kw or {})
        self.dispatcher = Dispatcher(self.queue, self.registry,
                                     engine_kw=ekw,
                                     store_root=store_root,
                                     persist=persist,
                                     retry_policy=retry_policy,
                                     breaker=breaker,
                                     dispatch_deadline_s=
                                     dispatch_deadline_s,
                                     journal=self.journal,
                                     lanes=lanes)
        if self.journal is not None:
            # every terminal transition — dispatcher publish, queued
            # timeout, cancel — marks the WAL entry complete, so a
            # restart never resurrects finished (or cancelled) work
            # (and, in fleet mode, frees the lease for the verdict's
            # entry — the done marker now answers for it everywhere)
            jnl = self.journal

            def _on_terminal(req: "rq.CheckRequest") -> None:
                jnl.finish(req.id, req.status, req.result)
                if self.fleet:
                    jnl.release(req.id, self.replica_id)

            self.registry.on_terminal = _on_terminal
        # streaming check sessions: long-lived checks whose carried
        # frontier the dispatcher advances per append block. Bounded
        # three ways: globally (max_open), per tenant (one tenant
        # must not exhaust the global bound), and in time (an open
        # session idle past the TTL is force-closed by the sweeper —
        # an abandoned session pins device state forever otherwise)
        self.sessions = sn.SessionRegistry(
            tenant_max_open=session_tenant_cap,
            idle_ttl_s=session_idle_ttl_s)
        self.dispatcher.sessions = self.sessions
        self._sweeper: Optional[threading.Thread] = None
        self._sweeper_stop = threading.Event()
        handler = type("Handler", (_Handler,), {"daemon_ref": self})
        self.httpd = _Server((host, port), handler)
        self._serve_thread: Optional[threading.Thread] = None
        self.accepting = True

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def start(self, *, dispatch: bool = True) -> "Daemon":
        """``dispatch=False`` starts only the HTTP side — protocol
        tests exercise admission/backpressure without a device
        engine behind the queue."""
        from jepsen_tpu import envcheck
        envcheck.check_once()       # typo'd opt-outs warn, not no-op
        if dispatch:
            self.dispatcher.start()
            self.replay_journal()
            self.replay_sessions()
            self._start_sweeper()
            self._start_fleet_scan()
            self._pod_up()
        self._serve_thread = threading.Thread(
            target=self.httpd.serve_forever, name="serve-http",
            daemon=True)
        self._serve_thread.start()
        return self

    def serve_forever(self) -> None:
        """Foreground mode (the CLI): blocks until interrupted, then
        shuts down gracefully."""
        self.dispatcher.start()
        self.replay_journal()
        self.replay_sessions()
        self._start_sweeper()
        self._start_fleet_scan()
        self._pod_up()
        try:
            self.httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            self.shutdown()

    def _pod_up(self) -> None:
        """Rank 0 of a pod: turn on driver mode (multi-host walks ship
        their operands to the compute peers) and run the warmup ping —
        one tiny word payload through the work channel and the DCN
        gather, proving every peer answers collectives BEFORE real
        checks ride on them. A failed warmup turns driver mode back
        off: the daemon serves single-host rather than paying a gather
        timeout per check against a torn pod."""
        if self.n_ranks <= 1 or self.rank != 0:
            return
        from jepsen_tpu.parallel import distributed
        distributed.set_driver(True)
        ping = np.arange(32, dtype=np.uint32).reshape(1, 32)
        try:
            with distributed.driver_lock():
                distributed.send_work(
                    {"op": "gather-ping", "words": ping},
                    timeout_s=distributed.gather_timeout_s())
                out = distributed.ChunkShard.detect().gather(ping)
            if out.shape[0] != self.n_ranks:
                raise RuntimeError(
                    f"warmup gathered {out.shape[0]}/{self.n_ranks}")
            obs.count("dist.warmup_ok")
            log.info("pod warmup: %d ranks answered", self.n_ranks)
        except Exception as e:                          # noqa: BLE001
            distributed.set_driver(False)
            obs.count("dist.warmup_failed")
            log.warning("pod warmup failed (%r): serving single-host",
                        e)

    def shutdown(self, drain_timeout: float = 30.0) -> bool:
        self.accepting = False
        if self.n_ranks > 1 and self.rank == 0:
            # release the compute peers (best-effort: a torn pod's
            # peers die by signal instead)
            from jepsen_tpu.parallel import distributed
            if distributed.driver_mode():
                try:
                    with distributed.driver_lock():
                        distributed.send_work({"op": "shutdown"},
                                              timeout_s=10.0)
                # jtlint: ok fallback — best-effort peer release on shutdown; peers also die by signal
                except Exception:                       # noqa: BLE001
                    pass
                distributed.set_driver(False)
        self._sweeper_stop.set()
        self._fleet_stop.set()
        if self._fleet_thread is not None:
            self._fleet_thread.join(5.0)
        drained = self.dispatcher.drain(timeout=drain_timeout)
        self.dispatcher.stop()
        if self._serve_thread is not None:
            # BaseServer.shutdown() handshakes with the serve loop; on
            # a daemon whose HTTP side never started (replay-only
            # tests, failed startups) it would wait forever
            self.httpd.shutdown()
        self.httpd.server_close()
        if self._serve_thread is not None:
            self._serve_thread.join(5.0)
        self.dispatcher._write_stats_file()
        return drained

    # -- journal replay --------------------------------------------------
    def replay_journal(self) -> int:
        """Feed unfinished journal entries back through the admission
        queue under their ORIGINAL ids (restart recovery). Deadlines
        re-derive from the wall clock: a deadline that passed while
        the daemon was dead replays as an immediate timeout. A corrupt
        entry is quarantined (marked terminal with a structured
        error), never looped on. Returns how many entries replayed."""
        if self.journal is None:
            return 0
        n = 0
        for rid in self.journal.pending_ids():
            if self.registry.get(rid) is not None:
                # already live HERE (double replay call / fleet-scan
                # revisit): in fleet mode, renew the lease so sibling
                # scans keep seeing a live holder
                if self.fleet:
                    self.journal.claim(rid, replica=self.replica_id,
                                       ttl_s=self.lease_ttl_s)
                continue
            if self.fleet and not self.journal.claim(
                    rid, replica=self.replica_id,
                    ttl_s=self.lease_ttl_s):
                continue    # a sibling's live lease: its work, not ours
            entry = self.journal.load_entry(rid)
            try:
                if entry is None:
                    raise ValueError("unreadable journal entry")
                ops = jr.history_from_edn(entry["history-edn"])
                if not ops:
                    raise ValueError("empty journaled history")
                if ops[0].index < 0:
                    ops = h.index(ops)
                model = resolve_model(str(entry["model"]))
                packed = h.pack(ops)
            except Exception as e:                      # noqa: BLE001
                log.warning("journal entry %s unreplayable: %s",
                            rid, e)
                obs.engine_fallback("serve-journal",
                                    type(e).__name__, id=rid,
                                    replay=True)
                self.journal.finish(
                    rid, rq.QUARANTINED,
                    {"valid": "unknown", "quarantined": True,
                     "cause": "journal-corrupt",
                     "error": f"{type(e).__name__}: {e}"})
                continue
            deadline = None
            timeout_s = entry.get("timeout-s")
            if timeout_s:
                elapsed = time.time() - float(
                    entry.get("submitted-at") or time.time())
                deadline = time.monotonic() \
                    + max(0.0, float(timeout_s) - elapsed)
            opts = _filter_opts(entry.get("options"), strict=False)
            req = rq.CheckRequest(
                id=rid, tenant=str(entry.get("tenant") or "anonymous"),
                model_name=str(entry["model"]), model=model,
                packed=packed, history=ops, n_ops=int(packed.n),
                opts=opts, deadline=deadline,
                idem_key=entry.get("idempotency-key"),
                journaled=True)
            self.registry.add(req)
            # force past the depth bound: this work was ALREADY
            # admitted (its 202 is in a client's hands)
            self.queue.submit(req, force=True)
            self.registry.ledger_record(req.tenant, "replayed",
                                        id=rid, ops=int(packed.n))
            obs.count("serve.journal.replayed")
            # (the dedup index already carries this entry's key:
            # __init__ seeds it from journal.idempotency_index())
            n += 1
        if n:
            log.info("journal replay: %d request(s) readmitted", n)
        return n

    def replay_sessions(self) -> int:
        """Re-create every open (unclosed) journaled session and
        replay its append blocks in seq order THROUGH THE ENGINE —
        the carried frontier re-derives deterministically from the
        stream, so a session rides a SIGKILL keeping its id, its seq
        counter, and its verdict. Corrupt session metadata gets a
        structured close marker (quarantine analog), never a loop."""
        if self.journal is None:
            return 0
        n = 0
        for sid in self.journal.open_session_ids():
            if self.sessions.get(sid) is not None:
                # live here: renew the pin so siblings 409 appends to
                # this session instead of adopting it out from under
                # its device-resident frontier
                if self.fleet:
                    self.journal.claim(sid, replica=self.replica_id,
                                       ttl_s=self.lease_ttl_s)
                continue
            if self.fleet and not self.journal.claim(
                    sid, replica=self.replica_id,
                    ttl_s=self.lease_ttl_s):
                continue    # pinned to a live sibling
            if self._replay_one_session(sid):
                n += 1
        if n:
            log.info("session replay: %d session(s) re-derived", n)
        return n

    def _replay_one_session(self, sid: str) -> bool:
        """Rebuild ONE journaled session through the engine (boot
        replay and fleet adoption share this path — a session always
        re-derives from its durable stream, never from copied state).
        Returns whether a live session came out of it."""
        meta = self.journal.load_session(sid)
        try:
            if meta is None:
                raise ValueError("unreadable session entry")
            model_name = str(meta["model"])
            model = resolve_model(model_name)
            opts = _filter_opts(meta.get("options"), strict=False)
        except Exception as e:                          # noqa: BLE001
            log.warning("session %s unreplayable: %s", sid, e)
            obs.engine_fallback("serve-journal",
                                type(e).__name__, session=sid,
                                replay=True)
            self.journal.session_close_marker(
                sid, {"valid": "unknown",
                      "cause": "session-journal-corrupt",
                      "error": f"{type(e).__name__}: {e}"})
            return False
        sess = sn.Session(
            sid, str(meta.get("tenant") or "anonymous"),
            model_name, model, opts)
        blocks = self.journal.session_appends(sid)
        for seq, entry in blocks:
            if seq != sess.seq + 1:
                # a seq GAP (missing/unreadable block file):
                # replay TRUNCATES here — advancing past the hole
                # would derive a frontier from a stream missing a
                # block AND falsely dedup the client's retry of
                # it. The client's retries re-apply from the
                # truncation point.
                obs.engine_fallback("serve-journal", "SeqGap",
                                    session=sid, seq=seq,
                                    expected=sess.seq + 1)
                break
            try:
                ops = jr.history_from_edn(entry["history-edn"])
                sess.advance_block(ops, seq=seq)
            except Exception as e:                      # noqa: BLE001
                # a torn block was never acknowledged: stop HERE
                # (same truncation argument — sess.seq must not
                # move past an unapplied block)
                obs.engine_fallback("serve-journal",
                                    type(e).__name__, session=sid,
                                    seq=seq)
                break
            sess.seq = seq
            sess.replayed += 1
        # the replayed stream counts as activity: a session must
        # not be swept as idle the instant its daemon restarts
        sess.last_active_mono = time.monotonic()
        try:
            self.sessions.add(sess)
        except RuntimeError as e:
            # past the open-session bound: leave the session
            # journaled (a later restart, after closes/GC, can
            # still replay it) — a full registry must degrade a
            # session, never abort the daemon's boot
            log.warning("session %s not replayed: %s", sid, e)
            obs.engine_fallback("serve-journal", "SessionBound",
                                session=sid, replay=True)
            return False
        self.registry.ledger_record(sess.tenant,
                                    "session-replayed",
                                    session=sid,
                                    appends=len(blocks))
        obs.count("serve.session.replayed")
        return True

    # -- idle-session sweeper --------------------------------------------
    def _start_sweeper(self) -> None:
        """Background idle-TTL sweep: an abandoned open session pins
        its carried device state (frontier buffer / closure masks)
        and a tenant-cap slot forever; the sweeper force-closes
        sessions idle past the TTL through the ordinary close path
        (exact verdict, journal close marker — a replayed daemon will
        not resurrect them)."""
        ttl = self.sessions.idle_ttl_s
        if not ttl or self._sweeper is not None:
            return
        interval = max(1.0, min(30.0, float(ttl) / 4.0))

        def _sweep_loop() -> None:
            while not self._sweeper_stop.wait(interval):
                try:
                    self.expire_idle_sessions()
                # jtlint: ok fallback — sweep failures retry next tick; evictions are counted
                except Exception:                       # noqa: BLE001
                    log.exception("idle-session sweep failed")

        self._sweeper = threading.Thread(
            target=_sweep_loop, name="serve-session-sweeper",
            daemon=True)
        self._sweeper.start()

    def expire_idle_sessions(self) -> int:
        """Force-close open sessions idle past the registry TTL
        (``serve.session.evicted_idle`` per eviction). Returns how
        many closes were initiated."""
        ttl = self.sessions.idle_ttl_s
        if not ttl:
            return 0
        n = 0
        for sess in self.sessions.idle_open(float(ttl)):
            idle_s = round(time.monotonic() - sess.last_active_mono, 3)
            obs.count("serve.session.evicted_idle")
            self.registry.ledger_record(
                sess.tenant, "session-evicted-idle",
                session=sess.id, idle_s=idle_s)
            log.info("session %s idle %.1fs > ttl %.1fs: force-close",
                     sess.id, idle_s, ttl)
            code, payload = self.session_close(sess.id)
            if code in (200, 202):
                n += 1
        return n

    # -- fleet scan (renew own leases, adopt expired ones) ---------------
    def _start_fleet_scan(self) -> None:
        """Background lease maintenance, fleet mode only. Every
        ``lease_ttl_s / 3`` (a renew cadence that survives two missed
        ticks before the lease lapses) the replica re-runs the replay
        paths: for work it already holds that is a lease RENEWAL; for
        pending entries whose holder stopped renewing — a SIGKILL'd
        sibling — the claim STEALS the expired lease and the entry
        replays here. That single mechanism is both heartbeat and
        failover: no separate membership protocol."""
        if not self.fleet or self._fleet_thread is not None:
            return
        interval = max(0.2, self.lease_ttl_s / 3.0)

        def _scan_loop() -> None:
            while not self._fleet_stop.wait(interval):
                try:
                    self.fleet_scan()
                # jtlint: ok fallback — a failed scan retries next tick; leases it missed renewing are re-claimable, never lost
                except Exception:                       # noqa: BLE001
                    log.exception("fleet scan failed")

        self._fleet_thread = threading.Thread(
            target=_scan_loop, name="serve-fleet-scan", daemon=True)
        self._fleet_thread.start()

    def fleet_scan(self) -> Tuple[int, int]:
        """One renew-and-adopt pass (exposed for tests: deterministic
        lease handoff without waiting on the scan thread). Returns
        (requests adopted, sessions adopted)."""
        return self.replay_journal(), self.replay_sessions()

    # -- streaming sessions (called from HTTP worker threads) ------------
    def session_open(self, body: bytes, content_type: str,
                     header_tenant: Optional[str]) -> Tuple[int, Dict]:
        if not self.accepting:
            return 503, {"error": "shutting down"}
        try:
            text = body.decode("utf-8") if body else "{}"
            if "edn" in (content_type or ""):
                vals = edn.loads_all(text)
                data = edn.to_plain(vals[0]) if vals else {}
            else:
                data = json.loads(text) if text.strip() else {}
            if not isinstance(data, dict):
                raise ValueError("body must be a map")
            model_name = str(data.get("model", "cas-register"))
            model = resolve_model(model_name)
            tenant = str(data.get("tenant") or header_tenant
                         or "anonymous")[:64]
            options = _filter_opts(data.get("options"))
        except Exception as e:                          # noqa: BLE001
            return 400, {"error": f"{type(e).__name__}: {e}"}
        sid = sn.new_session_id()
        if self.journal is not None:
            try:
                # durable BEFORE the id is returned: the journaled
                # appends need a session entry to replay into
                self.journal.session_open(sid, tenant=tenant,
                                          model_name=model_name,
                                          options=options)
            except OSError as e:
                obs.engine_fallback("serve-journal",
                                    type(e).__name__, session=sid)
                return 500, {"error": f"journal write failed: {e}"}
            if self.fleet:
                # pin the session HERE before the id is returned: a
                # sibling's scan racing this open must see the pin,
                # not adopt a session whose opener is mid-reply
                self.journal.claim(sid, replica=self.replica_id,
                                   ttl_s=self.lease_ttl_s)
        sess = sn.Session(sid, tenant, model_name, model, options)
        try:
            self.sessions.add(sess)
        except sn.TenantSessionCap as e:
            if self.journal is not None:
                self.journal.discard_session(sid)
            return 429, {"error": str(e), "cause": "tenant-cap",
                         "retry-after-s": 1.0}
        except RuntimeError as e:
            if self.journal is not None:
                self.journal.discard_session(sid)
            return 429, {"error": str(e), "retry-after-s": 1.0}
        self.registry.ledger_record(tenant, "session-opened",
                                    session=sid, model=model_name)
        out = {"session": sid, "status": "open",
               "tenant": tenant, "model": model_name,
               "engine": sess.engine_name}
        if self.fleet:
            out["pinned-to"] = self.replica_id
        return 201, out

    def _parse_append(self, body: bytes, content_type: str
                      ) -> Tuple[list, Optional[int], Optional[float],
                                 float]:
        text = body.decode("utf-8")
        if "edn" in (content_type or ""):
            vals = edn.loads_all(text)
            if len(vals) != 1:
                raise ValueError("expected one EDN map")
            data = edn.to_plain(vals[0])
        else:
            data = json.loads(text)
        if not isinstance(data, dict):
            raise ValueError("body must be a map")
        raw = data.get("history")
        if not isinstance(raw, list) or not raw:
            raise ValueError("'history' must be a non-empty list of "
                             "ops")
        ops = [Op.from_dict(edn.to_plain(d) if not isinstance(d, dict)
                            else d) for d in raw]
        seq = data.get("seq")
        seq = int(seq) if seq is not None else None
        timeout_s = data.get("timeout-s", data.get("timeout_s"))
        timeout_s = float(timeout_s) if timeout_s is not None else None
        wait_s = float(data.get("wait-s", 30.0))
        return ops, seq, timeout_s, wait_s

    def _adopt_session(self, sid: str
                       ) -> Tuple[Optional[sn.Session],
                                  Optional[Tuple[int, Dict]]]:
        """Fleet resolution of a session that is NOT live locally
        (and not closed — callers check that first). While the
        claiming replica's lease is live the session is PINNED there:
        the caller answers 409 with the pin, and the client retries
        against it (the carried frontier is that replica's device
        state — adopting a live session would fork it). Once the
        lease expires — the holder died — this replica claims the pin
        and re-derives the frontier from the journaled stream, and
        the append proceeds HERE. Returns (session, None) or
        (None, (code, payload))."""
        if self.fleet \
                and self.journal.load_session(sid) is not None:
            holder = self.journal.lease_live(sid)
            if holder is not None and holder != self.replica_id:
                return None, (409, {
                    "error": f"session {sid!r} is pinned to "
                             f"replica {holder!r}",
                    "session": sid, "pinned-to": holder,
                    "cause": "session-pinned"})
            if self.journal.claim(sid, replica=self.replica_id,
                                  ttl_s=self.lease_ttl_s) \
                    and self._replay_one_session(sid):
                sess = self.sessions.get(sid)
                if sess is not None:
                    obs.count("serve.session.adopted")
                    self.registry.ledger_record(
                        sess.tenant, "session-adopted", session=sid,
                        replica=self.replica_id)
                    log.info("session %s adopted by replica %s",
                             sid, self.replica_id)
                    return sess, None
        return None, (404, {"error": f"unknown session {sid!r}"})

    def session_append(self, sid: str, body: bytes,
                       content_type: str) -> Tuple[int, Dict]:
        if not self.accepting:
            return 503, {"error": "shutting down"}
        sess = self.sessions.get(sid)
        if sess is None:
            term = (self.journal.session_lookup_closed(sid)
                    if self.journal is not None else None)
            if term is not None:
                return 409, {"error": f"session {sid!r} is closed",
                             "session": sid, "status": "closed"}
            sess, err = self._adopt_session(sid)
            if sess is None:
                return err
        try:
            ops, seq, timeout_s, wait_s = self._parse_append(
                body, content_type)
        except Exception as e:                          # noqa: BLE001
            return 400, {"error": f"{type(e).__name__}: {e}"}
        with sess.lock:
            # closed/closing re-checked UNDER the lock: an append
            # racing a concurrent close must get its 409, not journal
            # a block into a closing session
            if sess.closed or sess.closing:
                return 409, {"error": f"session {sid!r} is closed",
                             "session": sid, "status": "closed"}
            if seq is not None and seq <= sess.seq:
                # at-least-once on the client side, exactly-once on
                # the frontier: a retried block (response lost to a
                # crash/restart) dedups to the already-applied seq
                obs.count("serve.session.deduped")
                out = sess.status()
                out.update({"deduped": True, "seq": seq})
                return 200, out
            if seq is not None and seq != sess.seq + 1:
                # a seq GAP is a protocol error, never silently
                # renumbered: accepting block k+2 as k+1 would break
                # the dedup contract (a later retry of the true k+1
                # would then double-advance the frontier)
                return 409, {"error": f"seq gap: expected "
                                      f"{sess.seq + 1}, got {seq}",
                             "session": sid, "seq": sess.seq}
            this_seq = sess.seq + 1
            if self.journal is not None:
                # durable BEFORE the verdict: the replay re-derives
                # the frontier from journaled blocks in seq order
                try:
                    self.journal.session_append_entry(sid, this_seq,
                                                      ops)
                except OSError as e:
                    obs.engine_fallback("serve-journal",
                                        type(e).__name__, session=sid)
                    return 500, {"error":
                                 f"journal write failed: {e}"}
            # NO deadline on an append: a journaled block is part of
            # the session's durable stream — expiring it queued would
            # leave a hole in the carried frontier while seq already
            # advanced past it (the client bounds its own wait with
            # wait-s and polls GET /check/<id> for slow dispatches)
            del timeout_s
            req = rq.CheckRequest(
                id=rq.new_request_id(), tenant=sess.tenant,
                model_name=sess.model_name, model=sess.model,
                packed=None, history=ops, n_ops=len(ops),
                opts=dict(sess.opts),
                kind="session-append", session=sess, seq=this_seq)
            try:
                self.registry.add(req)
                self.queue.submit(req)
            except Backpressure as e:
                self.registry.remove(req.id)
                if self.journal is not None:
                    self.journal.discard_session_append(sid, this_seq)
                self.registry.ledger_record(sess.tenant, "rejected",
                                            cause="backpressure",
                                            session=sid)
                return 429, {"error": str(e), "retry-after-s": 1.0}
            sess.seq = this_seq
        # synchronous by default: the append's whole point is a
        # verdict seconds after the ops ran. A slow dispatch returns
        # 202 + the request id; the verdict arrives via GET /check/<id>
        if req.done_event.wait(wait_s) and req.result is not None:
            out = dict(req.result)
            out["id"] = req.id
            out["status"] = req.status
            return 200, out
        return 202, {"id": req.id, "session": sid, "seq": this_seq,
                     "status": req.status}

    def session_close(self, sid: str, body: bytes = b""
                      ) -> Tuple[int, Dict]:
        sess = self.sessions.get(sid)
        if sess is None:
            term = (self.journal.session_lookup_closed(sid)
                    if self.journal is not None else None)
            if term is not None:
                out = {"session": sid, "status": "closed",
                       "recovered-from-journal": True}
                if term.get("result") is not None:
                    out["result"] = term["result"]
                return 200, out
            sess, err = self._adopt_session(sid)
            if sess is None:
                return err
        if sess.closed:
            return 200, {"session": sid, "status": "closed",
                         "result": dict(sess.result or {})}
        try:
            wait_s = float((json.loads(body.decode() or "{}")
                            or {}).get("wait-s", 120.0)) \
                if body else 120.0
        # jtlint: ok fallback — malformed wait-s defaults; the close itself proceeds
        except Exception:                               # noqa: BLE001
            wait_s = 120.0
        with sess.lock:
            if sess.closing:
                return 409, {"error": f"close of {sid!r} already in "
                                      f"flight"}
            sess.closing = True
            req = rq.CheckRequest(
                id=rq.new_request_id(), tenant=sess.tenant,
                model_name=sess.model_name, model=sess.model,
                packed=None, history=(), n_ops=len(sess.ops),
                opts=dict(sess.opts),
                kind="session-close", session=sess,
                seq=sess.seq + 1)
            try:
                self.registry.add(req)
                self.queue.submit(req)
            except Backpressure as e:
                sess.closing = False
                self.registry.remove(req.id)
                return 429, {"error": str(e), "retry-after-s": 1.0}
        if req.done_event.wait(wait_s) and req.result is not None:
            if not sess.closed:
                # the close dispatch crashed (closing was cleared so
                # a retry can succeed): report the TRUTH — the
                # session is still open — not a fabricated "closed"
                return 500, {"session": sid, "status": "open",
                             "id": req.id,
                             "error": "close failed; retry",
                             "result": dict(req.result)}
            out = {"session": sid, "status": "closed",
                   "id": req.id, "result": dict(req.result)}
            return 200, out
        return 202, {"id": req.id, "session": sid,
                     "status": req.status}

    def session_status(self, sid: str) -> Tuple[int, Dict]:
        sess = self.sessions.get(sid)
        if sess is not None:
            return 200, sess.status()
        term = (self.journal.session_lookup_closed(sid)
                if self.journal is not None else None)
        if term is not None:
            out = {"session": sid, "status": "closed",
                   "recovered-from-journal": True}
            if term.get("result") is not None:
                out["result"] = term["result"]
            return 200, out
        if self.fleet and self.journal.load_session(sid) is not None:
            # a status GET answers from the shared journal without
            # moving the pin (only appends/closes adopt): any replica
            # can tell the client where the session lives
            return 200, {"session": sid, "status": "open",
                         "fleet": True,
                         "pinned-to": self.journal.lease_live(sid)}
        return 404, {"error": f"unknown session {sid!r}"}

    # -- request handling (called from HTTP worker threads) -------------
    def _reserve_idem(self, tenant: str, idem: str,
                      req_id: str) -> Optional[str]:
        """Atomically claim (tenant, key) for ``req_id``. Returns the
        ALREADY-known id on a hit (the caller dedups), None when this
        request now owns the key. The reservation happens before any
        journaling or queue admission, so concurrent duplicate POSTs
        cannot both pass a check-then-act window."""
        with self._idem_lock:
            known = self._idem.get((tenant, idem))
            if known is not None:
                return known
            self._idem[(tenant, idem)] = req_id
            self._admitting.add(req_id)
            while len(self._idem) > 4096:
                self._idem.popitem(last=False)
            return None

    def _settle_idem(self, tenant: str, idem: Optional[str],
                     req_id: str, admitted: bool) -> None:
        """Resolve a reservation: keep the mapping on success, retract
        it (index + in-flight mark) when admission failed."""
        if idem is None:
            return
        with self._idem_lock:
            self._admitting.discard(req_id)
            if not admitted and self._idem.get((tenant, idem)) \
                    == req_id:
                self._idem.pop((tenant, idem), None)

    def _dedup_response(self, tenant: str, idem: str,
                        known: str) -> Optional[Tuple[int, Dict]]:
        """Map a duplicate POST onto the original request: live ones
        report their current status, journaled terminal ones their
        recorded one. Scoped by tenant. A reservation whose admission
        is still in flight on another worker thread is WAITED OUT
        (admission is a journal write + queue insert, milliseconds) —
        returning its id early would hand the client a 202 that
        dangles if the winner's admission then fails."""
        deadline = time.monotonic() + 5.0
        while True:
            req = self.registry.get(known)
            if req is not None:
                obs.count("serve.journal.deduped")
                return 202, {"id": known, "status": req.status,
                             "tenant": req.tenant, "deduped": True}
            term = (self.journal.lookup_terminal(known)
                    if self.journal is not None else None)
            if term is not None:
                obs.count("serve.journal.deduped")
                return 202, {"id": known,
                             "status": term.get("status", "done"),
                             "deduped": True}
            if self.fleet \
                    and self.journal.load_entry(known) is not None:
                # pending on a SIBLING replica (journaled, not
                # terminal, not in this registry): dedup to it — the
                # client polls GET /check/<id>, which any replica
                # answers from the shared journal
                obs.count("serve.journal.deduped")
                return 202, {"id": known, "status": "queued",
                             "deduped": True, "fleet": True,
                             "claimed-by":
                                 self.journal.lease_live(known)}
            with self._idem_lock:
                if known not in self._admitting:
                    # not mid-admission and resolvable on no tier:
                    # either the winner's admission failed (its
                    # retraction already popped the index) or the
                    # entry fell out of retention — admit fresh
                    if self._idem.get((tenant, idem)) == known:
                        self._idem.pop((tenant, idem), None)
                    return None
            if time.monotonic() >= deadline:
                # pathological stall of the winner: fail THIS
                # duplicate loudly rather than dangle or double-admit
                return 503, {"error": "idempotent admission of "
                             f"{known!r} still in flight"}
            time.sleep(0.002)

    def submit(self, body: bytes, content_type: str,
               header_tenant: Optional[str]) -> Tuple[int, Dict]:
        import time as _time
        if not self.accepting:
            return 503, {"error": "shutting down"}
        try:
            tenant, model_name, ops, options, timeout_s, idem = \
                parse_check_body(body, content_type,
                                 default_tenant=header_tenant
                                 or "anonymous")
            model = resolve_model(model_name)
            packed = h.pack(ops)
            from jepsen_tpu.txn.ops import ListAppend, micro_ops
            if isinstance(model, ListAppend):
                # validate micro-ops AT ADMISSION: a malformed txn
                # must be this client's 400, not a dispatch-time crash
                # that degrades every co-tenant in the coalesced group
                for op in ops:
                    if op.f == "txn":
                        micro_ops(op.value)
        except Exception as e:                          # noqa: BLE001
            return 400, {"error": f"{type(e).__name__}: {e}"}
        req = rq.CheckRequest(
            id=rq.new_request_id(), tenant=tenant,
            model_name=model_name, model=model, packed=packed,
            history=ops, n_ops=int(packed.n), opts=options,
            deadline=(_time.monotonic() + timeout_s
                      if timeout_s else None),
            idem_key=idem)
        if idem is not None:
            known = self._reserve_idem(tenant, idem, req.id)
            if known is None and self.fleet:
                # the local index only knows THIS replica's
                # admissions (plus the boot-time seed): a sibling may
                # already hold the key — rescan the shared journal
                # index before letting this admission through
                sibling = self.journal.idempotency_index().get(
                    (tenant, idem))
                if sibling is not None and sibling != req.id:
                    self._settle_idem(tenant, idem, req.id,
                                      admitted=False)
                    known = sibling
            if known is not None:
                dup = self._dedup_response(tenant, idem, known)
                if dup is not None:
                    return dup
                # the known id was stale on every tier and has been
                # retracted: claim the key for this request
                if self._reserve_idem(tenant, idem, req.id) is not None:
                    # lost the re-claim race to another fresh POST:
                    # let that one win, admit this without a key
                    idem = None
                    req.idem_key = None
        if self.journal is not None:
            # durable BEFORE the 202: a client holding this id holds
            # a claim that survives SIGKILL. Append precedes queue
            # entry so a crash between the two replays the request
            # (at-least-once) instead of losing it.
            try:
                self.journal.append(
                    req_id=req.id, tenant=tenant,
                    model_name=model_name, options=options,
                    timeout_s=timeout_s, idempotency_key=idem,
                    history=ops)
                req.journaled = True
                if self.fleet:
                    # lease the entry to THIS replica before the 202:
                    # a sibling's scan racing the admission must see
                    # a live holder, never adopt-and-double-dispatch
                    # (fresh id — the exclusive-create cannot collide)
                    self.journal.claim(req.id,
                                       replica=self.replica_id,
                                       ttl_s=self.lease_ttl_s)
            except OSError as e:
                obs.engine_fallback("serve-journal",
                                    type(e).__name__, append=True)
                self._settle_idem(tenant, idem, req.id,
                                  admitted=False)
                return 500, {"error": f"journal write failed: {e}"}
        try:
            self.registry.add(req)
            self.queue.submit(req)
        except Backpressure as e:
            # the id was never returned to the client: retract it so
            # rejected requests cannot accumulate in the registry —
            # or resurrect from the journal
            self.registry.remove(req.id)
            if self.journal is not None:
                self.journal.discard(req.id)
            self._settle_idem(tenant, idem, req.id, admitted=False)
            self.registry.ledger_record(tenant, "rejected",
                                        cause="backpressure")
            return 429, {"error": str(e), "retry-after-s": 1.0}
        self._settle_idem(tenant, idem, req.id, admitted=True)
        self.registry.ledger_record(tenant, "admitted", id=req.id,
                                    ops=int(packed.n))
        return 202, {"id": req.id, "status": req.status,
                     "tenant": tenant, "ops": int(packed.n)}

    def lookup(self, req_id: str) -> Tuple[int, Dict]:
        req = self.registry.get(req_id)
        if req is None:
            # a request that completed just before a crash: its
            # registry state died with the process, but the journal's
            # completion marker carries the verdict
            term = (self.journal.lookup_terminal(req_id)
                    if self.journal is not None else None)
            if term is not None:
                out: Dict[str, Any] = {
                    "id": req_id,
                    "status": term.get("status", "done"),
                    "recovered-from-journal": True}
                if term.get("result") is not None:
                    out["result"] = term["result"]
                code = (500 if out["status"] == rq.QUARANTINED
                        else 200)
                return code, out
            if self.fleet \
                    and self.journal.load_entry(req_id) is not None:
                # pending on another replica: answer the poll from
                # the shared journal (status detail lives with the
                # claiming replica; the verdict will land in the
                # shared done marker either way)
                return 200, {"id": req_id, "status": "queued",
                             "fleet": True,
                             "claimed-by":
                                 self.journal.lease_live(req_id)}
            return 404, {"error": f"unknown request {req_id!r}"}
        # a quarantined request is a structured 500: the daemon is
        # healthy, THIS request poisoned its dispatches
        code = 500 if req.status == rq.QUARANTINED else 200
        return code, req.to_json()

    def profile(self, body: bytes) -> Tuple[int, Dict]:
        """Arm on-demand profiling: the next N dispatches run under
        ``jax.profiler.trace``. 409 when already armed or when the
        daemon has no store root to persist the capture into."""
        try:
            data = json.loads(body) if body else {}
            n = int(data.get("dispatches", 1))
            if not 1 <= n <= 1000:
                raise ValueError("dispatches must be in 1..1000")
        except Exception as e:                          # noqa: BLE001
            return 400, {"error": f"{type(e).__name__}: {e}"}
        try:
            d = self.dispatcher.arm_profile(n)
        except RuntimeError as e:
            return 409, {"error": str(e)}
        except Exception as e:                          # noqa: BLE001
            # e.g. an unwritable store root: the capture dir could
            # not be created — an HTTP error, never a dropped socket
            return 500, {"error": f"{type(e).__name__}: {e}"}
        return 202, {"profile-dir": d, "dispatches": n}

    def cancel(self, req_id: str) -> Tuple[int, Dict]:
        req = self.registry.get(req_id)
        if req is None:
            # journaled but not (yet) replayed into the registry — a
            # crash-recovery window: write the cancelled marker so a
            # restart cannot resurrect cancelled work
            if self.journal is not None \
                    and self.journal.cancel_pending(req_id):
                obs.count("serve.cancelled")
                return 200, {"id": req_id, "status": rq.CANCELLED,
                             "cancelled-in-journal": True}
            return 404, {"error": f"unknown request {req_id!r}"}
        queued = self.queue.cancel(req_id)
        if queued is not None:
            obs.count("serve.cancelled")
            obs.count(f"serve.tenant."
                      f"{self.registry.bucket_tenant(req.tenant)}"
                      f".cancelled")
            self.registry.finish(queued, rq.CANCELLED,
                                 {"valid": "unknown",
                                  "cause": "cancelled"})
            self.registry.ledger_record(req.tenant, "cancelled",
                                        id=req_id)
        else:
            # already walking: flag it; the dispatch abort hook and
            # completion path observe the flag
            req.cancel_requested = True
        return 200, req.to_json()

    def stats(self) -> Dict[str, Any]:
        out = self.dispatcher.stats()
        if self.fleet:
            out["fleet"] = {
                "replica": self.replica_id,
                "lease-ttl-s": self.lease_ttl_s,
                "leases": self.journal.stats().get("leases", 0)}
        if self.n_ranks > 1:
            out["dist"] = {"rank": self.rank, "ranks": self.n_ranks}
        return out

    def health(self) -> Dict[str, Any]:
        """Liveness + degradation: ``ok`` means the daemon serves;
        ``degraded`` means it serves from the host path while the
        device-path breaker is open (or probing half-open)."""
        breaker = self.dispatcher.breaker
        out: Dict[str, Any] = {"ok": True,
                               "degraded": breaker.degraded,
                               "breaker": breaker.to_json()}
        if self.journal is not None:
            out["journal"] = {"pending": self.journal.pending_count()}
        if self.fleet:
            out["fleet"] = {"replica": self.replica_id,
                            "lease-ttl-s": self.lease_ttl_s}
        return out


def run_compute_peer(*, rank: int, n_ranks: int) -> None:
    """Pod mode, ranks > 0: no HTTP socket, no lease, no dispatcher —
    the process stays resident to join the multi-host walks rank 0's
    daemon drives. The loop blocks in :func:`distributed.recv_work`;
    each received item is one walk (operands shipped by the driver —
    this rank's phase B joins the gather collective, its verdict is
    discarded, rank 0's fold is the one that serves). Exits on the
    driver's shutdown broadcast. Deliberately NOT a Daemon:
    constructing one here would bind a second HTTP port and claim
    leases rank 0 already owns."""
    from jepsen_tpu.checkers import reach_chunklock as rcl
    from jepsen_tpu.parallel import distributed

    obs.gauge("dist.processes", n_ranks)
    obs.gauge("dist.rank", rank)
    log.info("compute peer up: rank %d of %d", rank, n_ranks)
    print(f'{{"peer": {rank}, "ranks": {n_ranks}}}', flush=True)
    while True:
        item = distributed.recv_work()
        op = str(item.get("op"))
        if op == "shutdown":
            log.info("compute peer rank %d: clean shutdown", rank)
            return
        try:
            if op == "gather-ping":
                # pod warmup: prove this rank answers a DCN collective
                distributed.ChunkShard.detect().gather(
                    np.ascontiguousarray(item["words"]))
            elif op == "chunklock":
                rcl.walk_chunklock(
                    np.ascontiguousarray(item["P"], np.float32),
                    np.ascontiguousarray(item["ret_slot"], np.int8),
                    np.ascontiguousarray(item["slot_ops"]),
                    int(item["M"]), n_chunks=int(item["n_chunks"]),
                    e_pad=int(item["e_pad"]),
                    suffix=int(item["suffix"]),
                    interpret=bool(int(item["interpret"])))
        except Exception:                               # noqa: BLE001
            # a peer-side failure costs rank 0 one gather timeout and
            # a local rescue, never correctness; stay resident
            obs.count("dist.peer_errors")
            log.exception("compute peer rank %d: work item failed",
                          rank)


class _Handler(BaseHTTPRequestHandler):
    daemon_ref: Daemon = None           # type: ignore[assignment]
    protocol_version = "HTTP/1.1"

    def _reply(self, code: int, payload: Dict) -> None:
        body = json.dumps(payload, default=str).encode()
        self._reply_raw(code, body, "application/json")

    def _reply_raw(self, code: int, body: bytes,
                   content_type: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        if code == 429:
            self.send_header("Retry-After", "1")
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self) -> None:                          # noqa: N802
        path = self.path.rstrip("/")
        n = int(self.headers.get("Content-Length") or 0)
        if n > self.daemon_ref.max_body_bytes:
            # refuse BEFORE reading: a body cap enforced after
            # rfile.read would already have paid the memory
            self._reply(413, {"error": f"body {n} bytes exceeds "
                              f"{self.daemon_ref.max_body_bytes}"})
            return
        if path == "/profile":
            body = self.rfile.read(n) if n else b""
            code, payload = self.daemon_ref.profile(body)
            self._reply(code, payload)
            return
        if path == "/session":
            body = self.rfile.read(n) if n else b""
            code, payload = self.daemon_ref.session_open(
                body, self.headers.get("Content-Type", ""),
                self.headers.get("X-Tenant"))
            self._reply(code, payload)
            return
        if path.startswith("/session/"):
            rest = path[len("/session/"):]
            sid, _, action = rest.partition("/")
            body = self.rfile.read(n) if n else b""
            if action == "append":
                code, payload = self.daemon_ref.session_append(
                    sid, body, self.headers.get("Content-Type", ""))
            elif action == "close":
                code, payload = self.daemon_ref.session_close(
                    sid, body)
            else:
                code, payload = 404, {
                    "error": "POST /session/<id>/append or .../close"}
            self._reply(code, payload)
            return
        if path != "/check":
            self._reply(404,
                        {"error": "POST /check, /session or "
                                  "/profile only"})
            return
        body = self.rfile.read(n) if n else b""
        code, payload = self.daemon_ref.submit(
            body, self.headers.get("Content-Type", ""),
            self.headers.get("X-Tenant"))
        self._reply(code, payload)

    def do_GET(self) -> None:                           # noqa: N802
        path = self.path.split("?", 1)[0]
        if path.startswith("/check/"):
            code, payload = self.daemon_ref.lookup(
                path[len("/check/"):].strip("/"))
            self._reply(code, payload)
            return
        if path.startswith("/session/"):
            code, payload = self.daemon_ref.session_status(
                path[len("/session/"):].strip("/"))
            self._reply(code, payload)
            return
        if path.rstrip("/") == "/stats":
            self._reply(200, self.daemon_ref.stats())
            return
        if path.rstrip("/") == "/metrics":
            # Prometheus text exposition of the process-global
            # recorder: counters, numeric gauges, histogram ladders
            from jepsen_tpu import obs
            self._reply_raw(200, obs.prometheus_text().encode(),
                            "text/plain; version=0.0.4; "
                            "charset=utf-8")
            return
        if path.rstrip("/") == "/healthz":
            self._reply(200, self.daemon_ref.health())
            return
        self._reply(404, {"error": f"no route {path!r}"})

    def do_DELETE(self) -> None:                        # noqa: N802
        path = self.path.split("?", 1)[0]
        if path.startswith("/check/"):
            code, payload = self.daemon_ref.cancel(
                path[len("/check/"):].strip("/"))
            self._reply(code, payload)
            return
        self._reply(404, {"error": "DELETE /check/<id> only"})

    def log_message(self, *args) -> None:               # quiet
        pass
