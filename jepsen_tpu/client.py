"""Client protocol — upstream ``jepsen/src/jepsen/client.clj``
(SURVEY.md §2.1, L4): the per-process connection to the system under test.

Lifecycle, as driven by :mod:`jepsen_tpu.core`:

- ``open(test, node)`` → a client bound to one node (upstream ``open!``;
  era-tolerant: clients that don't override it are shared as-is, like the
  pre-``open!`` era where ``setup!`` did the binding).
- ``setup(test)`` once after open (schema creation etc.).
- ``invoke(test, op)`` → completed op (``ok``/``fail``/``info``) for each
  invocation the generator emits. MUST be exception-safe: the runner maps
  exceptions to ``info`` (indeterminate) exactly like the upstream worker.
- ``teardown(test)`` / ``close(test)`` on shutdown.

``invoke`` receives the full invocation :class:`~jepsen_tpu.op.Op` and
returns its completion — typically ``op.with_(type=OK, value=...)``.
"""
from __future__ import annotations

from typing import Any, Mapping, Optional

from jepsen_tpu.op import FAIL, INFO, OK, Op


class Client:
    """Base client (upstream ``jepsen.client/Client`` protocol)."""

    def open(self, test: Mapping, node: Any) -> "Client":
        """Return a client instance bound to ``node``. Default: bind self
        (single shared client, pre-``open!`` era semantics)."""
        return self

    def setup(self, test: Mapping) -> None:
        pass

    def invoke(self, test: Mapping, op: Op) -> Op:
        raise NotImplementedError

    def teardown(self, test: Mapping) -> None:
        pass

    def close(self, test: Mapping) -> None:
        pass


class NoopClient(Client):
    """Acknowledges every op without doing anything (upstream
    ``jepsen.client/noop-client``); the default in ``noop_test``."""

    def invoke(self, test: Mapping, op: Op) -> Op:
        return op.with_(type=OK)


def noop_client() -> NoopClient:
    return NoopClient()


def closable(client: Client) -> bool:
    """Whether the client overrides ``close`` (upstream
    ``jepsen.client/closable?``)."""
    return type(client).close is not Client.close


def ok(op: Op, value: Any = None) -> Op:
    """Complete ``op`` successfully, optionally replacing its value."""
    return op.with_(type=OK, value=value if value is not None else op.value)


def _with_error(op: Op, type_: str, error: Optional[str]) -> Op:
    if error is None:
        return op.with_(type=type_)
    extra = dict(op.extra or {})
    extra["error"] = error
    return op.with_(type=type_, extra=extra)


def fail(op: Op, error: Optional[str] = None) -> Op:
    """The op definitely did not happen."""
    return _with_error(op, FAIL, error)


def info(op: Op, error: Optional[str] = None) -> Op:
    """Indeterminate: the op may or may not have happened (timeouts,
    crashes). Checkers must keep it pending forever."""
    return _with_error(op, INFO, error)
