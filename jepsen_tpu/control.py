"""Remote control — upstream ``jepsen/src/jepsen/control.clj``
(SURVEY.md §2.1, L0): run commands on DB nodes, upload/download files.

The upstream drives JSch (Java SSH) with dynamic vars ``*host* *session*
*sudo* *dir*``. Here the seam is an explicit :class:`Remote` protocol (the
later-upstream design, which grew pluggable docker/dummy remotes) with
three implementations:

- :class:`SSHRemote` — drives the system ``ssh``/``scp`` binaries
  (paramiko is not in the image; OpenSSH with ControlMaster multiplexing
  is faster than JSch anyway).
- :class:`LocalRemote` — runs commands in a local shell, node name ignored
  (the docker/CI story: every "node" is this machine).
- :class:`FakeRemote` — records commands and returns scripted replies; for
  unit tests of nemeses/DB automation without any cluster.

A :class:`Session` binds a Remote to one node plus sudo/dir context, giving
the upstream verbs: ``exec``, ``upload``, ``download``, ``su``, ``cd``.
"""
from __future__ import annotations

import os
import shlex
import subprocess
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple, Union

class RemoteError(RuntimeError):
    """Non-zero exit from a remote command (upstream throws on bad exit)."""

    def __init__(self, cmd: str, exit_code: int, out: str, err: str):
        super().__init__(
            f"remote command failed ({exit_code}): {cmd}\n"
            f"stdout: {out.strip()[:500]}\nstderr: {err.strip()[:500]}")
        self.cmd = cmd
        self.exit_code = exit_code
        self.out = out
        self.err = err


@dataclass
class Result:
    exit_code: int
    out: str
    err: str


class Remote:
    """Transport protocol (upstream later-era ``jepsen.control/Remote``)."""

    def connect(self, node: str, ssh: Mapping) -> None:
        pass

    def disconnect(self, node: str) -> None:
        pass

    def execute(self, node: str, cmd: str, *, timeout: Optional[float] = None
                ) -> Result:
        raise NotImplementedError

    def upload(self, node: str, local: str, remote_path: str) -> None:
        raise NotImplementedError

    def download(self, node: str, remote_path: str, local: str) -> None:
        raise NotImplementedError


class LocalRemote(Remote):
    """Every node is the local machine (CI / single-box testing)."""

    def execute(self, node, cmd, *, timeout=None):
        p = subprocess.run(["/bin/sh", "-c", cmd], capture_output=True,
                           text=True, timeout=timeout)
        return Result(p.returncode, p.stdout, p.stderr)

    def upload(self, node, local, remote_path):
        subprocess.run(["cp", "-r", local, remote_path], check=True)

    def download(self, node, remote_path, local):
        subprocess.run(["cp", "-r", remote_path, local], check=True)


class SSHRemote(Remote):
    """OpenSSH binary transport with per-node ControlMaster multiplexing
    (one real TCP/auth handshake per node, upstream keeps one JSch session
    the same way)."""

    def __init__(self, control_dir: str = "/tmp/jepsen-ssh"):
        os.makedirs(control_dir, exist_ok=True)
        self._control_dir = control_dir
        self._opts: Dict[str, List[str]] = {}

    def _base(self, node: str) -> List[str]:
        return ["ssh"] + self._opts.get(node, []) + [
            "-o", f"ControlPath={self._control_dir}/%r@%h:%p",
            "-o", "ControlMaster=auto", "-o", "ControlPersist=60",
            "-o", "StrictHostKeyChecking=no",
            "-o", "UserKnownHostsFile=/dev/null",
            "-o", "LogLevel=ERROR"]

    def connect(self, node, ssh):
        opts: List[str] = []
        if ssh.get("username"):
            opts += ["-l", str(ssh["username"])]
        if ssh.get("port"):
            opts += ["-p", str(ssh["port"])]
        if ssh.get("private-key-path"):
            opts += ["-i", str(ssh["private-key-path"])]
        self._opts[node] = opts

    def disconnect(self, node):
        subprocess.run(self._base(node) + ["-O", "exit", node],
                       capture_output=True)

    def execute(self, node, cmd, *, timeout=None):
        p = subprocess.run(self._base(node) + [node, cmd],
                           capture_output=True, text=True, timeout=timeout)
        return Result(p.returncode, p.stdout, p.stderr)

    def _scp_target(self, node: str) -> str:
        user = ""
        opts = self._opts.get(node, [])
        if "-l" in opts:
            user = opts[opts.index("-l") + 1] + "@"
        return f"{user}{node}"

    def upload(self, node, local, remote_path):
        p = subprocess.run(
            ["scp", "-r", "-o", "StrictHostKeyChecking=no",
             "-o", "UserKnownHostsFile=/dev/null", "-o", "LogLevel=ERROR",
             "-o", f"ControlPath={self._control_dir}/%r@%h:%p",
             local, f"{self._scp_target(node)}:{remote_path}"],
            capture_output=True, text=True)
        if p.returncode:
            raise RemoteError(f"scp {local}", p.returncode, p.stdout, p.stderr)

    def download(self, node, remote_path, local):
        p = subprocess.run(
            ["scp", "-r", "-o", "StrictHostKeyChecking=no",
             "-o", "UserKnownHostsFile=/dev/null", "-o", "LogLevel=ERROR",
             "-o", f"ControlPath={self._control_dir}/%r@%h:%p",
             f"{self._scp_target(node)}:{remote_path}", local],
            capture_output=True, text=True)
        if p.returncode:
            raise RemoteError(f"scp {remote_path}", p.returncode, p.stdout,
                              p.stderr)


class FakeRemote(Remote):
    """Scripted remote for unit tests: records every command; replies from
    ``responses`` (cmd-substring → stdout), else empty success."""

    def __init__(self, responses: Optional[Dict[str, str]] = None):
        self.commands: List[Tuple[str, str]] = []   # (node, cmd)
        self.uploads: List[Tuple[str, str, str]] = []
        self.downloads: List[Tuple[str, str, str]] = []
        self.responses = responses or {}
        self._lock = threading.Lock()

    def execute(self, node, cmd, *, timeout=None):
        with self._lock:
            self.commands.append((node, cmd))
        for key, out in self.responses.items():
            if key in cmd:
                if isinstance(out, tuple):
                    return Result(out[0], out[1], "")
                return Result(0, out, "")
        return Result(0, "", "")

    def upload(self, node, local, remote_path):
        with self._lock:
            self.uploads.append((node, local, remote_path))

    def download(self, node, remote_path, local):
        with self._lock:
            self.downloads.append((node, remote_path, local))


def lit(s: str) -> "Literal":
    """An unescaped literal for command construction (upstream
    ``control/lit``)."""
    return Literal(s)


@dataclass(frozen=True)
class Literal:
    s: str


def escape(arg: Any) -> str:
    """Shell-escape one argument (upstream ``control/escape``)."""
    if isinstance(arg, Literal):
        return arg.s
    return shlex.quote(str(arg))


@dataclass
class Session:
    """A Remote bound to one node + sudo/dir context — the upstream dynamic
    vars made explicit. Cheap to copy; ``su``/``cd`` return new sessions."""

    remote: Remote
    node: str
    sudo: Optional[str] = None
    dir: Optional[str] = None
    ssh: Mapping = field(default_factory=dict)

    def connect(self) -> "Session":
        self.remote.connect(self.node, self.ssh)
        return self

    def disconnect(self) -> None:
        self.remote.disconnect(self.node)

    def su(self, user: str = "root") -> "Session":
        return Session(self.remote, self.node, sudo=user, dir=self.dir,
                       ssh=self.ssh)

    def cd(self, dir: str) -> "Session":
        return Session(self.remote, self.node, sudo=self.sudo, dir=dir,
                       ssh=self.ssh)

    def wrap(self, cmd: str) -> str:
        if self.dir:
            cmd = f"cd {escape(self.dir)} && {cmd}"
        if self.sudo:
            cmd = f"sudo -S -u {escape(self.sudo)} /bin/sh -c {escape(cmd)}"
        return cmd

    def exec(self, *args: Any, timeout: Optional[float] = None,
             check: bool = True) -> str:
        """Run a command built from escaped args; returns trimmed stdout
        (upstream ``control/exec``)."""
        cmd = " ".join(escape(a) for a in args)
        res = self.remote.execute(self.node, self.wrap(cmd), timeout=timeout)
        if check and res.exit_code != 0:
            raise RemoteError(cmd, res.exit_code, res.out, res.err)
        return res.out.strip()

    def exec_raw(self, cmd: str, timeout: Optional[float] = None) -> Result:
        return self.remote.execute(self.node, self.wrap(cmd), timeout=timeout)

    def upload(self, local: str, remote_path: str) -> None:
        self.remote.upload(self.node, local, remote_path)

    def download(self, remote_path: str, local: str) -> None:
        self.remote.download(self.node, remote_path, local)


def remote_for(test: Mapping) -> Remote:
    """The test map's remote: ``test["remote"]`` if given, else a shared
    SSH remote cached into the test map (so ControlMaster sockets and
    per-node credentials persist across sessions). Upstream defaults to
    SSH; ``--dummy`` style local runs pass ``LocalRemote``."""
    r = test.get("remote")
    if r is not None:
        return r
    r = SSHRemote()
    try:
        test["remote"] = r                              # type: ignore[index]
    except TypeError:
        pass                                    # immutable test map: one-shot
    return r


def session(test: Mapping, node: str) -> Session:
    """A connected session for ``node`` — registers the test's ssh
    credentials (username/port/key) with the remote. Note: password auth
    is not supported (no sshpass in the image); use key-based auth."""
    return Session(remote_for(test), node,
                   ssh=test.get("ssh") or {}).connect()


def on_nodes(test: Mapping, fn, nodes: Optional[Sequence[str]] = None
             ) -> Dict[str, Any]:
    """Run ``fn(session, node)`` on every node in parallel threads
    (upstream ``control/on-many`` / ``core/on-nodes``)."""
    nodes = list(nodes if nodes is not None else test.get("nodes", []))
    out: Dict[str, Any] = {}
    errs: Dict[str, Exception] = {}

    def run(node: str) -> None:
        try:
            out[node] = fn(session(test, node), node)
        except Exception as e:                          # noqa: BLE001
            errs[node] = e

    threads = [threading.Thread(target=run, args=(n,), daemon=True)
               for n in nodes]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errs:
        node, e = next(iter(errs.items()))
        raise RuntimeError(f"on_nodes failed on {node}: {e}") from e
    return out
