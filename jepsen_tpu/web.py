"""Results browser — upstream ``jepsen/src/jepsen/web.clj``
(SURVEY.md §2.1, L9): a tiny HTTP server over the store directory listing
runs and serving their artifacts. stdlib ``http.server``; no http-kit.
"""
from __future__ import annotations

import html
import json
import os
import urllib.parse
from http.server import SimpleHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from jepsen_tpu import store


# the artifacts the checker/report pipeline writes into a run dir,
# in display order (upstream web.clj links the same set: results,
# history, timeline, perf charts, the linearizability diagram, logs)
_ARTIFACTS = ("results.json", "history.txt", "timeline.html",
              "latency-raw.png", "rate.png", "linear.svg",
              "jepsen.log")


def _badge(valid: str) -> str:
    """Upstream-style verdict badge: green valid, red invalid, amber
    unknown/indeterminate."""
    color, label = {
        "True": ("#2e7d32", "valid"),
        "False": ("#c62828", "INVALID"),
    }.get(valid, ("#b07d2b", valid or "?"))
    return (f"<span class='badge' style='background:{color}'>"
            f"{html.escape(label)}</span>")


def _run_row(root: str, name: str, run: str) -> str:
    valid = ""
    res_path = os.path.join(run, "results.json")
    if os.path.exists(res_path):
        try:
            with open(res_path) as f:
                valid = str(json.load(f).get("valid"))
        except Exception:                               # noqa: BLE001
            valid = "?"
    rel = urllib.parse.quote(os.path.relpath(run, root))
    links = " ".join(
        f"<a href='/files/{rel}/{urllib.parse.quote(a)}'>"
        f"{html.escape(a)}</a>"
        for a in _ARTIFACTS
        if os.path.exists(os.path.join(run, a)))
    return (f"<tr><td><a href='/files/{rel}/'>{html.escape(name)}</a>"
            f"</td><td>{html.escape(os.path.basename(run))}</td>"
            f"<td>{_badge(valid)}</td>"
            f"<td class='artifacts'>{links}</td></tr>")


def _index_html(root: str) -> str:
    rows = [_run_row(root, name, run)
            for name, runs in store.tests(root).items()
            for run in reversed(runs)]
    return ("<!doctype html><title>jepsen-tpu results</title>"
            "<style>body{font-family:sans-serif;margin:2em}"
            "table{border-collapse:collapse}td,th{padding:4px 12px;"
            "border-bottom:1px solid #eee;text-align:left}"
            ".badge{color:#fff;border-radius:3px;padding:1px 7px;"
            "font-size:85%}"
            ".artifacts a{margin-right:.6em;font-size:90%}</style>"
            "<h1>jepsen-tpu results</h1><table>"
            "<tr><th>test</th><th>run</th><th>valid?</th>"
            "<th>artifacts</th></tr>"
            + "".join(rows) + "</table>")


class _Handler(SimpleHTTPRequestHandler):
    store_root = "store"

    def do_GET(self):                                   # noqa: N802
        if self.path in ("/", "/index.html"):
            body = _index_html(self.store_root).encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/html; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if self.path.startswith("/files/"):
            rel = urllib.parse.unquote(self.path[len("/files/"):])
            self.path = "/" + rel
            return SimpleHTTPRequestHandler.do_GET(self)
        self.send_error(404)

    def translate_path(self, path):
        path = urllib.parse.urlparse(path).path
        safe = os.path.normpath(urllib.parse.unquote(path)).lstrip("/")
        full = os.path.join(os.path.abspath(self.store_root), safe)
        if not full.startswith(os.path.abspath(self.store_root)):
            return os.path.abspath(self.store_root)
        return full

    def log_message(self, *args):                       # quiet
        pass


def serve(root: str = "store", port: int = 8080,
          block: bool = True) -> Optional[ThreadingHTTPServer]:
    """Serve the store (upstream ``jepsen.web/serve!`` / CLI ``serve``)."""
    handler = type("Handler", (_Handler,), {"store_root": root})
    httpd = ThreadingHTTPServer(("0.0.0.0", port), handler)
    print(f"jepsen-tpu web: http://localhost:{port}/ (store root {root})")
    if block:
        try:
            httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        return None
    import threading
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd
