"""Results browser — upstream ``jepsen/src/jepsen/web.clj``
(SURVEY.md §2.1, L9): a tiny HTTP server over the store directory listing
runs and serving their artifacts. stdlib ``http.server``; no http-kit.
"""
from __future__ import annotations

import html
import json
import os
import urllib.parse
from http.server import SimpleHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from jepsen_tpu import store


def _index_html(root: str) -> str:
    rows = []
    for name, runs in store.tests(root).items():
        for run in reversed(runs):
            valid = ""
            res_path = os.path.join(run, "results.json")
            if os.path.exists(res_path):
                try:
                    with open(res_path) as f:
                        valid = str(json.load(f).get("valid"))
                except Exception:                       # noqa: BLE001
                    valid = "?"
            color = {"True": "#6db66d", "False": "#d66"}.get(valid, "#d6a76d")
            rel = urllib.parse.quote(os.path.relpath(run, root))
            rows.append(
                f"<tr><td><a href='/files/{rel}/'>{html.escape(name)}</a>"
                f"</td><td>{html.escape(os.path.basename(run))}</td>"
                f"<td style='color:{color}'>{valid}</td></tr>")
    return ("<!doctype html><title>jepsen-tpu results</title>"
            "<style>body{font-family:sans-serif;margin:2em}"
            "table{border-collapse:collapse}td,th{padding:4px 12px;"
            "border-bottom:1px solid #eee;text-align:left}</style>"
            "<h1>jepsen-tpu results</h1><table>"
            "<tr><th>test</th><th>run</th><th>valid?</th></tr>"
            + "".join(rows) + "</table>")


class _Handler(SimpleHTTPRequestHandler):
    store_root = "store"

    def do_GET(self):                                   # noqa: N802
        if self.path in ("/", "/index.html"):
            body = _index_html(self.store_root).encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/html; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if self.path.startswith("/files/"):
            rel = urllib.parse.unquote(self.path[len("/files/"):])
            self.path = "/" + rel
            return SimpleHTTPRequestHandler.do_GET(self)
        self.send_error(404)

    def translate_path(self, path):
        path = urllib.parse.urlparse(path).path
        safe = os.path.normpath(urllib.parse.unquote(path)).lstrip("/")
        full = os.path.join(os.path.abspath(self.store_root), safe)
        if not full.startswith(os.path.abspath(self.store_root)):
            return os.path.abspath(self.store_root)
        return full

    def log_message(self, *args):                       # quiet
        pass


def serve(root: str = "store", port: int = 8080,
          block: bool = True) -> Optional[ThreadingHTTPServer]:
    """Serve the store (upstream ``jepsen.web/serve!`` / CLI ``serve``)."""
    handler = type("Handler", (_Handler,), {"store_root": root})
    httpd = ThreadingHTTPServer(("0.0.0.0", port), handler)
    print(f"jepsen-tpu web: http://localhost:{port}/ (store root {root})")
    if block:
        try:
            httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        return None
    import threading
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd
