"""Results browser — upstream ``jepsen/src/jepsen/web.clj``
(SURVEY.md §2.1, L9): a tiny HTTP server over the store directory listing
runs and serving their artifacts. stdlib ``http.server``; no http-kit.
"""
from __future__ import annotations

import html
import json
import os
import urllib.parse
from http.server import SimpleHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from jepsen_tpu import store


# the artifacts the checker/report pipeline writes into a run dir,
# in display order (upstream web.clj links the same set: results,
# history, timeline, perf charts, the linearizability diagram, logs)
_ARTIFACTS = ("results.json", "history.txt", "timeline.html",
              "latency-raw.png", "rate.png", "linear.svg",
              "jepsen.log")


def _badge(valid: str) -> str:
    """Upstream-style verdict badge: green valid, red invalid, amber
    for the checker's own ``"unknown"`` verdict, grey for anything
    else (a malformed results.json, an error string) — an
    indeterminate-but-well-formed verdict must not look the same as
    garbage."""
    color, label = {
        "True": ("#2e7d32", "valid"),
        "False": ("#c62828", "INVALID"),
        "unknown": ("#b07d2b", "unknown"),
    }.get(valid, ("#616161", valid or "?"))
    return (f"<span class='badge' style='background:{color}'>"
            f"{html.escape(label)}</span>")


# Elle anomaly classes the transactional checker reports, by severity
# color: write cycles darkest, the committed-read classes amber-red,
# inference-direct classes purple. Anything NOT in this table — an
# unknown anomaly string from a future checker or a malformed
# results.json — takes the existing grey badge path via _badge.
_ANOMALY_COLORS = {
    "G0": "#7b1fa2", "G1c": "#c2185b", "G-single": "#d84315",
    "G2": "#c62828", "G1a": "#ad1457",
    "incompatible-order": "#6a1b9a", "duplicate-append": "#6a1b9a",
}


def _anomaly_badge(name: str) -> str:
    color = _ANOMALY_COLORS.get(name)
    if color is None:
        return _badge(name)                 # unknown string: grey path
    return (f"<span class='badge' style='background:{color}'>"
            f"{html.escape(name)}</span>")


def _witness_html(res: dict) -> str:
    """The txn verdict's witness cycle as an ordered op list (one
    <li> per transaction, the edge type that leads OUT of it
    annotated), collapsed behind <details> so invalid rows stay
    scannable."""
    w = res.get("witness")
    if not isinstance(w, dict) or not w.get("cycle"):
        return ""
    items = []
    edges = w.get("edges") or []
    for i, t in enumerate(w["cycle"]):
        et = edges[i] if i < len(edges) else "?"
        items.append(
            f"<li>txn {html.escape(str(t.get('txn')))} "
            f"(p{html.escape(str(t.get('process')))}"
            f"@{html.escape(str(t.get('index')))}): "
            f"<code>{html.escape(json.dumps(t.get('value')))}</code> "
            f"&rarr;<b>{html.escape(str(et))}</b></li>")
    return (f"<details><summary>witness cycle "
            f"({len(items)} txns)</summary><ol>"
            + "".join(items) + "</ol></details>")


def _txn_cell(res: dict) -> str:
    """Anomaly-class badges + witness for a transactional verdict;
    empty for non-txn results."""
    anomalies = res.get("anomalies")
    if not isinstance(anomalies, list) or not anomalies:
        return ""
    badges = " ".join(_anomaly_badge(str(a)) for a in anomalies)
    return f" {badges}{_witness_html(res)}"


# mirrors txn.lattice.LEVELS (weak -> strong); kept local so the web
# view never imports the checker stack just to render a report
_LATTICE_LEVELS = ("read-committed", "causal", "pl-2", "si",
                   "serializable")


def _lattice_cell(res: dict) -> str:
    """Per-level lattice verdict badges for a consistency-checked txn
    result: one badge per reported level in lattice order, green
    where the level holds, red where violated, and the WEAKEST
    violated level (the first guarantee the history breaks walking up
    the lattice) outlined so it reads at a glance."""
    holds = res.get("holds")
    if not isinstance(holds, dict) or not holds:
        return ""
    wv = res.get("weakest-violated")
    out = []
    for lvl in _LATTICE_LEVELS:
        if lvl not in holds:
            continue
        ok = bool(holds[lvl])
        color = "#2e7d32" if ok else "#c62828"
        mark = "&#10003;" if ok else "&#10007;"
        extra = "outline:2px solid #ffab00;" if lvl == wv else ""
        out.append(
            f"<span class='badge' "
            f"style='background:{color};{extra}'>"
            f"{html.escape(lvl)} {mark}</span>")
    return (" " + " ".join(out)) if out else ""


def _run_row(root: str, name: str, run: str) -> str:
    valid = ""
    res: dict = {}
    res_path = os.path.join(run, "results.json")
    if os.path.exists(res_path):
        try:
            with open(res_path) as f:
                res = json.load(f)
            valid = str(res.get("valid"))
        except Exception:                               # noqa: BLE001
            valid = "?"
            res = {}
    rel = urllib.parse.quote(os.path.relpath(run, root))
    links = " ".join(
        f"<a href='/files/{rel}/{urllib.parse.quote(a)}'>"
        f"{html.escape(a)}</a>"
        for a in _ARTIFACTS
        if os.path.exists(os.path.join(run, a)))
    # txn verdicts may live at the top level (cli check / serve runs)
    # or composed under results.txn (suite runs)
    txn_res = res if ("anomalies" in res or "holds" in res) else \
        (res.get("results", {}) or {}).get("txn", {})
    if not isinstance(txn_res, dict):
        txn_res = {}
    txn_cell = _lattice_cell(txn_res) + _txn_cell(txn_res)
    return (f"<tr><td><a href='/files/{rel}/'>{html.escape(name)}</a>"
            f"</td><td>{html.escape(os.path.basename(run))}</td>"
            f"<td>{_badge(valid)}{txn_cell}</td>"
            f"<td class='artifacts'>{links}</td></tr>")


def _live_row(root: str) -> str:
    """When a check-serve daemon persists into this store (its stats
    snapshot exists), surface it: a 'live' row on top of the index
    linking the daemon's stats page and its persisted runs (the
    ``serve-<model>`` test groups below are those runs)."""
    stats_path = os.path.join(root, "serve", "stats.json")
    if not os.path.exists(stats_path):
        return ""
    n_done = ""
    try:
        with open(stats_path) as f:
            st = json.load(f)
        n = st.get("counters", {}).get("serve.completed")
        if n is not None:
            n_done = f" ({int(n)} checks served)"
    except Exception:                                   # noqa: BLE001
        pass
    return (f"<tr><td><a href='/engine'>live</a></td>"
            f"<td>check-serve daemon{html.escape(n_done)}</td>"
            f"<td>{_badge('live')}</td>"
            f"<td class='artifacts'><a href='/engine'>engine stats"
            f"</a></td></tr>")


_STYLE = ("<style>body{font-family:sans-serif;margin:2em}"
          "table{border-collapse:collapse}td,th{padding:4px 12px;"
          "border-bottom:1px solid #eee;text-align:left}"
          ".badge{color:#fff;border-radius:3px;padding:1px 7px;"
          "font-size:85%}"
          ".artifacts a{margin-right:.6em;font-size:90%}"
          ".spark{display:inline-block;vertical-align:middle;"
          "margin-right:2em}"
          ".spark .lbl{font-size:80%;color:#666}"
          "pre{background:#f6f6f6;padding:1em;overflow:auto}</style>")


def _sparkline(values, width: int = 220, height: int = 36) -> str:
    """Inline-SVG sparkline over a list of numbers (None gaps are
    skipped). No javascript — the /engine page is a meta-refresh
    dashboard, so each render is a fresh polyline."""
    pts = [(i, float(v)) for i, v in enumerate(values)
           if isinstance(v, (int, float))]
    if len(pts) < 2:
        return "<span style='color:#999'>&mdash;</span>"
    lo = min(v for _, v in pts)
    hi = max(v for _, v in pts)
    span_x = max(1, pts[-1][0] - pts[0][0])
    span_y = (hi - lo) or 1.0
    coords = " ".join(
        f"{(i - pts[0][0]) / span_x * width:.1f},"
        f"{height - 3 - (v - lo) / span_y * (height - 6):.1f}"
        for i, v in pts)
    return (f"<svg width='{width}' height='{height}'>"
            f"<polyline points='{coords}' fill='none' "
            f"stroke='#3b6ea5' stroke-width='1.5'/></svg>")


def _spark_row(points, key: str, label: str, fmt: str = "{:g}") -> str:
    vals = [p.get(key) for p in points]
    last = next((v for v in reversed(vals)
                 if isinstance(v, (int, float))), None)
    last_s = fmt.format(last) if last is not None else "?"
    return (f"<span class='spark'><span class='lbl'>{label} "
            f"(now {html.escape(last_s)})</span><br>"
            f"{_sparkline(vals)}</span>")


def _index_html(root: str) -> str:
    rows = [_run_row(root, name, run)
            for name, runs in store.tests(root).items()
            for run in reversed(runs)]
    return ("<!doctype html><title>jepsen-tpu results</title>"
            + _STYLE +
            "<h1>jepsen-tpu results</h1><table>"
            "<tr><th>test</th><th>run</th><th>valid?</th>"
            "<th>artifacts</th></tr>"
            + _live_row(root) + "".join(rows) + "</table>")


def _engine_html(root: str) -> str:
    """The ``/engine`` page: the check-serve daemon's latest stats
    snapshot (``<root>/serve/stats.json``, rewritten by the daemon
    after every dispatch) — a live auto-refreshing dashboard with
    sparklines over the daemon's rolling time-series ring (req/s,
    p50/p99, queue depth, in-flight), latency-histogram digests,
    per-tenant device-seconds, queue depth, per-tenant serve ledgers,
    per-geometry dispatch counts, and every ``serve.*`` counter."""
    stats_path = os.path.join(root, "serve", "stats.json")
    head = ("<!doctype html><title>jepsen-tpu engine</title>"
            "<meta http-equiv='refresh' content='2'>" + _STYLE
            + "<h1>check-serve daemon</h1>"
              "<p><a href='/'>&larr; results index</a> &middot; "
              "auto-refreshes every 2 s</p>")
    if not os.path.exists(stats_path):
        return (head + "<p>No daemon stats found — start one with "
                       "<code>python -m jepsen_tpu check-serve"
                       "</code> (it writes "
                       "<code>serve/stats.json</code> under its "
                       "store root).</p>")
    try:
        with open(stats_path) as f:
            st = json.load(f)
    except Exception as e:                              # noqa: BLE001
        return head + f"<p>stats unreadable: {html.escape(str(e))}</p>"
    counters = st.get("counters", {})
    # degradation banner: breaker state (amber while not closed —
    # reusing the verdict badges' color path, "unknown" == amber) and
    # quarantined-request count, surfaced ABOVE the tables so a
    # degraded daemon is unmissable on the dashboard
    breaker = st.get("breaker") or {}
    bstate = breaker.get("state", "closed")
    n_quar = int(counters.get("serve.quarantined", 0))

    def _state_span(label: str, color: str) -> str:
        # same badge element/colors as the verdict badges (amber =
        # the "unknown" path, green = valid, red = INVALID)
        return (f"<span class='badge' style='background:{color}'>"
                f"{html.escape(label)}</span>")

    banner = ""
    if st.get("degraded") or bstate != "closed":
        banner += (
            "<p>" + _state_span(f"DEGRADED: breaker {bstate}",
                                "#b07d2b")
            + " device path unhealthy (consecutive failures: "
            f"{breaker.get('consecutive_failures', '?')}) — serving "
            "host-side, verdicts identical but slower</p>")
    elif breaker:
        banner += (f"<p>{_state_span('breaker closed', '#2e7d32')} "
                   f"device path healthy</p>")
    if n_quar:
        banner += (f"<p>{_state_span(f'{n_quar} quarantined', '#c62828')} "
                   f"poison member(s) isolated by the bisect retry; "
                   f"each answered a structured 500</p>")
    jstats = st.get("journal") or {}
    if jstats:
        banner += (f"<p>journal: {jstats.get('pending', 0)} pending, "
                   f"{jstats.get('terminal', 0)} terminal entries"
                   f"</p>")
    # open streaming sessions: count + oldest age + per-tenant spread
    # (green when live sessions are being served, the grey path when
    # none — same badge element/colors as the verdicts)
    sess = st.get("sessions") or {}
    if sess:
        n_open = int(sess.get("open", 0))
        if n_open:
            tenants_s = ", ".join(
                f"{html.escape(str(t))}: {c}" for t, c in
                sorted((sess.get("per-tenant") or {}).items()))
            banner += (
                "<p>" + _state_span(f"{n_open} open session"
                                    f"{'s' if n_open != 1 else ''}",
                                    "#2e7d32")
                + f" oldest {sess.get('oldest-age-s', '?')} s, "
                  f"{sess.get('appends', 0)} appends / "
                  f"{sess.get('ops', 0)} ops carried"
                + (f" &middot; {tenants_s}" if tenants_s else "")
                + "</p>")
        else:
            banner += (f"<p>{_state_span('no open sessions', '#616161')} "
                       f"{sess.get('closed', 0)} closed retained</p>")
    serve_rows = "".join(
        f"<tr><td>{html.escape(k)}</td><td>{v}</td></tr>"
        for k, v in sorted(counters.items())
        if k.startswith("serve."))
    disp_rows = "".join(
        f"<tr><td>{html.escape(k)}</td><td>{v}</td></tr>"
        for k, v in sorted(st.get("dispatch", {}).items()))
    tenants = st.get("tenants", {})
    tenant_rows = "".join(
        f"<tr><td>{html.escape(t)}</td>"
        f"<td>{html.escape(json.dumps(ev))}</td></tr>"
        for t, ev in sorted(tenants.items()))
    points = st.get("timeseries", [])
    sparks = ""
    if points:
        sparks = ("<h2>live (last %d dispatches)</h2><div>" %
                  len(points)
                  + _spark_row(points, "req_s", "req/s")
                  + _spark_row(points, "p50_s", "p50 s", "{:.3f}")
                  + _spark_row(points, "p99_s", "p99 s", "{:.3f}")
                  + _spark_row(points, "depth", "queue depth")
                  + _spark_row(points, "inflight", "in-flight")
                  + "</div>")
    hists = st.get("histograms", {})
    hist_rows = "".join(
        f"<tr><td>{html.escape(k)}</td><td>{h.get('count', 0)}</td>"
        f"<td>{h.get('p50', '')}</td><td>{h.get('p99', '')}</td>"
        f"<td>{h.get('mean', '')}</td></tr>"
        for k, h in sorted(hists.items()))
    dev_rows = "".join(
        f"<tr><td>{html.escape(t)}</td><td>{v}</td></tr>"
        for t, v in sorted(st.get("device-seconds", {}).items()))
    q = st.get("queue", {})
    return (head
            + banner
            + f"<p>queue depth {q.get('depth', '?')} / "
              f"{q.get('max_depth', '?')}, group width "
              f"{q.get('group', '?')}, per-tenant in-flight cap "
              f"{q.get('max_inflight_per_tenant', '?')}</p>"
            + sparks
            + ("<h2>latency histograms</h2><table>"
               "<tr><th>histogram</th><th>count</th><th>p50 s</th>"
               "<th>p99 s</th><th>mean s</th></tr>"
               + hist_rows + "</table>" if hist_rows else "")
            + ("<h2>device-seconds by tenant</h2><table>"
               "<tr><th>tenant</th><th>attributed s</th></tr>"
               + dev_rows + "</table>" if dev_rows else "")
            + "<h2>serve counters</h2><table>"
              "<tr><th>counter</th><th>value</th></tr>"
            + serve_rows + "</table>"
            + "<h2>dispatch groups (model/width)</h2><table>"
              "<tr><th>geometry</th><th>count</th></tr>"
            + disp_rows + "</table>"
            + "<h2>tenants</h2><table>"
              "<tr><th>tenant</th><th>events</th></tr>"
            + tenant_rows + "</table>"
            + "<h2>raw snapshot</h2><pre>"
            + html.escape(json.dumps(st, indent=2, default=str))
            + "</pre>")


class _Handler(SimpleHTTPRequestHandler):
    store_root = "store"

    def _html(self, body: str) -> None:
        data = body.encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/html; charset=utf-8")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):                                   # noqa: N802
        if self.path in ("/", "/index.html"):
            self._html(_index_html(self.store_root))
            return
        if self.path.rstrip("/") == "/engine":
            self._html(_engine_html(self.store_root))
            return
        if self.path.startswith("/files/"):
            rel = urllib.parse.unquote(self.path[len("/files/"):])
            self.path = "/" + rel
            return SimpleHTTPRequestHandler.do_GET(self)
        self.send_error(404)

    def translate_path(self, path):
        path = urllib.parse.urlparse(path).path
        safe = os.path.normpath(urllib.parse.unquote(path)).lstrip("/")
        full = os.path.join(os.path.abspath(self.store_root), safe)
        if not full.startswith(os.path.abspath(self.store_root)):
            return os.path.abspath(self.store_root)
        return full

    def log_message(self, *args):                       # quiet
        pass


def serve(root: str = "store", port: int = 8080,
          block: bool = True) -> Optional[ThreadingHTTPServer]:
    """Serve the store (upstream ``jepsen.web/serve!`` / CLI ``serve``)."""
    handler = type("Handler", (_Handler,), {"store_root": root})
    httpd = ThreadingHTTPServer(("0.0.0.0", port), handler)
    print(f"jepsen-tpu web: http://localhost:{port}/ (store root {root})")
    if block:
        try:
            httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        return None
    import threading
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd
