"""Result persistence — upstream ``jepsen/src/jepsen/store.clj``
(SURVEY.md §2.1, L9): ``store/<test-name>/<timestamp>/`` directories with
the serialized test, history, results, and logs, plus a ``latest`` symlink.

The upstream serializes with fressian (JVM binary); here the formats are
JSONL for histories (crash-safe, append-only — written live by
:class:`jepsen_tpu.core.History`), JSON for results, and EDN exports for
interop with upstream tooling (``history.edn`` readable by real Jepsen /
knossos and vice versa via :func:`jepsen_tpu.history.load_edn`).
"""
from __future__ import annotations

import json
import logging
import os
from typing import Any, Dict, List, Mapping, Optional

from jepsen_tpu import edn
from jepsen_tpu import history as h
from jepsen_tpu.op import Op

log = logging.getLogger("jepsen.store")

# keys that are live objects, not data — skipped when serializing the test
# map (the upstream stores fressian handlers for these; we store repr)
_LIVE_KEYS = ("client", "db", "os", "net", "nemesis", "generator", "checker",
              "model", "remote", "cluster", "active-processes", "history",
              "results")


# -- persistent warm-start caches ------------------------------------------
#
# Fresh processes re-paid XLA compilation for every kernel geometry and
# re-ran the memo BFS for every alphabet (ISSUE 3): the persistent tier
# lives under the store dir — ``<store-root>/.cache/{xla,memo}`` — so a
# recheck of a stored run starts warm. ``JEPSEN_TPU_NO_PERSIST=1``
# disables everything; ``JEPSEN_TPU_CACHE_DIR`` relocates it.

_PERSIST_STATE: Dict[str, Any] = {}


def persist_root(store_root: Optional[str] = None) -> Optional[str]:
    """Root directory of the persistent caches, or None when
    persistence is disabled (``JEPSEN_TPU_NO_PERSIST=1``). Defaults to
    ``<store-root>/.cache`` — keyed under the store dir so the caches
    travel with the runs they warmed — overridable via
    ``JEPSEN_TPU_CACHE_DIR``. With no explicit ``store_root``, the
    last root wired through :func:`enable_compilation_cache` applies
    (a run configured with a custom ``store-root`` re-keys BOTH tiers
    — XLA and memo — away from the CWD default). Env is consulted per
    call (tests toggle it at runtime)."""
    if os.environ.get("JEPSEN_TPU_NO_PERSIST"):
        return None
    d = os.environ.get("JEPSEN_TPU_CACHE_DIR")
    if d:
        return d
    root = store_root or _PERSIST_STATE.get("root") or "store"
    return os.path.join(root, ".cache")


def enable_compilation_cache(store_root: Optional[str] = None
                             ) -> Optional[str]:
    """Point jax's persistent compilation cache at
    ``<persist-root>/xla`` so rechecks and fresh processes skip XLA
    recompiles of every kernel geometry they have seen before.
    Idempotent and best-effort (a read-only filesystem or an old jax
    must never fail a check); returns the cache dir, or None when
    disabled or unavailable. The compile-time floor is dropped to 0 —
    the walks compile MANY small per-geometry programs whose aggregate
    recompile cost is the warm-start wall this hides."""
    p = persist_root(store_root)
    if p is None:
        return None
    if store_root:
        _PERSIST_STATE["root"] = store_root
    d = os.path.join(p, "xla")
    if _PERSIST_STATE.get("cc_dir") == d:
        return d
    try:
        os.makedirs(d, exist_ok=True)
        import jax
        jax.config.update("jax_compilation_cache_dir", d)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0)
        try:
            jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                              -1)
        except Exception:                               # noqa: BLE001
            pass                        # flag renamed/absent: floor only
        try:
            # bound the tier: fuzz/soak mint fresh geometries forever,
            # and with the floors at 0/-1 every one persists — let jax
            # evict LRU past 1 GiB instead of growing monotonically
            jax.config.update("jax_compilation_cache_max_size",
                              1 << 30)
        except Exception:                               # noqa: BLE001
            pass                        # older jax: unbounded, floor-only
        _install_compile_cache_metrics()
        _PERSIST_STATE["cc_dir"] = d
        return d
    except Exception as e:                              # noqa: BLE001
        log.warning("persistent compilation cache unavailable: %s", e)
        return None


def _install_compile_cache_metrics() -> None:
    """Translate jax's compilation-cache monitoring events into obs
    counters (``compile_cache.hits`` / ``compile_cache.requests``) so
    bench runs and stored ``obs.jsonl`` show whether a warm start
    actually skipped recompiles. Internal jax API — best-effort."""
    if _PERSIST_STATE.get("metrics"):
        return
    try:
        from jax._src import monitoring

        from jepsen_tpu import obs

        def _on_event(event: str, **kw: Any) -> None:
            if event == "/jax/compilation_cache/cache_hits":
                obs.count("compile_cache.hits")
            elif event == "/jax/compilation_cache/compile_requests_use_cache":
                obs.count("compile_cache.requests")

        monitoring.register_event_listener(_on_event)
        _PERSIST_STATE["metrics"] = True
    except Exception:                                   # noqa: BLE001
        pass


def create_run_dir(test: Mapping) -> str:
    root = test.get("store-root", "store")
    # re-key the persistent caches under THIS run's store root (a test
    # configured with store-root=/data/runs must not leave its warm
    # artifacts under ./store/.cache of whatever CWD the process has);
    # engine entries that fired earlier pointed jax at the default —
    # the update below re-points it for every later compile
    enable_compilation_cache(root)
    name = str(test.get("name", "test")).replace("/", "_")
    ts = test.get("start-time") or "run"
    d = os.path.join(root, name, ts)
    n = 0
    base = d
    while os.path.exists(d):
        n += 1
        d = f"{base}-{n}"
    os.makedirs(d, exist_ok=True)
    _symlink_latest(os.path.join(root, name), d)
    _symlink_latest(root, d)
    return d


def _symlink_latest(parent: str, target: str) -> None:
    link = os.path.join(parent, "latest")
    try:
        if os.path.islink(link):
            os.unlink(link)
        os.symlink(os.path.relpath(target, parent), link)
    except OSError:                                     # e.g. on Windows
        pass


def attach_log(run_dir: str) -> logging.Handler:
    """Tee the jepsen logger into ``<dir>/jepsen.log`` (upstream logback
    config writes the same file). Returns the handler; callers must pass
    it to :func:`detach_log` when the run ends or handlers accumulate
    across runs in one process."""
    handler = logging.FileHandler(os.path.join(run_dir, "jepsen.log"))
    handler.setFormatter(logging.Formatter(
        "%(asctime)s %(levelname)s [%(name)s] %(message)s"))
    logging.getLogger("jepsen").addHandler(handler)
    return handler


def detach_log(handler: logging.Handler) -> None:
    logging.getLogger("jepsen").removeHandler(handler)
    handler.close()


def _serializable_test(test: Mapping) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for k, v in test.items():
        if k in _LIVE_KEYS:
            if v is not None:
                out[k] = repr(v)
        else:
            try:
                json.dumps(v)
                out[k] = v
            except (TypeError, ValueError):
                out[k] = repr(v)
    return out


def save(test: Mapping, run_dir: Optional[str] = None) -> str:
    """Persist a completed test (upstream ``store/save!``): ``test.json``,
    ``results.json`` + ``results.edn``, ``history.jsonl`` (if not already
    streamed), ``history.edn``, ``history.txt``."""
    run_dir = run_dir or test.get("dir") or create_run_dir(test)
    history: List[Op] = test.get("history", [])

    with open(os.path.join(run_dir, "test.json"), "w") as f:
        json.dump(_serializable_test(test), f, indent=2, default=str)

    results = test.get("results", {})
    with open(os.path.join(run_dir, "results.json"), "w") as f:
        json.dump(results, f, indent=2, default=str)
    with open(os.path.join(run_dir, "results.edn"), "w") as f:
        f.write(edn.dumps(results) + "\n")

    jsonl = os.path.join(run_dir, "history.jsonl")
    if not os.path.exists(jsonl):
        h.save_jsonl(history, jsonl)
    h.save_edn(history, os.path.join(run_dir, "history.edn"))
    with open(os.path.join(run_dir, "history.txt"), "w") as f:
        for op in history:
            f.write(f"{op.process}\t{op.type}\t{op.f}\t{op.value!r}\n")
    return run_dir


def save_obs(run_dir: str, capture: Optional[Any] = None) -> None:
    """Persist the run's observability record next to its history:
    ``obs.jsonl`` (spans + counters + engine-decision ledger, one JSON
    object per line) and ``trace.json`` (Chrome/Perfetto
    ``trace_event`` — load in ``chrome://tracing`` or ui.perfetto.dev;
    summarize with ``tools/trace_view.py``). ``capture`` is the run's
    :class:`jepsen_tpu.obs.Capture` (None exports the process-global
    recorder). Best-effort: persistence failures must never fail a
    completed run."""
    from jepsen_tpu import obs
    try:
        obs.export_jsonl(os.path.join(run_dir, "obs.jsonl"), capture)
        obs.export_trace(os.path.join(run_dir, "trace.json"), capture)
    except Exception as e:                              # noqa: BLE001
        log.warning("obs persistence failed: %s", e)


def save_check(root: str, name: str, run_id: str, history: List[Op],
               results: Mapping) -> str:
    """Persist one standalone check (the check-serve daemon's unit of
    work) as a browsable run dir — ``<root>/<name>/<ts>-<run_id>/``.
    Delegates to :func:`save` so daemon runs carry the exact artifact
    set CLI runs do (``results.json``/``.edn``, ``history.jsonl``/
    ``.edn``/``.txt``, ``test.json``) and cannot drift from it."""
    import time as _time
    ts = _time.strftime("%Y%m%dT%H%M%S", _time.gmtime())
    d = os.path.join(root, str(name).replace("/", "_"),
                     f"{ts}-{run_id}")
    os.makedirs(d, exist_ok=True)
    return save({"name": name, "history": list(history),
                 "results": results}, run_dir=d)


def serve_journal_dir(root: str) -> str:
    """The check-serve daemon's durable admission journal —
    ``<root>/serve/journal/``, beside its ``stats.json`` and profile
    captures: the WAL of admitted requests that makes the daemon's
    202s survive SIGKILL (see :mod:`jepsen_tpu.serve.journal`)."""
    d = os.path.join(root, "serve", "journal")
    os.makedirs(d, exist_ok=True)
    return d


def serve_profile_dir(root: str) -> str:
    """Create (and return) a fresh capture directory for the
    check-serve daemon's on-demand profiler —
    ``<root>/serve/profile-<ts>/``, beside the daemon's
    ``stats.json`` so captures are browsable artifacts of the store
    like everything else the daemon writes."""
    import time as _time
    ts = _time.strftime("%Y%m%dT%H%M%S", _time.gmtime())
    d = os.path.join(root, "serve", f"profile-{ts}")
    n = 0
    base = d
    while os.path.exists(d):
        n += 1
        d = f"{base}-{n}"
    os.makedirs(d, exist_ok=True)
    return d


def load_history(run_dir: str) -> List[Op]:
    """Load a stored history for offline re-analysis (the upstream
    re-check path; SURVEY.md §5 checkpoint/resume)."""
    jsonl = os.path.join(run_dir, "history.jsonl")
    if os.path.exists(jsonl):
        return h.load_jsonl(jsonl)
    p = os.path.join(run_dir, "history.edn")
    if os.path.exists(p):
        return h.load_edn(p)
    raise FileNotFoundError(f"no history in {run_dir}")


def load_results(run_dir: str) -> Dict[str, Any]:
    with open(os.path.join(run_dir, "results.json")) as f:
        return json.load(f)


def tests(root: str = "store") -> Dict[str, List[str]]:
    """Map test name → sorted run dirs (upstream ``store/tests``)."""
    out: Dict[str, List[str]] = {}
    if not os.path.isdir(root):
        return out
    for name in sorted(os.listdir(root)):
        d = os.path.join(root, name)
        if name == "latest" or not os.path.isdir(d):
            continue
        runs = sorted(
            os.path.join(d, r) for r in os.listdir(d)
            if r != "latest" and os.path.isdir(os.path.join(d, r)))
        if runs:
            out[name] = runs
    return out


def latest(root: str = "store") -> Optional[str]:
    link = os.path.join(root, "latest")
    if os.path.islink(link) or os.path.isdir(link):
        return os.path.realpath(link)
    return None
