"""Result persistence — upstream ``jepsen/src/jepsen/store.clj``
(SURVEY.md §2.1, L9): ``store/<test-name>/<timestamp>/`` directories with
the serialized test, history, results, and logs, plus a ``latest`` symlink.

The upstream serializes with fressian (JVM binary); here the formats are
JSONL for histories (crash-safe, append-only — written live by
:class:`jepsen_tpu.core.History`), JSON for results, and EDN exports for
interop with upstream tooling (``history.edn`` readable by real Jepsen /
knossos and vice versa via :func:`jepsen_tpu.history.load_edn`).
"""
from __future__ import annotations

import json
import logging
import os
from typing import Any, Dict, List, Mapping, Optional

from jepsen_tpu import edn
from jepsen_tpu import history as h
from jepsen_tpu.op import Op

log = logging.getLogger("jepsen.store")

# keys that are live objects, not data — skipped when serializing the test
# map (the upstream stores fressian handlers for these; we store repr)
_LIVE_KEYS = ("client", "db", "os", "net", "nemesis", "generator", "checker",
              "model", "remote", "cluster", "active-processes", "history",
              "results")


def create_run_dir(test: Mapping) -> str:
    root = test.get("store-root", "store")
    name = str(test.get("name", "test")).replace("/", "_")
    ts = test.get("start-time") or "run"
    d = os.path.join(root, name, ts)
    n = 0
    base = d
    while os.path.exists(d):
        n += 1
        d = f"{base}-{n}"
    os.makedirs(d, exist_ok=True)
    _symlink_latest(os.path.join(root, name), d)
    _symlink_latest(root, d)
    return d


def _symlink_latest(parent: str, target: str) -> None:
    link = os.path.join(parent, "latest")
    try:
        if os.path.islink(link):
            os.unlink(link)
        os.symlink(os.path.relpath(target, parent), link)
    except OSError:                                     # e.g. on Windows
        pass


def attach_log(run_dir: str) -> logging.Handler:
    """Tee the jepsen logger into ``<dir>/jepsen.log`` (upstream logback
    config writes the same file). Returns the handler; callers must pass
    it to :func:`detach_log` when the run ends or handlers accumulate
    across runs in one process."""
    handler = logging.FileHandler(os.path.join(run_dir, "jepsen.log"))
    handler.setFormatter(logging.Formatter(
        "%(asctime)s %(levelname)s [%(name)s] %(message)s"))
    logging.getLogger("jepsen").addHandler(handler)
    return handler


def detach_log(handler: logging.Handler) -> None:
    logging.getLogger("jepsen").removeHandler(handler)
    handler.close()


def _serializable_test(test: Mapping) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for k, v in test.items():
        if k in _LIVE_KEYS:
            if v is not None:
                out[k] = repr(v)
        else:
            try:
                json.dumps(v)
                out[k] = v
            except (TypeError, ValueError):
                out[k] = repr(v)
    return out


def save(test: Mapping, run_dir: Optional[str] = None) -> str:
    """Persist a completed test (upstream ``store/save!``): ``test.json``,
    ``results.json`` + ``results.edn``, ``history.jsonl`` (if not already
    streamed), ``history.edn``, ``history.txt``."""
    run_dir = run_dir or test.get("dir") or create_run_dir(test)
    history: List[Op] = test.get("history", [])

    with open(os.path.join(run_dir, "test.json"), "w") as f:
        json.dump(_serializable_test(test), f, indent=2, default=str)

    results = test.get("results", {})
    with open(os.path.join(run_dir, "results.json"), "w") as f:
        json.dump(results, f, indent=2, default=str)
    with open(os.path.join(run_dir, "results.edn"), "w") as f:
        f.write(edn.dumps(results) + "\n")

    jsonl = os.path.join(run_dir, "history.jsonl")
    if not os.path.exists(jsonl):
        h.save_jsonl(history, jsonl)
    h.save_edn(history, os.path.join(run_dir, "history.edn"))
    with open(os.path.join(run_dir, "history.txt"), "w") as f:
        for op in history:
            f.write(f"{op.process}\t{op.type}\t{op.f}\t{op.value!r}\n")
    return run_dir


def save_obs(run_dir: str, capture: Optional[Any] = None) -> None:
    """Persist the run's observability record next to its history:
    ``obs.jsonl`` (spans + counters + engine-decision ledger, one JSON
    object per line) and ``trace.json`` (Chrome/Perfetto
    ``trace_event`` — load in ``chrome://tracing`` or ui.perfetto.dev;
    summarize with ``tools/trace_view.py``). ``capture`` is the run's
    :class:`jepsen_tpu.obs.Capture` (None exports the process-global
    recorder). Best-effort: persistence failures must never fail a
    completed run."""
    from jepsen_tpu import obs
    try:
        obs.export_jsonl(os.path.join(run_dir, "obs.jsonl"), capture)
        obs.export_trace(os.path.join(run_dir, "trace.json"), capture)
    except Exception as e:                              # noqa: BLE001
        log.warning("obs persistence failed: %s", e)


def load_history(run_dir: str) -> List[Op]:
    """Load a stored history for offline re-analysis (the upstream
    re-check path; SURVEY.md §5 checkpoint/resume)."""
    jsonl = os.path.join(run_dir, "history.jsonl")
    if os.path.exists(jsonl):
        return h.load_jsonl(jsonl)
    p = os.path.join(run_dir, "history.edn")
    if os.path.exists(p):
        return h.load_edn(p)
    raise FileNotFoundError(f"no history in {run_dir}")


def load_results(run_dir: str) -> Dict[str, Any]:
    with open(os.path.join(run_dir, "results.json")) as f:
        return json.load(f)


def tests(root: str = "store") -> Dict[str, List[str]]:
    """Map test name → sorted run dirs (upstream ``store/tests``)."""
    out: Dict[str, List[str]] = {}
    if not os.path.isdir(root):
        return out
    for name in sorted(os.listdir(root)):
        d = os.path.join(root, name)
        if name == "latest" or not os.path.isdir(d):
            continue
        runs = sorted(
            os.path.join(d, r) for r in os.listdir(d)
            if r != "latest" and os.path.isdir(os.path.join(d, r)))
        if runs:
            out[name] = runs
    return out


def latest(root: str = "store") -> Optional[str]:
    link = os.path.join(root, "latest")
    if os.path.islink(link) or os.path.isdir(link):
        return os.path.realpath(link)
    return None
